"""Plotting utilities: importance / metric / tree visualization.

Reference: python-package/lightgbm/plotting.py (UNVERIFIED — empty mount,
see SURVEY.md banner): matplotlib horizontal-bar importances, recorded
eval-metric curves, and graphviz tree diagrams. matplotlib/graphviz are
imported lazily so the core package stays import-light.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import numpy as np

from .basic import Booster
from .utils.log import LightGBMError

__all__ = ["plot_importance", "plot_metric", "plot_tree",
           "create_tree_digraph"]


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "You must install matplotlib to plot importance/metric") from e


def _to_booster(booster) -> Booster:
    if isinstance(booster, Booster):
        return booster
    if hasattr(booster, "booster_"):     # sklearn estimator
        return booster.booster_
    raise TypeError("booster must be a Booster or LGBMModel instance")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """Horizontal-bar feature importances (lightgbm.plot_importance)."""
    plt = _check_matplotlib()
    bst = _to_booster(booster)
    if importance_type == "auto":
        importance_type = getattr(booster, "importance_type", "split")
    imp = np.asarray(bst.feature_importance(importance_type))
    names = bst.feature_name()
    pairs = sorted(zip(imp, names), key=lambda t: t[0])
    if ignore_zero:
        pairs = [p for p in pairs if p[0] > 0]
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    if not pairs:
        raise ValueError(
            "Cannot plot trees with zero feature importance")
    values, labels = zip(*pairs)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ypos = np.arange(len(values))
    ax.barh(ypos, values, height=height, align="center", **kwargs)
    for y, v in zip(ypos, values):
        ax.text(v + 1e-12, y,
                f"{v:.{precision}f}" if importance_type == "gain"
                else str(int(v)), va="center")
    ax.set_yticks(ypos)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster: Union[Dict, Any], metric: Optional[str] = None,
                dataset_names=None, ax=None, xlim=None, ylim=None,
                title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    """Plot recorded eval results (lightgbm.plot_metric): accepts the
    dict filled by ``record_evaluation`` or a fitted sklearn estimator."""
    plt = _check_matplotlib()
    if isinstance(booster, dict):
        eval_results = booster
    elif hasattr(booster, "evals_result_"):
        eval_results = booster.evals_result_
    else:
        raise TypeError(
            "booster must be a dict from record_evaluation or a fitted "
            "LGBMModel (train() Boosters don't store eval history)")
    if not eval_results:
        raise ValueError("eval results cannot be empty")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    for name in names:
        metrics = eval_results[name]
        m = metric or next(iter(metrics))
        ax.plot(metrics[m], label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric or next(iter(eval_results[names[0]]))
                  if ylabel == "@metric@" else ylabel)
    ax.grid(grid)
    return ax


def _tree_to_graph(model, tree_index: int, precision: int = 3,
                   **kwargs):
    import graphviz
    t = model.trees[tree_index]
    g = graphviz.Digraph(**kwargs)
    names = model.feature_names

    def leaf_label(i):
        return (f"leaf {i}: {t.leaf_value[i]:.{precision}f}\n"
                f"count: {int(t.leaf_count[i])}")

    for nd in range(t.num_nodes):
        f = int(t.split_feature[nd])
        fname = names[f] if f < len(names) else f"Column_{f}"
        if t.is_categorical is not None and t.is_categorical[nd]:
            lab = f"{fname} in {{...}}"
        else:
            lab = f"{fname} <= {t.threshold_real[nd]:.{precision}g}"
        g.node(f"split{nd}", label=f"{lab}\ngain: "
                                   f"{t.split_gain[nd]:.{precision}g}")
    for nd in range(t.num_nodes):
        for child, tag in ((t.left_child[nd], "yes"),
                           (t.right_child[nd], "no")):
            if child >= 0:
                g.edge(f"split{nd}", f"split{child}", label=tag)
            else:
                leaf = -int(child) - 1
                g.node(f"leaf{leaf}", label=leaf_label(leaf),
                       shape="box")
                g.edge(f"split{nd}", f"leaf{leaf}", label=tag)
    if t.num_nodes == 0:
        g.node("leaf0", label=leaf_label(0), shape="box")
    return g


def create_tree_digraph(booster, tree_index: int = 0,
                        precision: int = 3, **kwargs):
    """graphviz.Digraph of one tree (lightgbm.create_tree_digraph)."""
    bst = _to_booster(booster)
    try:
        import graphviz  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "You must install graphviz to plot tree") from e
    model = (bst._from_model if bst._from_model is not None
             else bst._to_host_model())
    if not 0 <= tree_index < len(model.trees):
        raise IndexError(f"tree_index {tree_index} out of range "
                         f"(0..{len(model.trees) - 1})")
    return _tree_to_graph(model, tree_index, precision=precision,
                          **kwargs)


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              dpi=None, precision: int = 3, **kwargs):
    """Render one tree into a matplotlib axis (lightgbm.plot_tree)."""
    plt = _check_matplotlib()
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                precision=precision, **kwargs)
    from io import BytesIO
    try:
        import matplotlib.image as mpimg
        s = BytesIO(graph.pipe(format="png"))
        img = mpimg.imread(s)
    except Exception as e:
        raise LightGBMError(
            f"Rendering the tree requires the graphviz binary: {e}") from e
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.imshow(img)
    ax.axis("off")
    return ax
