"""Async request queue + adaptive micro-batching primitives.

The coalescing contract (docs/serving.md): concurrent ``submit``
requests for one model ride ONE bucketed dispatch when they arrive
within the latency budget. A dispatch takes the model's **maximal FIFO
prefix** that fits the row cap — strict per-model submit order, a
later request never overtakes an earlier one that did not fit — and a
batch flushes the moment either

- that prefix reaches the row cap (``tpu_serve_max_batch_rows`` — the
  "bucket filled" signal; the engine pads the dispatch up to PR 7's
  power-of-two row buckets, so fuller batches mean higher
  ``serve.batch_fill_ratio`` at the same compiled shapes; a request
  larger than the cap alone is its own full prefix and dispatches
  alone), or
- the OLDEST queued request has waited ``tpu_serve_batch_budget_ms``
  (the latency-budget cutoff — a lone request never waits longer than
  the budget for company that is not coming), or
- a request arrives that does not fit the remaining cap: the prefix is
  frozen (strict FIFO — no later request may join past it), so the
  batch dispatches immediately rather than burning the budget.

The fill signal and the pop agree by construction: both read the same
maintained prefix, so rows queued BEHIND a request that does not fit
can never flush a nearly-empty batch early.

FIFO across models: the dispatcher always serves the model of the
oldest queued request, so one chatty tenant cannot starve another.
This module is pure queueing — no JAX, no engine; the dispatch itself
lives in serve/service.py.

Request lifecycle tracing (docs/observability.md "Request tracing"):
every request is minted a process-unique ``id`` and stamps its
enqueue time; when tracing is on, ``submit`` emits a flow-start event
under that id (one bool check when off) which the dispatch loop's
per-batch span closes — a coalesced rider's submit point visually
connects to the batch that carried it in Perfetto. The pop classifies
WHY the batch flushed (``flush_cause``: "fill" / "freeze" /
"deadline" / "close") onto the popped requests so the dispatch can
attribute latency to queue policy, not just measure it.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..obs import tracing as _tracing

__all__ = ["PredictRequest", "MicroBatchQueue"]

# process-unique request ids: the trace flow id AND the span attr that
# lets one request be followed across submit -> batch -> resolve
_req_ids = itertools.count(1)


class PredictRequest:
    """One queued request: rows + the future its caller blocks on.

    ``kind`` is the predict kind the rider asked for ("predict" |
    "contrib"): requests coalesce only within one (model, kind) lane —
    an explain rider never joins a predict batch (their dispatches run
    different programs with different output shapes and latency
    envelopes), but both lanes share the flush-cause taxonomy, the
    global cross-model FIFO, and the SLO plane."""

    __slots__ = ("model_id", "X", "rows", "future", "t_enqueue",
                 "deadline", "dispatched", "id", "flush_cause", "kind")

    def __init__(self, model_id: str, X, budget_s: float,
                 kind: str = "predict"):
        self.model_id = str(model_id)
        self.kind = str(kind)
        self.X = X
        self.rows = int(np.shape(X)[0])
        self.future: Future = Future()
        self.id = next(_req_ids)
        self.t_enqueue = time.monotonic()
        self.deadline = self.t_enqueue + max(float(budget_s), 0.0)
        self.dispatched = False
        self.flush_cause: Optional[str] = None


class MicroBatchQueue:
    """Thread-safe per-model FIFO of :class:`PredictRequest` with
    prefix-batch pops.

    ``depth()`` is the live ``slo.queue_depth`` feed — requests
    admitted but not yet handed to a dispatch.

    Internal invariant (everything under ``_cond``'s lock):
    ``_prefix[m]`` is the row total of model m's maximal poppable FIFO
    prefix, and ``_open[m]`` says whether that prefix still covers the
    model's WHOLE deque (so a new submit may extend it O(1)). The
    dispatch wake-up's fill check reads ``_prefix`` instead of
    re-scanning the queue.
    """

    def __init__(self, budget_s: float, max_batch_rows: int):
        self.budget_s = max(float(budget_s), 0.0)
        self.max_batch_rows = max(int(max_batch_rows), 1)
        # global submit order (lazily cleaned of dispatched entries —
        # pops remove from the per-model deques only)
        self._order: Deque[PredictRequest] = deque()
        # coalescing lanes keyed (model_id, kind): explain riders never
        # coalesce into a predict batch
        self._by_model: Dict[Tuple[str, str],
                             Deque[PredictRequest]] = {}
        self._prefix: Dict[Tuple[str, str], int] = {}
        self._open: Dict[Tuple[str, str], bool] = {}
        self._depth = 0
        self._cond = threading.Condition()
        self._closed = False

    # ------------------------------------------------------------------
    def submit(self, model_id: str, X,
               kind: str = "predict") -> Future:
        """Enqueue one request; returns the Future its rows resolve
        through. Raises RuntimeError after close() — a shutting-down
        service must refuse loudly, not drop silently. ``kind`` picks
        the coalescing lane (strict FIFO within one (model, kind))."""
        req = PredictRequest(model_id, X, self.budget_s, kind=kind)
        with self._cond:
            if self._closed:
                raise RuntimeError("serve queue is closed")
            if _tracing.tracing_enabled():
                # flow START on the CALLER's thread at enqueue time —
                # AFTER the closed check, so a refused submit leaves
                # no orphan arrow: the dispatch loop's batch span ends
                # the flow (submit -> carrying-batch arrows per rider)
                _tracing.record_flow("serve/req", req.id, "s",
                                     {"model": req.model_id,
                                      "kind": req.kind,
                                      "rows": req.rows})
            lane = (req.model_id, req.kind)
            d = self._by_model.get(lane)
            if d is None:
                d = self._by_model[lane] = deque()
            if not d:
                # a lone head is always its own prefix, oversize or not
                self._prefix[lane] = req.rows
                self._open[lane] = True
            elif self._open[lane]:
                fits = (self._prefix[lane] + req.rows
                        <= self.max_batch_rows)
                if fits:
                    self._prefix[lane] += req.rows
                else:
                    self._open[lane] = False
            d.append(req)
            self._order.append(req)
            self._depth += 1
            self._cond.notify_all()
        return req.future

    def depth(self) -> int:
        """Requests admitted and not yet dispatched (lock-free read of
        a maintained int — scrape threads call this)."""
        return self._depth

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> List[PredictRequest]:
        """Refuse new submits and hand back whatever is still queued so
        the service can fail those futures explicitly (zero SILENT
        drops even at shutdown)."""
        with self._cond:
            self._closed = True
            leftover = [r for r in self._order if not r.dispatched]
            self._order.clear()
            self._by_model.clear()
            self._prefix.clear()
            self._open.clear()
            self._depth = 0
            self._cond.notify_all()
        return leftover

    # ------------------------------------------------------------------
    def _head(self) -> Optional[PredictRequest]:
        """Oldest undispatched request. Caller holds the lock."""
        q = self._order
        while q and q[0].dispatched:
            q.popleft()
        return q[0] if q else None

    def _rescan_prefix(self, lane: Tuple[str, str],
                       d: "Deque[PredictRequest]") -> None:
        """Rebuild ``_prefix``/``_open`` for a lane's remaining deque
        after a pop — O(next batch), it stops at the cap. Caller holds
        the lock."""
        acc = 0
        opened = True
        for r in d:
            if acc >= self.max_batch_rows or (
                    acc and acc + r.rows > self.max_batch_rows):
                opened = False
                break
            acc += r.rows
        self._prefix[lane] = acc
        self._open[lane] = opened

    def next_batch(self, poll_s: float = 0.05
                   ) -> Optional[Tuple[str, List[PredictRequest]]]:
        """Block up to ~``poll_s`` for work, then pop the oldest
        request's model's maximal FIFO prefix per the flush rules
        above. Returns None on an empty poll or after close() — the
        dispatch loop's idle tick.
        """
        with self._cond:
            head = self._head()
            if head is None:
                if self._closed:
                    return None
                self._cond.wait(poll_s)
                head = self._head()
                if head is None:
                    return None
            model_id = head.model_id
            lane = (head.model_id, head.kind)
            # coalescing window: sleep toward the oldest deadline,
            # waking on every submit to re-check the fill level. The
            # exit branch IS the flush cause — stamped on the popped
            # requests so the dispatch span can attribute the flush
            # ("fill" = prefix reached the row cap, "freeze" = a
            # non-fitting request ended the prefix, "deadline" = the
            # oldest request's budget ran out, "close" = shutdown).
            cause = "close"
            while not self._closed:
                if self._prefix.get(lane, 0) >= self.max_batch_rows:
                    cause = "fill"
                    break
                if not self._open.get(lane, True):
                    # a non-fitting request FROZE the prefix — under
                    # strict FIFO nothing can ever join this batch, so
                    # waiting out the budget would be pure added
                    # latency for it AND the request blocked behind it
                    cause = "freeze"
                    break
                now = time.monotonic()
                if now >= head.deadline:
                    cause = "deadline"
                    break
                self._cond.wait(head.deadline - now)
            d = self._by_model.get(lane)
            if not d:
                return None         # close() drained it mid-wait
            batch: List[PredictRequest] = []
            rows = 0
            while d:
                r = d[0]
                if batch and rows + r.rows > self.max_batch_rows:
                    break           # prefix ends HERE: strict FIFO,
                d.popleft()         # later requests never overtake r
                r.dispatched = True
                r.flush_cause = cause
                batch.append(r)
                rows += r.rows
                if rows >= self.max_batch_rows:
                    break
            self._depth -= len(batch)
            if d:
                self._rescan_prefix(lane, d)
            else:
                del self._by_model[lane]
                self._prefix.pop(lane, None)
                self._open.pop(lane, None)
            return (model_id, batch)
