"""PredictService: the async serving front of the engine.

One process, many tenants, one dispatch loop:

- callers ``submit(model_id, X)`` from any thread and get a Future;
- the micro-batch queue (serve/queue.py) coalesces concurrent
  requests per model under the latency budget;
- the dispatch thread checks the model out of the LRU registry
  (serve/registry.py), takes the model's hot-swap lock
  (serving.ModelWatcher.swap_lock) and runs ONE bucketed
  ``Booster.predict`` for the whole batch — steady-state traffic
  compiles zero programs (PR 7's pow2 row buckets), and a mid-batch
  hot-swap or LRU eviction can reorder work but never drop a request:
  every Future resolves with rows or an exception.

Observability contract (docs/serving.md): the queue feeds the REAL
``slo.queue_depth`` gauge through obs/slo.py's registered provider,
the dispatch loop stamps ``heartbeat.serve`` (so ``/readyz`` turns
green after :meth:`warmup` — the PR 13 readiness-by-warmup contract),
and every dispatch records ``serve.dispatches`` /
``serve.coalesced_requests`` / ``serve.batch_fill_ratio``.

Request-lifecycle tracing (docs/observability.md "Request tracing"):
each dispatched batch runs under ONE ``serve/batch`` span whose
children decompose it — per-rider ``serve/queue_wait`` (recorded
retroactively from the request's enqueue stamp), ``serve/coalesce``
(riders / rows / fill / flush cause), ``serve/registry_checkout``
(hit vs re-admission re-stack), ``serve/dispatch`` (the bucketed
predict), and ``serve/postprocess`` (slice + resolve). Riders attach
to their carrying batch as flow events, and the same stage durations
feed the PR 11 sliding windows so ``SloTracker.evaluate()`` derives
``slo.queue_wait_p50|p99_ms`` / ``slo.dispatch_p99_ms`` /
``slo.device_share`` and the ``serve.flush_cause{cause=...}``
counters — the p99 decomposition is live on ``/metrics``, not only
in trace files. All of it is off by default behind the existing obs
gates (one bool check per site when off).
"""
from __future__ import annotations

import threading
import time
import weakref
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from .. import obs
from ..config import Config
from ..obs import slo as _slo
from ..obs import tracing as _tracing
from ..utils import log
from .queue import MicroBatchQueue, PredictRequest
from .registry import ModelRegistry

__all__ = ["PredictService"]

# slo.queue_depth sources: every LIVE service's queue contributes to
# ONE module-level provider, so the gauge survives any construct/close
# interleaving (blue/green in either order) and reads the process's
# total backlog — the quantity a load balancer actually cares about.
# Weak references: a service abandoned without close() must not pin
# its queue (and every undispatched request payload) for the process
# lifetime, nor keep feeding a dead backlog into the gauge
_live_lock = threading.Lock()
_live_queues: "weakref.WeakSet" = weakref.WeakSet()


def _total_queue_depth() -> float:
    with _live_lock:     # vs a blue/green construct/close mid-scrape
        queues = list(_live_queues)
    return float(sum(q.depth() for q in queues))


def _track_queue(q: MicroBatchQueue) -> None:
    with _live_lock:
        _live_queues.add(q)
        _slo.set_queue_depth_provider(_total_queue_depth)


def _untrack_queue(q: MicroBatchQueue) -> None:
    with _live_lock:
        _live_queues.discard(q)
        if not _live_queues:
            _slo.clear_queue_depth_provider(_total_queue_depth)


def _resolve(req: PredictRequest, value=None, exc=None) -> None:
    """Settle one request's future, tolerating a client-side cancel: a
    caller that cancelled (e.g. after a result() timeout) made its own
    choice — settling its batchmates must not blow up on its
    InvalidStateError and poison THEIR correctly computed results."""
    fut = req.future
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
    except Exception:
        if not fut.cancelled() and not fut.done():
            raise


class PredictService:
    """Async micro-batching predict service over a model registry."""

    def __init__(self, params=None,
                 registry: Optional[ModelRegistry] = None,
                 start: bool = True):
        cfg = params if isinstance(params, Config) \
            else Config(dict(params or {}))
        self.config = cfg
        # the service is a serving PROCESS entry point: honor the obs
        # knobs (tpu_metrics_port and friends) the same way train() does
        obs.configure_from_config(cfg)
        self.registry = registry if registry is not None \
            else ModelRegistry(cfg)
        self.queue = MicroBatchQueue(
            budget_s=float(cfg.tpu_serve_batch_budget_ms) / 1000.0,
            max_batch_rows=int(cfg.tpu_serve_max_batch_rows))
        _track_queue(self.queue)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # riders of the batch CURRENTLY mid-dispatch (0 when the loop
        # is between batches). The queue's depth() drops at pop, so
        # depth alone cannot tell "idle" from "wedged inside predict"
        # — the fleet replica's liveness loop (serve/fleet.py) stamps
        # heartbeat.serve only while depth()==0 AND inflight==0, so a
        # wedged dispatch goes /readyz-stale and gets replaced
        self._inflight = 0
        if start:
            self.start()

    # ------------------------------------------------------------------
    def start(self) -> "PredictService":
        if self.queue.closed:
            # close() is terminal for the queue: a restarted thread
            # would spin while every submit raises — refuse loudly
            # instead of returning a zombie service
            raise RuntimeError("serve: service is closed; build a new "
                               "PredictService")
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="lightgbm-tpu-serve-dispatch")
            self._thread.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Stop dispatching; queued-but-undispatched futures fail with
        RuntimeError (explicitly — never a silent drop)."""
        self._stop.set()
        leftover = self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        for req in leftover:
            if not req.future.done():
                _resolve(req, exc=RuntimeError(
                    "serve: service closed before dispatch"))
        _untrack_queue(self.queue)

    def __enter__(self) -> "PredictService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    def add_model(self, model_id: str, booster,
                  watch_dir: Optional[str] = None,
                  watch_interval: float = 2.0) -> "PredictService":
        self.registry.register(model_id, booster, watch_dir=watch_dir,
                               watch_interval=watch_interval)
        return self

    @property
    def inflight(self) -> int:
        """Riders of the batch currently mid-dispatch (0 between
        batches) — with ``queue.depth()``, the replica idle/wedged
        discriminator."""
        return self._inflight

    def submit(self, model_id: str, X,
               kind: str = "predict") -> Future:
        """Enqueue one request; the Future resolves to exactly the rows
        submitted, or raises what the predict raised.

        ``kind="predict"`` resolves to converted model output;
        ``kind="contrib"`` resolves to per-feature SHAP contributions
        (``pred_contrib`` layout: ``[rows, n_feat + 1]`` per class).
        Explain riders ride the same micro-batch queue and flush rules
        but coalesce only with other explain requests for the same
        model — never into a predict batch."""
        if kind not in ("predict", "contrib"):
            raise ValueError(f"serve: unknown predict kind {kind!r} "
                             f"(expected 'predict' or 'contrib')")
        return self.queue.submit(model_id, X, kind=kind)

    def predict(self, model_id: str, X,
                timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(model_id, X).result(timeout=timeout)

    def warmup(self, model_id: str, X,
               kinds=("predict",)) -> None:
        """Compile the steady state for one model: predict one batch at
        every pow2 row bucket up to the batch cap (tiling ``X``'s first
        row), through the registry like real traffic. After this
        returns, ``heartbeat.serve`` is stamped — the /readyz contract
        — and warm dispatches of any COALESCED size compile nothing.
        A single request LARGER than ``tpu_serve_max_batch_rows``
        dispatches alone and pads to a bigger pow2 bucket the warmup
        never visited — it pays a one-time compile per new bucket
        (bounded: log2(chunk/cap) programs); size the batch cap to
        your largest expected request to avoid that.

        ``kinds``: which predict kinds to warm — serve mixed
        predict+explain traffic with ``kinds=("predict", "contrib")``
        so warm SHAP dispatches also compile nothing."""
        X = np.asarray(X, dtype=np.float64)
        row = X[:1]
        if (self._thread is None or not self._thread.is_alive()
                or self.queue.closed):
            # no inline fallback: a predict on the caller's thread
            # would race the dispatch loop on the engine AND stamp
            # heartbeat.serve (the engine's predict instrumentation),
            # turning /readyz green for a service that drains nothing
            raise RuntimeError("serve: warmup needs a running service "
                               "— call start() first")
        # walk every pow2 bucket from the ENGINE's floor up to the
        # batch cap: steady-state dispatches of any coalesced size then
        # reuse a compiled program (CompileWatch pins zero warm
        # compiles across swap + eviction in serve_bench)
        from ..boosting.gbdt import PREDICT_ROW_BUCKET_FLOOR
        cap = self.queue.max_batch_rows
        for kind in kinds:
            bucket = PREDICT_ROW_BUCKET_FLOOR
            while True:
                # through the real dispatch path, one awaited bucket at
                # a time (awaiting keeps warmup batches from coalescing
                # WITH EACH OTHER into a skipped bucket): registry
                # checkout and the engine's stack/SHAP-cache mutations
                # stay on the dispatch thread, so a warmup — or a
                # tenant added mid-traffic — never races a live
                # dispatch on the same engine
                self.submit(model_id, np.repeat(row, bucket, axis=0),
                            kind=kind).result()
                if bucket >= cap:
                    break
                bucket = min(bucket * 2, cap)
        obs.heartbeat("serve")

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            item = self.queue.next_batch(poll_s=0.05)
            if item is None:
                continue
            model_id, batch = item
            self._inflight = len(batch)
            try:
                self._dispatch(model_id, batch)
            except Exception as e:   # belt-and-braces: the loop lives on
                for req in batch:
                    if not req.future.done():
                        _resolve(req, exc=e)
                log.warning(f"serve: dispatch for model "
                            f"{model_id!r} failed ({e})")
            finally:
                self._inflight = 0

    def _dispatch(self, model_id: str,
                  batch: List[PredictRequest],
                  admitted: bool = False) -> None:
        rows = sum(r.rows for r in batch)
        # the queue stamped WHY it flushed onto the popped requests;
        # warmup-era direct calls (tests) may carry none. The batch is
        # kind-homogeneous by the queue's (model, kind) lanes.
        cause = batch[0].flush_cause or "fill"
        kind = getattr(batch[0], "kind", "predict")
        with obs.span("serve/batch", model=model_id, riders=len(batch),
                      rows=rows, cause=cause, kind=kind,
                      req=batch[0].id) as bsp:
            if not admitted and obs.any_enabled():
                self._admission_records(batch)
            X = self._coalesce(batch, rows, cause)
            if X is None and bsp is not None:
                bsp.set(shattered=True)
            if X is not None:
                self._dispatch_batch(model_id, batch, X, rows, cause)
        if X is None:
            # one malformed rider (wrong column count, ragged
            # payload) must not poison its batchmates: dispatch
            # each request alone so only the offender's future
            # fails, with the engine's own error. admitted=True:
            # queue waits / flow ends were already recorded for the
            # shattered batch — re-recording would double-feed the
            # SLO windows and duplicate flow finishes
            for req in batch:
                self._dispatch(model_id, [req], admitted=True)

    def _admission_records(self, batch: List[PredictRequest]) -> None:
        """Per-rider admission instrumentation, under the open
        ``serve/batch`` span: the queue-wait stage (feeds the metrics
        histogram + the SLO sliding window) and, when tracing, a
        RETROACTIVE ``serve/queue_wait`` event spanning enqueue→now on
        the virtual "serve queue" track (its own Perfetto row — waits
        overlap the previous batch's spans on the dispatch thread)
        plus the flow end tying each rider's submit to this batch."""
        now = time.monotonic()
        tracing = _tracing.tracing_enabled()
        qtid = _tracing.track_tid("serve queue") if tracing else 0
        for req in batch:
            wait = max(now - req.t_enqueue, 0.0)
            obs.observe("serve/queue_wait", wait)
            if tracing:
                _tracing.record_event(
                    "serve/queue_wait", req.t_enqueue, wait,
                    {"parent": "serve/batch", "req": req.id,
                     "model": req.model_id, "rows": req.rows},
                    tid=qtid)
                _tracing.record_flow("serve/req", req.id, "f")

    def _coalesce(self, batch: List[PredictRequest], rows: int,
                  cause: str):
        """Concatenate the riders into one payload (None = a malformed
        rider; the caller shatters the batch). ``fill`` is estimated
        against the SERVICE config's bucket ladder — the dispatched
        booster (whose knobs decide the real padding) is not checked
        out yet; ``serve.batch_fill_ratio`` stays the exact number."""
        with obs.span("serve/coalesce", riders=len(batch), rows=rows,
                      cause=cause,
                      fill=round(rows / float(self._bucket_rows(rows)),
                                 4)):
            if len(batch) == 1:
                return batch[0].X
            try:
                return np.concatenate([np.asarray(r.X) for r in batch],
                                      axis=0)
            except Exception:
                return None

    def _dispatch_batch(self, model_id: str,
                        batch: List[PredictRequest], X, rows: int,
                        cause: str) -> None:
        try:
            # admission and predict under ONE continuous hold of the
            # model's registry lock (begin_dispatch) — register() /
            # evict() engine mutations from user threads serialize
            # against this in-flight predict, and an evict cannot
            # slip between admission and the predict that would
            # repopulate the stack it released. Booster.predict
            # itself additionally holds the watcher's swap_lock for
            # the whole model read (basic.py), so a concurrent
            # hot-swap lands before or after the WHOLE batch: every
            # rider sees one model.
            with obs.span("serve/registry_checkout",
                          model=model_id) as ck:
                booster, lock, hit = \
                    self.registry.begin_dispatch(model_id)
                if ck is not None:
                    ck.set(hit=hit)
        except KeyError as e:
            for req in batch:
                _resolve(req, exc=e)
            return
        kind = getattr(batch[0], "kind", "predict")
        try:
            with obs.span("serve/dispatch", rows=rows,
                          riders=len(batch), kind=kind):
                out = (booster.predict(X, pred_contrib=True)
                       if kind == "contrib" else booster.predict(X))
        except Exception as e:
            for req in batch:
                _resolve(req, exc=e)
            self._record(batch, rows, booster, cause)
            return
        finally:
            lock.release()
        with obs.span("serve/postprocess", riders=len(batch)):
            off = 0
            for req in batch:
                part = out[off:off + req.rows]
                # coalesced riders get COPIES: independent callers must
                # not hold aliasing views of one shared batch buffer (an
                # in-place tweak by one would corrupt its batchmates, and
                # a retained slice would pin the whole batch)
                _resolve(req, value=(part.copy() if len(batch) > 1
                                     else part))
                off += req.rows
        self._record(batch, rows, booster, cause)

    def _record(self, batch: List[PredictRequest], rows: int,
                booster=None, cause: str = "fill") -> None:
        obs.inc("serve.dispatches")
        explain = getattr(batch[0], "kind", "predict") == "contrib"
        if explain:
            obs.inc("serve.explain_requests", len(batch))
        if len(batch) > 1:
            obs.inc("serve.coalesced_requests", len(batch))
        obs.set_gauge("serve.batch_fill_ratio",
                      rows / float(self._bucket_rows(rows, booster)))
        if obs.enabled():
            # flush-cause taxonomy + per-rider end-to-end latency: the
            # decomposition the slo.* gauges derive from (one bool
            # gate for the per-request loop). Explain riders feed their
            # own window too, so slo.explain_p99_ms decomposes the
            # mixed workload without muddying the predict e2e signal.
            obs.inc("serve.flush_cause", cause=cause)
            now = time.monotonic()
            for req in batch:
                e2e = max(now - req.t_enqueue, 0.0)
                obs.observe("serve/e2e", e2e)
                if explain:
                    obs.observe("serve/explain", e2e)
        # liveness from the LOOP, not just the predict instrumentation:
        # /readyz must track "the dispatcher is draining work" even
        # with a model whose predicts error
        obs.heartbeat("serve")

    def _bucket_rows(self, rows: int, booster=None) -> int:
        """The pow2 bucket this dispatch padded to (PR 7's serving
        bucketing) — the fill-ratio denominator, from the engine's own
        shared pad policy. The DISPATCHED booster's config decides the
        real padding (a tenant may carry its own chunk/bucket knobs);
        the service config is only the host-model / unregistered
        fallback."""
        from ..boosting.gbdt import predict_pad_rows
        eng = getattr(booster, "_engine", None) if booster is not None \
            else None
        cfg = eng.config if eng is not None else self.config
        return predict_pad_rows(rows, cfg.tpu_predict_chunk_rows,
                                cfg.tpu_predict_buckets)
