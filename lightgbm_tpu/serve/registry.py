"""Multi-tenant model registry: a bounded LRU of device forests.

One serving process holds MANY tenants' boosters; what must be bounded
is not the host-side tree lists (cheap) but the device-resident
stacked forests each model's warm predicts pin in HBM. The registry
keeps every registered Booster forever and runs an LRU over which of
them may be DEVICE-RESIDENT:

- capacity is ``tpu_serve_cache_models`` models AND
  ``tpu_serve_cache_bytes`` bytes (0 = auto against the shared
  utils/hbm.py estimate and HBM limit probe);
- residency identity is the engine's existing
  ``(len(models), _models_version)`` stack key — a hot-swap
  (serving.ModelWatcher) bumps the version, and the registry re-costs
  the entry on its next checkout instead of trusting a stale estimate;
- eviction releases the engine's stacked-forest device cache
  (``_stack_cache``); the Booster stays registered, and the next
  checkout re-admits it — a re-stack, NOT a recompile (stable bucketed
  shapes), and never a dropped request.

Metrics (docs/observability.md): ``serve.cache_hits`` /
``serve.evictions`` counters, ``serve.cache_models`` /
``serve.cache_bytes`` gauges.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional

from .. import obs
from ..config import Config
from ..utils import log
from ..utils.hbm import SERVE_HBM_FRACTION, hbm_bytes_limit
from .shard import auto_shard_mesh, forest_bytes_estimate

__all__ = ["ModelRegistry"]


class _Entry:
    __slots__ = ("model_id", "booster", "resident", "bytes", "key",
                 "lock")

    def __init__(self, model_id: str, booster):
        self.model_id = model_id
        self.booster = booster
        self.resident = False
        self.bytes = 0
        self.key: Optional[tuple] = None
        # serializes ENGINE mutation (release, shard policy) against
        # the dispatch thread's in-flight predict on this booster: the
        # service holds it from admission through each dispatched
        # predict (begin_dispatch), and register/evict from user
        # threads take it before touching the engine. Always acquired
        # AFTER the registry lock, never the other way (one fixed
        # order, no deadlock).
        self.lock = threading.RLock()


class ModelRegistry:
    """Bounded LRU of device-resident stacked forests (module doc)."""

    def __init__(self, params=None, max_models: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        cfg = params if isinstance(params, Config) \
            else Config(dict(params or {}))
        self.config = cfg
        self.max_models = int(max_models if max_models is not None
                              else cfg.tpu_serve_cache_models)
        if max_bytes is None:
            max_bytes = int(cfg.tpu_serve_cache_bytes)
        if max_bytes == 0:
            limit = hbm_bytes_limit()
            max_bytes = (int(limit * SERVE_HBM_FRACTION) if limit
                         else 0)          # 0 = no byte cap (count only)
        self.max_bytes = int(max_bytes)
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._lock = threading.RLock()

    # ------------------------------------------------------------------
    def register(self, model_id: str, booster,
                 watch_dir: Optional[str] = None,
                 watch_interval: float = 2.0) -> None:
        """Add (or replace) one tenant's Booster. ``watch_dir`` wires
        the per-model hot-swap watcher (serving.ModelWatcher); the
        tree-shard policy (``tpu_serve_shard_trees``) is applied here
        so every model the registry serves routed through one gate."""
        model_id = str(model_id)
        entry = _Entry(model_id, booster)
        with self._lock:
            old = self._entries.pop(model_id, None)
            # a re-register can hand back the very booster a dispatch
            # is mid-predict on: the old entry's lock serializes the
            # engine mutations below against that predict (a brand-new
            # booster object has no dispatches yet — its own fresh
            # lock is uncontended)
            guard = old.lock if old is not None else entry.lock
            with guard:
                if old is not None and old.resident:
                    # a deploy refresh, not cache pressure: free the
                    # old device forest without counting an eviction
                    self._release(old, count=False)
                if watch_dir:
                    booster.watch_checkpoints(watch_dir,
                                              interval=watch_interval)
                elif getattr(booster, "_engine", None) is not None:
                    # pin bucketed predict shapes even without a
                    # watcher: LRU re-admission must reuse the same
                    # compiled programs
                    booster._engine._stable_predict_shapes = True
                auto_shard_mesh(booster, self.config)
            if old is not None:
                # dispatches still in flight for this model keep
                # serializing on the lock they already fetched
                entry.lock = old.lock
            # popped + re-inserted: the refreshed model lands at the
            # most-recent end, never the next LRU victim
            self._entries[model_id] = entry

    def model_ids(self):
        with self._lock:
            return list(self._entries)

    def resident_ids(self):
        with self._lock:
            return [e.model_id for e in self._entries.values()
                    if e.resident]

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.bytes for e in self._entries.values()
                       if e.resident)

    # ------------------------------------------------------------------
    def checkout(self, model_id: str):
        """LRU-touch and return the Booster for one dispatch, admitting
        its device forest (evicting colder tenants as needed). Raises
        KeyError for an unregistered model — the service fails those
        futures explicitly.

        A predict on the returned Booster is NOT serialized against
        concurrent register/evict engine mutations — that protection
        belongs to the serving dispatch loop's :meth:`begin_dispatch`,
        which keeps the per-model lock held from admission through the
        predict. Use checkout for single-threaded callers and tests."""
        with self._lock:
            return self._admit(model_id)[0].booster

    def begin_dispatch(self, model_id: str):
        """Checkout for the serving dispatch loop: admit + LRU-touch,
        then return ``(booster, lock, hit)`` with the per-model lock
        ALREADY HELD — the caller releases it after its predict. The
        lock is continuous from admission through the predict, so an
        evict() between the two cannot release a stack the predict is
        about to repopulate (which would leave real HBM residency
        accounted as zero). ``hit`` says whether the checkout found
        the forest device-resident (vs a re-admission re-stack) — the
        dispatch loop's ``serve/registry_checkout`` span records it,
        so an LRU-thrash p99 breach is visible per batch in the
        trace, not only as cumulative eviction counters."""
        with self._lock:
            entry, hit = self._admit(model_id)
            entry.lock.acquire()    # registry -> entry order, held out
            return entry.booster, entry.lock, hit

    def _admit(self, model_id: str):
        """LRU-touch + device-forest admission; returns
        ``(entry, hit)`` where ``hit`` means the stacked forest was
        already device-resident under its current stack key. Caller
        holds the registry lock."""
        entry = self._entries.get(str(model_id))
        if entry is None:
            raise KeyError(f"model {model_id!r} is not registered")
        self._entries.move_to_end(entry.model_id)
        key = self._stack_key(entry.booster)
        hit = bool(entry.resident and key == entry.key)
        if hit:
            obs.inc("serve.cache_hits")
        else:
            # admission (first touch, post-eviction re-admission, or a
            # hot-swap that bumped the stack identity): re-run the
            # shard policy — a swap may have grown the forest past the
            # single-device auto threshold — then re-cost and make
            # room. Engine mutation under the entry lock: another
            # service sharing this registry may be mid-predict on the
            # same booster.
            with entry.lock:
                auto_shard_mesh(entry.booster, self.config)
                entry.bytes = self._estimate(entry.booster)
                # key AFTER the policy: first-time shard enablement
                # bumps the model version, and storing the pre-policy
                # key would re-take this admission path every checkout
                entry.key = self._stack_key(entry.booster)
            entry.resident = True
            self._enforce_caps(keep=entry.model_id)
        self._refresh_gauges()
        return entry, hit

    def evict(self, model_id: str) -> bool:
        """Explicitly release one model's device forest (it stays
        registered). Returns True when it was resident."""
        with self._lock:
            entry = self._entries.get(str(model_id))
            if entry is None or not entry.resident:
                return False
            with entry.lock:    # vs a dispatch mid-predict (who would
                self._release(entry)     # repopulate the stack cache)
            self._refresh_gauges()
            return True

    # ------------------------------------------------------------------
    def _stack_key(self, booster) -> Optional[tuple]:
        """The engine's stacked-forest identity. Caller holds the lock."""
        eng = getattr(booster, "_engine", None)
        if eng is None:
            return None
        return (len(eng.models),
                getattr(eng, "_models_version", 0))

    def _estimate(self, booster) -> int:
        """Device-byte cost of one resident model. Caller holds the
        lock. Host-model boosters (no engine) pin no device stack."""
        eng = getattr(booster, "_engine", None)
        if eng is None:
            return 0
        est = forest_bytes_estimate(eng)
        mesh = getattr(eng, "_predict_mesh", None)
        if mesh is not None:
            # tree-sharded stacks spread over the mesh: per-device
            # residency is what the cap protects
            est = -(-est // max(int(mesh.devices.size), 1))
        return est

    def _enforce_caps(self, keep: str) -> None:
        """Evict LRU residents until count and byte caps hold (never
        the entry being admitted). Caller holds the lock."""
        while True:
            resident = [e for e in self._entries.values() if e.resident]
            over_count = len(resident) > self.max_models
            over_bytes = (self.max_bytes > 0
                          and sum(e.bytes for e in resident)
                          > self.max_bytes)
            if not (over_count or over_bytes):
                return
            victim = next((e for e in self._entries.values()
                           if e.resident and e.model_id != keep), None)
            if victim is None:
                # one model alone over the byte cap: serve it anyway —
                # the cap bounds the FLEET, it must not brick a tenant
                if over_bytes:
                    log.warning(
                        f"serve registry: model {keep!r} alone exceeds "
                        f"the device-cache byte cap "
                        f"({self.max_bytes}); serving it uncapped")
                return
            # the lock serializes vs begin_dispatch predicts (a
            # checkout()-path predict is unserialized by contract —
            # see checkout's docstring)
            with victim.lock:
                self._release(victim)

    def _release(self, entry: "_Entry", count: bool = True) -> None:
        """Drop one entry's device forest. Caller holds the lock."""
        eng = getattr(entry.booster, "_engine", None)
        if eng is not None:
            # the stacked-forest device cache IS the HBM residency;
            # dropping it releases the device buffers once in-flight
            # dispatches finish (tests pin the live-buffer count).
            # The SHAP path-table cache rides the same residency: an
            # evicted tenant must not pin its explain tables either
            eng._stack_cache = None
            eng._shap_cache = None
        entry.resident = False
        entry.bytes = 0
        entry.key = None
        if count:
            obs.inc("serve.evictions")

    def _refresh_gauges(self) -> None:
        """Residency gauges after any admission/eviction. Caller holds
        the lock."""
        resident = [e for e in self._entries.values() if e.resident]
        obs.set_gauge("serve.cache_models", float(len(resident)))
        obs.set_gauge("serve.cache_bytes",
                      float(sum(e.bytes for e in resident)))
