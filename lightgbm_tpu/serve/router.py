"""FleetRouter: the thin in-process front of a serving fleet.

The router owns the zero-drop contract the fleet advertises
(docs/serving.md "Fleet deployment"): every ``submit`` Future
resolves EXACTLY ONCE — with the predicted rows, with the request's
own error (unknown model, malformed payload), or with an explicit
shutdown/exhaustion RuntimeError. Never silently.

How it gets there:

- **Admission**: requests only go to handles the supervisor marked
  ready (``/readyz`` green — the warmup-gated readiness contract). A
  joining or relaunched replica takes zero routed traffic until its
  steady state is compiled; tests/test_fleet.py pins this via the
  router's per-rank dispatch counters.
- **Placement**: least-loaded by (router-side in-flight count +
  the replica's last-scraped ``slo.queue_depth``) — the same backlog
  signal a load balancer would scrape from ``/metrics``, kept warm by
  the supervisor's monitor loop at zero extra scrape traffic.
- **Failover**: the router HOLDS each request until its future
  settles. A connection error / 5xx / timeout marks a REPLICA attempt
  failed (``fleet.router_retries``); the request backs off and
  re-dispatches to a sibling (``fleet.redispatches`` once per request
  that had already reached a replica). Predict is pure, so a replica
  that died AFTER computing but BEFORE replying costs a duplicate
  compute, never a wrong or dropped answer. 404/400 are REQUEST
  errors: the future fails immediately, no retry burned.
- **Bounded budget**: ``retries`` sibling attempts (plus the first)
  and a wall-clock deadline per request; exhaustion resolves the
  future with a RuntimeError naming every attempt. ``close()``
  resolves anything still queued the same way — the no-silent-drop
  guarantee survives shutdown.
"""
from __future__ import annotations

import io
import queue as _queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from .. import obs
from ..utils import log
from .fleet import FleetSupervisor, ReplicaHandle

__all__ = ["FleetRouter"]


class _RequestError(Exception):
    """The REQUEST is bad (unknown model, malformed payload) — every
    replica would refuse it identically; fail fast, burn no retries."""


class _Req:
    __slots__ = ("model_id", "payload", "rows", "future", "deadline",
                 "attempts", "touched")

    def __init__(self, model_id: str, X, deadline: float):
        self.model_id = model_id
        buf = io.BytesIO()
        np.save(buf, np.asarray(X, np.float64), allow_pickle=False)
        self.payload = buf.getvalue()
        self.rows = int(np.asarray(X).shape[0])
        self.future: Future = Future()
        self.deadline = deadline
        self.attempts = 0
        self.touched: List[int] = []    # ranks that saw this request


class FleetRouter:
    """Least-loaded router with retry/redispatch over a
    :class:`~.fleet.FleetSupervisor`'s ready replicas."""

    def __init__(self, supervisor: FleetSupervisor, *,
                 retries: int = 4, backoff_s: float = 0.05,
                 backoff_cap_s: float = 1.0,
                 request_timeout_s: float = 60.0,
                 workers: Optional[int] = None):
        self.sup = supervisor
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.request_timeout_s = float(request_timeout_s)
        # one worker per replica slot plus slack: a worker blocks for
        # its request's whole retry saga, so the pool bounds router
        # concurrency, not correctness
        self.workers = int(workers) if workers \
            else max(2 * supervisor.n_replicas, 4)
        self._q: "_queue.Queue[Optional[_Req]]" = _queue.Queue()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # per-rank routed-dispatch counters — the joining-replica
        # admission invariant is asserted against these (a rank absent
        # here received ZERO routed requests; warmup traffic is the
        # replica's own and never passes the router)
        self.dispatch_counts: Dict[int, int] = {}
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"lgbm-tpu-router-{i}")
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    def submit(self, model_id: str, X) -> Future:
        """Enqueue one request; the Future resolves exactly once."""
        if self._stop.is_set():
            raise RuntimeError("fleet router is closed")
        req = _Req(model_id, X,
                   time.monotonic() + self.request_timeout_s)
        self._q.put(req)
        return req.future

    def predict(self, model_id: str, X,
                timeout: Optional[float] = None) -> np.ndarray:
        return self.submit(model_id, X).result(timeout=timeout)

    def close(self) -> None:
        """Stop the workers; anything still undispatched resolves with
        an explicit shutdown error (never a silent drop)."""
        self._stop.set()
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=5.0)
        while True:
            try:
                req = self._q.get_nowait()
            except _queue.Empty:
                break
            if req is not None and not req.future.done():
                req.future.set_exception(RuntimeError(
                    "fleet: router closed before dispatch"))

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    def _worker(self) -> None:
        while True:
            req = self._q.get()
            if req is None:
                return
            try:
                self._run_one(req)
            except Exception as e:      # belt-and-braces: never drop
                if not req.future.done():
                    req.future.set_exception(e)

    def _pick(self, req: _Req) -> Optional[ReplicaHandle]:
        """Least-loaded ready replica, preferring ranks this request
        has not yet touched (a relaunched generation of a touched rank
        is fair game again — membership may be down to one)."""
        ready = self.sup.ready_handles()
        if not ready:
            return None
        fresh = [h for h in ready if h.rank not in req.touched]
        pool = fresh or ready
        return min(pool, key=lambda h: h.inflight + h.depth)

    def _run_one(self, req: _Req) -> None:
        delay = self.backoff_s
        while True:
            if req.future.done():       # caller cancelled
                return
            h = self._pick(req)
            if h is None:
                # no ready replica RIGHT NOW (mid-relaunch, warming):
                # wait within the deadline — elastic membership means
                # capacity usually returns
                if time.monotonic() >= req.deadline:
                    self._exhaust(req, "no ready replica")
                    return
                time.sleep(0.02)
                continue
            req.attempts += 1
            if req.touched:
                # this request already reached a replica and is now
                # being sent elsewhere — the in-flight work of a dying
                # replica re-dispatching instead of dropping
                obs.inc("fleet.redispatches", force=True)
            req.touched.append(h.rank)
            with self._lock:
                self.dispatch_counts[h.rank] = \
                    self.dispatch_counts.get(h.rank, 0) + 1
            h.inflight += 1
            try:
                out = self._call(h, req)
            except _RequestError as e:
                req.future.set_exception(RuntimeError(str(e)))
                return
            except Exception as e:
                obs.inc("fleet.router_retries", force=True)
                log.warning(f"fleet: attempt {req.attempts} at replica "
                            f"{h.rank} failed ({type(e).__name__}: "
                            f"{e}); retrying a sibling")
                if (req.attempts > self.retries
                        or time.monotonic() >= req.deadline):
                    self._exhaust(req, f"last error: {e}")
                    return
                time.sleep(min(delay, self.backoff_cap_s))
                delay *= 2
                continue
            finally:
                h.inflight -= 1
            if not req.future.done():
                req.future.set_result(out)
            return

    def _exhaust(self, req: _Req, why: str) -> None:
        if not req.future.done():
            req.future.set_exception(RuntimeError(
                f"fleet: request for model {req.model_id!r} "
                f"({req.rows} rows) failed after {req.attempts} "
                f"attempt(s) across replicas {req.touched} — {why}"))

    # ------------------------------------------------------------------
    def _call(self, h: ReplicaHandle, req: _Req) -> np.ndarray:
        url = (f"{h.predict_url}/predict?model="
               f"{urllib.parse.quote(req.model_id)}")
        # per-attempt timeout: a replica that dies mid-reply must not
        # eat the whole request deadline before the sibling retry
        budget = max(min(self.sup.predict_timeout_s,
                         req.deadline - time.monotonic()), 0.1)
        r = urllib.request.Request(
            url, data=req.payload,
            headers={"Content-Type": "application/octet-stream"},
            method="POST")
        try:
            with urllib.request.urlopen(r, timeout=budget) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")
            except Exception:
                pass
            if e.code in (400, 404):
                raise _RequestError(
                    f"replica {h.rank} refused request ({e.code}): "
                    f"{detail}") from None
            raise RuntimeError(f"replica {h.rank} HTTP {e.code}: "
                               f"{detail}") from None
        return np.load(io.BytesIO(body), allow_pickle=False)
