"""Tree-sharded predict: placement + policy for forests > one device.

A stacked forest is ``[T, ...]`` device arrays (gbdt.py
``_stack_model_list``); at a few thousand deep trees those tables are
the HBM item that stops fitting long before the request rows do. This
module splits the TREE axis over the local mesh with ``NamedSharding``
(the pjit/NamedSharding idiom of SNIPPETS.md [1][2]) so each device
holds 1/D of the forest and traverses its block against replicated
rows; ``ops/predict.py::forest_predict_sharded`` gathers the per-tree
leaf values back replicated and replays the exact global sequential
class accumulation — outputs are BIT-IDENTICAL to the single-device
path (tests/test_shard_predict.py pins this on the fake-device mesh).

Policy rides the capability table (``capabilities.SHARDED_PREDICT``):
DART's in-place leaf rescales and the host-model predict paths
(streaming engine, ``linear_tree``) DEMOTE to the unsharded path —
they serve fine, just unsplit. ``tpu_serve_shard_trees`` is the knob:
``auto`` engages when one model's stacked estimate
(utils/hbm.py ``stacked_forest_bytes``) exceeds
``SERVE_HBM_FRACTION`` of a device, ``true`` forces it on any >= 2
device host, ``false`` never.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import capabilities
from ..utils import log
from ..utils.hbm import (SERVE_HBM_FRACTION, hbm_bytes_limit,
                         stacked_forest_bytes)

__all__ = ["TREE_AXIS", "tree_mesh", "place_tree_sharded",
           "place_tree_axis", "place_shap_sharded",
           "replicate_on", "engine_kind", "forest_bytes_estimate",
           "enable_tree_sharding", "auto_shard_mesh"]

TREE_AXIS = "trees"


def tree_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the tree axis (trees sharded, rows replicated)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (TREE_AXIS,))


def replicate_on(mesh: Mesh, arr):
    """Commit ``arr`` replicated on every mesh device."""
    return jax.device_put(arr, NamedSharding(mesh, P()))


def place_tree_sharded(stacked: Dict, class_idx, mesh: Mesh
                       ) -> Tuple[Dict, object]:
    """Commit a stacked forest with its leading ``[T]`` axis split over
    ``mesh`` (every per-tree table shards; the class index stays
    replicated — the accumulation scan consumes it on gathered
    values). A tree count the mesh does not divide places replicated
    instead — the caller's pad path (``_stack_for_predict``) prevents
    that in serving, but training-side stacks (score rebuilds) must
    never crash here."""
    T = int(stacked["split_feature"].shape[0])
    D = int(mesh.devices.size)
    if D <= 1 or T % D != 0:
        repl = NamedSharding(mesh, P())
        return ({k: jax.device_put(v, repl) for k, v in stacked.items()},
                jax.device_put(class_idx, repl))
    placed = {
        k: jax.device_put(
            v, NamedSharding(mesh, P(TREE_AXIS,
                                     *([None] * (v.ndim - 1)))))
        for k, v in stacked.items()}
    return placed, replicate_on(mesh, class_idx)


def place_tree_axis(mesh: Mesh, arr):
    """Commit one host ``[T, ...]`` array with its leading tree axis
    split over ``mesh`` (trailing axes replicated) — the per-chunk
    routing-bit upload of the tree-sharded SHAP scan."""
    return jax.device_put(
        arr, NamedSharding(mesh, P(TREE_AXIS,
                                   *([None] * (np.ndim(arr) - 1)))))


def place_shap_sharded(tables: Dict, mesh: Mesh) -> Dict:
    """Commit stacked SHAP path tables (``ops/shap.py::
    build_shap_tables``, every array leading with the ``[T]`` axis)
    tree-sharded over ``mesh``. A tree count the mesh does not divide
    places replicated instead — the engine's pad path
    (``_shap_tables_for``) prevents that, mirroring
    :func:`place_tree_sharded`'s never-crash policy."""
    T = int(next(iter(tables.values())).shape[0])
    D = int(mesh.devices.size)
    if D <= 1 or T % D != 0:
        return {k: replicate_on(mesh, v) for k, v in tables.items()}
    return {k: place_tree_axis(mesh, v) for k, v in tables.items()}


def engine_kind(engine) -> str:
    """Capability-table engine key for a live engine object."""
    name = type(engine).__name__
    return {"GBDT": "gbdt", "DART": "dart", "RandomForest": "rf",
            "StreamingGBDT": "streaming"}.get(name, name.lower())


def forest_bytes_estimate(engine) -> int:
    """The shared utils/hbm.py stacked-forest estimate for this
    engine's CURRENT model, at the stable serving pad shapes — the
    pow2 (and, sharded, mesh-divisible) tree-count padding
    `_stack_for_predict` actually allocates (a 520-tree model stacks
    1024 padded slots; costing the raw count would let the registry
    byte cap admit ~2x the real bytes)."""
    from ..boosting.gbdt import _ceil_to, _next_pow2
    n_trees = len(getattr(engine, "models", []) or [])
    if getattr(engine, "_stable_predict_shapes", False) and n_trees:
        n_trees = _next_pow2(n_trees)
    est_mesh = getattr(engine, "_predict_mesh", None)
    if est_mesh is not None and n_trees:
        # the sharded stack pads further to a mesh-divisible count
        # (gbdt._stack_for_predict); cost what is actually pinned
        n_trees = _ceil_to(n_trees, int(est_mesh.devices.size))
    leaves = int(getattr(engine.config, "num_leaves", 31))
    words = 0
    if getattr(engine, "has_categorical", False):
        words = (int(getattr(engine, "B", 32)) + 31) // 32
    return stacked_forest_bytes(n_trees, leaves, words)


def enable_tree_sharding(booster, mesh: Optional[Mesh] = None
                         ) -> Optional[Mesh]:
    """Pin a serving Booster's predicts to the tree-sharded path.

    Returns the mesh in effect, or None when the capability table
    demotes this booster (host-model path, DART) or the host has one
    device — in which case nothing changes and the unsharded path
    keeps serving. Invalidates the stacked-forest cache so the next
    predict re-stacks at mesh-divisible padded shapes.
    """
    eng = getattr(booster, "_engine", None)
    if eng is None or getattr(booster, "_from_model", None) is not None:
        log.info("tree-sharded predict demoted: model-file boosters "
                 "serve through the host model")
        return None
    verdict = capabilities.sharded_predict_verdict(
        engine_kind(eng), getattr(eng, "config", None))
    if verdict != capabilities.SUPPORTED:
        log.info(f"tree-sharded predict demoted for the "
                 f"{type(eng).__name__} engine "
                 f"(capabilities.SHARDED_PREDICT); serving unsharded")
        return None
    if mesh is None:
        if len(jax.devices()) < 2:
            return None
        mesh = tree_mesh()
    if int(mesh.devices.size) < 2:
        return None
    if getattr(eng, "_predict_mesh", None) == mesh:
        # already engaged on this mesh: a re-applied policy (every LRU
        # admission runs it) must not bump the model version / drop the
        # stack cache, or warm checkouts re-stack forever
        return mesh
    eng._predict_mesh = mesh
    # stable bucketed shapes so every model in a size bucket — and the
    # mesh-divisible pad — reuses the compiled sharded programs
    eng._stable_predict_shapes = True
    eng._shard_consts = (replicate_on(mesh, eng.feat_num_bin),
                         replicate_on(mesh, eng.feat_has_nan))
    eng._invalidate_forest_cache()
    return mesh


def auto_shard_mesh(booster, cfg) -> Optional[Mesh]:
    """Apply the ``tpu_serve_shard_trees`` policy to one serving
    booster; returns the mesh engaged (or None)."""
    knob = str(getattr(cfg, "tpu_serve_shard_trees", "auto"))
    if knob == "false":
        return None
    if knob == "true":
        return enable_tree_sharding(booster)
    # auto: shard only when one resident copy of this forest would
    # crowd a single device
    eng = getattr(booster, "_engine", None)
    if eng is None:
        return None
    limit = hbm_bytes_limit()
    if not limit:
        return None
    if forest_bytes_estimate(eng) <= SERVE_HBM_FRACTION * limit:
        return None
    return enable_tree_sharding(booster)
