"""Serving service (docs/serving.md): the process in front of the
engine's predict surface.

Three layers, one import:

- **Queue** (serve/queue.py): thread-safe request queue with adaptive
  micro-batching — concurrent ``submit(model_id, X)`` calls coalesce
  into one bucketed dispatch per model under the
  ``tpu_serve_batch_budget_ms`` latency cutoff.
- **Registry** (serve/registry.py): multi-tenant bounded LRU of
  device-resident stacked forests (``tpu_serve_cache_models`` /
  ``tpu_serve_cache_bytes``), with per-model hot-swap watchers.
- **Shard** (serve/shard.py): tree-axis ``NamedSharding`` for forests
  too large for one device (``tpu_serve_shard_trees``), bit-identical
  to single-device predict.

One process: :class:`~.service.PredictService`. N replicas of it
behind an elastic router: :class:`~.fleet.FleetSupervisor` +
:class:`~.router.FleetRouter` (serve/fleet.py, serve/router.py —
docs/serving.md "Fleet deployment").
"""
from .fleet import FleetSupervisor, ReplicaModel
from .registry import ModelRegistry
from .router import FleetRouter
from .service import PredictService
from .shard import enable_tree_sharding, tree_mesh

__all__ = ["PredictService", "ModelRegistry", "FleetSupervisor",
           "FleetRouter", "ReplicaModel", "enable_tree_sharding",
           "tree_mesh"]
