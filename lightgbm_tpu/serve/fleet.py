"""Serving fleet: N ``PredictService`` replica processes under one
supervisor (docs/serving.md "Fleet deployment").

One serving process (serve/service.py) survives hot-swaps and slow
tenants but not its own death — "millions of users" (ROADMAP item 4)
needs replication. The fleet layer composes machinery that already
exists instead of inventing new protocols:

- **Replica** = one spawned process running the full single-process
  stack: micro-batch queue + LRU registry + (optionally tree-sharded)
  predict, a REQUIRED metrics endpoint on an ephemeral port
  (``obs.server.start_server(0, required=True)`` — a replica whose
  /metrics cannot bind is invisible to the router and refuses to
  start), a tiny HTTP predict endpoint the router calls, and a
  per-rank heartbeat stamp file (the gang launcher's watchdog file
  protocol, ``heartbeat.serve.rank<r>``).
- **Readiness is warmup** (the PR 15 contract): a joining replica
  warms every pow2 bucket through its real dispatch queue before
  ``heartbeat.serve`` is stamped, so its ``/readyz`` stays 503 — and
  the router admits zero traffic — until the steady state is
  compiled.
- **Liveness has two watchers**: the supervisor kills-and-relaunches
  a replica whose heartbeat FILE goes stale (wedged dispatch: the
  replica's idle loop stamps only while ``queue.depth()==0 and
  service.inflight==0``, so a predict stuck on-device stops the
  stamps) or whose process exits; the router independently stops
  routing at a replica whose ``/readyz`` goes 503 and re-dispatches
  its un-acked in-flight work to siblings (predict is pure — a
  re-sent request is idempotent).
- **Elastic membership** reuses degrade-and-continue (PR 18): a
  ``.host_gone.rank<r>`` marker (chaos harness or operator
  touch-file) or an exhausted per-replica restart budget retires the
  slot permanently — the fleet degrades to N−1 and keeps serving —
  while ordinary deaths relaunch into the SAME rank with a fresh
  generation.
- **Model convergence needs no coordination**: every replica watches
  the one checkpoint dir through its own ``ModelWatcher`` (atomic
  forward-only publishes + per-watcher poll jitter), so publishes
  reach all replicas without a control plane.

Fleet metrics (forced — rare events must be visible with metrics
off; docs/observability.md): ``fleet.replicas_live``,
``fleet.degrades``, ``fleet.relaunches`` in this module;
``fleet.router_retries``, ``fleet.redispatches`` in serve/router.py.

The wire protocol is deliberately minimal (stdlib http + npy bodies,
localhost only — same safety posture as obs/server.py): the router
POSTs ``/predict?model=<id>`` with an ``np.save`` body and gets an
``np.save`` body back. 404 = unknown model (a REQUEST error: the
router fails the future, no retry); 503 = closed/overloaded and any
connection error = a REPLICA error (the router retries a sibling).
"""
from __future__ import annotations

import io
import json
import multiprocessing as mp
import os
import signal
import tempfile
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from .. import obs
from ..recovery.faults import (clear_host_gone_markers, host_gone_ranks,
                               write_host_gone_marker)
from ..utils import log

__all__ = ["FleetSupervisor", "ReplicaModel", "ReplicaHandle"]

_HB_PREFIX = "heartbeat.serve.rank"
_ENDPOINT_TMPL = "replica_{rank}.json"


@dataclass
class ReplicaModel:
    """One tenant every replica serves: the model text (pickles across
    the spawn boundary), a sample row for bucketed warmup, and an
    optional checkpoint dir the replica's watcher hot-swaps from."""

    model_id: str
    model_str: str
    warmup_row: Optional[np.ndarray] = None
    watch_dir: Optional[str] = None
    watch_interval: float = 2.0


@dataclass
class ReplicaHandle:
    """Supervisor-side view of one replica slot."""

    rank: int
    proc: Optional[mp.process.BaseProcess] = None
    generation: int = 0
    restarts: int = 0
    predict_url: Optional[str] = None
    metrics_url: Optional[str] = None
    ready: bool = False
    retired: bool = False          # degraded away — never relaunched
    started_at: float = 0.0
    inflight: int = 0              # router-side in-flight counter
    depth: float = 0.0             # last scraped slo.queue_depth

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.is_alive()


# ----------------------------------------------------------------------
# replica process side
# ----------------------------------------------------------------------

def _scrub_replica_obs_params(params: Dict) -> Dict:
    """The driver's obs knobs must not replay in a replica: a fixed
    tpu_metrics_port would collide across N processes (the replica
    binds its own REQUIRED ephemeral endpoint), and file-writing knobs
    (dump/rank-dir/trace) would have N processes clobber one path."""
    p = dict(params or {})
    for k in ("tpu_metrics_port", "tpu_metrics_dump",
              "tpu_metrics_rank_dir", "tpu_trace_dir",
              "tpu_model_watch"):
        p.pop(k, None)
    return p


class _PredictHandler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-replica"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:       # router calls spam logs
        pass

    def _send(self, code: int, body: bytes,
              ctype: str = "application/octet-stream") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_err(self, code: int, msg: str) -> None:
        self._send(code, json.dumps({"error": msg}).encode(),
                   "application/json")

    def do_POST(self) -> None:          # noqa: N802 (stdlib API name)
        path, _, query = self.path.partition("?")
        if path != "/predict":
            self._send_err(404, "not found")
            return
        model_id = None
        for part in query.split("&"):
            if part.startswith("model="):
                model_id = urllib.parse.unquote(part[len("model="):])
        try:
            n = int(self.headers.get("Content-Length", "0"))
            X = np.load(io.BytesIO(self.rfile.read(n)),
                        allow_pickle=False)
        except Exception as e:
            self._send_err(400, f"bad payload: {e}")
            return
        svc = self.server.service
        try:
            out = svc.predict(model_id or "", X,
                              timeout=self.server.predict_timeout_s)
        except KeyError as e:
            self._send_err(404, f"unknown model: {e}")
            return
        except RuntimeError as e:
            # closed queue / shutdown — retriable at a sibling
            self._send_err(503, str(e))
            return
        except Exception as e:
            self._send_err(500, f"{type(e).__name__}: {e}")
            return
        buf = io.BytesIO()
        np.save(buf, np.asarray(out), allow_pickle=False)
        try:
            self._send(200, buf.getvalue())
        except BrokenPipeError:
            pass        # router gave up / died mid-reply; work is pure


class _PredictServer(ThreadingHTTPServer):
    daemon_threads = True
    service = None
    predict_timeout_s = 30.0


def _replica_main(rank: int, fleet_dir: str, params: Dict,
                  models: List[ReplicaModel], heartbeat_timeout: float,
                  platform: Optional[str], warmup_delay_s: float,
                  predict_timeout_s: float) -> None:
    """Entry point of one spawned replica process: build the full
    single-process serving stack, prove readiness by warmup, publish
    the endpoint file, then idle-stamp liveness until killed."""
    from ..parallel.launch import strip_fake_device_flags
    strip_fake_device_flags()
    if platform:
        # through jax.config, not the env var: a site config that
        # pins jax_platforms (e.g. the tunneled-TPU container) ignores
        # JAX_PLATFORMS — and N replicas must not fight over one chip
        import jax
        jax.config.update("jax_platforms", platform)
    import lightgbm_tpu as lgb
    from ..obs.server import start_server
    from .service import PredictService

    obs.enable(metrics=True, slo=True)
    # REQUIRED endpoint on an ephemeral port: a replica the router
    # cannot scrape must fail its launch, not serve blind
    srv = start_server(0, heartbeat_timeout_s=heartbeat_timeout,
                       required=True)
    # heartbeat FILE before the first stamp: warmup's heartbeat("serve")
    # doubles as the supervisor watchdog's first proof of life
    obs.set_heartbeat_file(
        "serve", os.path.join(fleet_dir, f"{_HB_PREFIX}{rank}"))

    svc = PredictService(_scrub_replica_obs_params(params))
    for spec in models:
        bst = lgb.Booster(model_str=spec.model_str)
        svc.add_model(spec.model_id, bst, watch_dir=spec.watch_dir,
                      watch_interval=spec.watch_interval)

    httpd = _PredictServer(("127.0.0.1", 0), _PredictHandler)
    httpd.service = svc
    httpd.predict_timeout_s = float(predict_timeout_s)
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="lightgbm-tpu-replica-predict").start()

    # publish WHERE to find this replica before it is ready — the
    # supervisor/router poll /readyz (503 until warmup stamps the
    # heartbeat) to decide WHEN to admit traffic. Atomic rename: a
    # half-written endpoint file must never parse
    ep = {"rank": rank, "pid": os.getpid(),
          "predict_url": f"http://127.0.0.1:"
                         f"{httpd.server_address[1]}",
          "metrics_url": srv.url}
    path = os.path.join(fleet_dir, _ENDPOINT_TMPL.format(rank=rank))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ep, f)
    os.replace(tmp, path)

    if warmup_delay_s > 0:      # chaos/test hook: a slow joiner
        time.sleep(warmup_delay_s)
    for spec in models:
        row = spec.warmup_row
        if row is None:
            continue
        svc.warmup(spec.model_id, np.asarray(row, np.float64)
                   .reshape(1, -1))

    # liveness loop: stamp while TRULY idle (empty queue AND nothing
    # mid-dispatch). Under load _record() stamps per dispatched batch;
    # a wedged predict leaves inflight>0 with no _record stamps — the
    # file goes stale and the supervisor replaces this process
    try:
        while True:
            t = svc._thread
            if t is None or not t.is_alive():
                break               # dispatcher died: stop stamping
            if svc.queue.depth() == 0 and svc.inflight == 0:
                obs.heartbeat("serve")
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    svc.close()


# ----------------------------------------------------------------------
# supervisor side
# ----------------------------------------------------------------------

class FleetSupervisor:
    """Spawns, watches, relaunches, and degrades N serving replicas.

    The monitor thread owns membership: process exits and stale
    heartbeat files turn into relaunches (same rank, next generation)
    until the slot's ``max_restarts`` budget runs out or a host-gone
    marker names it — then the slot retires and the fleet serves at
    N−1 (degrade-and-continue, PR 18 semantics). ``/readyz`` scraped
    per replica gates ``ReplicaHandle.ready``; the router
    (serve/router.py) only dispatches at ready handles and gets
    queue-depth hints from the same scrape loop.
    """

    def __init__(self, params: Optional[Dict],
                 models: List[ReplicaModel], n_replicas: int, *,
                 fleet_dir: Optional[str] = None,
                 max_restarts: int = 2,
                 heartbeat_timeout: float = 10.0,
                 platform: Optional[str] = "cpu",
                 warmup_delay_s: float = 0.0,
                 slow_warmup_ranks: tuple = (),
                 predict_timeout_s: float = 30.0,
                 poll_s: float = 0.1):
        if n_replicas < 1:
            raise ValueError("fleet: n_replicas must be >= 1")
        self.params = dict(params or {})
        self.models = list(models)
        self.n_replicas = int(n_replicas)
        self.fleet_dir = fleet_dir or tempfile.mkdtemp(
            prefix="lgbm_tpu_fleet_")
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.max_restarts = int(max_restarts)
        self.heartbeat_timeout = max(float(heartbeat_timeout), 1.0)
        self.platform = platform
        self.warmup_delay_s = float(warmup_delay_s)
        self.slow_warmup_ranks = tuple(slow_warmup_ranks)
        self.predict_timeout_s = float(predict_timeout_s)
        self.poll_s = float(poll_s)
        self.handles: List[ReplicaHandle] = [
            ReplicaHandle(rank=r) for r in range(self.n_replicas)]
        self.degrades = 0
        self.relaunches = 0
        self._ctx = mp.get_context("spawn")
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        # fresh-run hygiene, exactly like the gang launcher: stale
        # heartbeat files read as instantly-hung replicas, stale
        # host-gone markers re-apply yesterday's loss
        self._clear_files()
        clear_host_gone_markers(self.fleet_dir)
        for h in self.handles:
            self._launch(h)
        self._stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="lightgbm-tpu-fleet-monitor")
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        for h in self.handles:
            self._terminate(h)

    def __enter__(self) -> "FleetSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    def ready_handles(self) -> List[ReplicaHandle]:
        """Snapshot of handles the router may dispatch at."""
        with self._lock:
            return [h for h in self.handles
                    if h.ready and not h.retired and h.alive]

    def live_count(self) -> int:
        return len(self.ready_handles())

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 120.0) -> int:
        """Block until ``n`` replicas (default: every non-retired
        slot) pass /readyz; returns the ready count."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                want = n if n is not None else sum(
                    1 for h in self.handles if not h.retired)
            got = self.live_count()
            if got >= want:
                return got
            time.sleep(0.05)
        return self.live_count()

    # ------------------------------------------------------------------
    def kill_replica(self, rank: int, host_gone: bool = False) -> None:
        """Chaos/test helper: SIGKILL one replica mid-traffic. With
        ``host_gone`` the marker is written FIRST, so the monitor
        degrades instead of relaunching — the 'machine vanished'
        shape, not the 'process crashed' shape."""
        h = self.handles[rank]
        if host_gone:
            write_host_gone_marker(self.fleet_dir, rank,
                                   note="fleet kill_replica")
        if h.proc is not None and h.proc.pid and h.alive:
            try:
                os.kill(h.proc.pid, signal.SIGKILL)
            except OSError:
                pass

    # ------------------------------------------------------------------
    def _clear_files(self) -> None:
        try:
            names = os.listdir(self.fleet_dir)
        except OSError:
            return
        for name in names:
            if name.startswith(_HB_PREFIX) \
                    or name.startswith("replica_"):
                try:
                    os.unlink(os.path.join(self.fleet_dir, name))
                except OSError:
                    pass

    def _launch(self, h: ReplicaHandle) -> None:
        """(Re)spawn one slot; the handle's endpoint/readiness reset
        until the new process republishes and re-warms."""
        h.ready = False
        h.predict_url = None
        h.metrics_url = None
        h.depth = 0.0
        # a relaunch must not read the DEAD generation's last stamp as
        # fresh, nor its endpoint file as live
        for name in (f"{_HB_PREFIX}{h.rank}",
                     _ENDPOINT_TMPL.format(rank=h.rank)):
            try:
                os.unlink(os.path.join(self.fleet_dir, name))
            except OSError:
                pass
        delay = self.warmup_delay_s \
            if (not self.slow_warmup_ranks
                or h.rank in self.slow_warmup_ranks) else 0.0
        h.proc = self._ctx.Process(
            target=_replica_main,
            args=(h.rank, self.fleet_dir, self.params, self.models,
                  self.heartbeat_timeout, self.platform, delay,
                  self.predict_timeout_s),
            daemon=True, name=f"lgbm-tpu-replica-{h.rank}")
        h.proc.start()
        h.generation += 1
        h.started_at = time.monotonic()

    def _terminate(self, h: ReplicaHandle) -> None:
        if h.proc is None:
            return
        try:
            if h.alive:
                h.proc.terminate()
                h.proc.join(timeout=3.0)
            if h.alive:
                h.proc.kill()
                h.proc.join(timeout=3.0)
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:      # the fleet outlives its nurse
                log.warning(f"fleet: monitor tick failed ({e})")
            self._stop.wait(self.poll_s)

    def _tick(self) -> None:
        gone = set(host_gone_ranks(self.fleet_dir))
        for h in self.handles:
            if h.retired:
                continue
            if h.rank in gone:
                self._retire(h, f"host-gone marker for rank {h.rank}")
                clear_host_gone_markers(self.fleet_dir,
                                        ranks=[h.rank])
                continue
            if not h.alive:
                self._replace(h, f"exit code {h.proc.exitcode}"
                              if h.proc is not None else "never spawned")
                continue
            age = self._heartbeat_age(h)
            if age is not None and age > self.heartbeat_timeout:
                log.warning(f"fleet: replica {h.rank} heartbeat stale "
                            f"({age:.1f}s > {self.heartbeat_timeout}s)"
                            f"; killing for relaunch")
                self.kill_replica(h.rank)
                self._replace(h, f"stale heartbeat ({age:.1f}s)")
                continue
            self._scrape(h)
        obs.set_gauge("fleet.replicas_live", float(self.live_count()),
                      force=True)

    def _heartbeat_age(self, h: ReplicaHandle) -> Optional[float]:
        """Age of the slot's stamp file; None before the first stamp
        (starting up / warming — that is readiness's job, not a
        hang)."""
        try:
            st = os.stat(os.path.join(self.fleet_dir,
                                      f"{_HB_PREFIX}{h.rank}"))
        except OSError:
            return None
        return time.time() - st.st_mtime

    def _replace(self, h: ReplicaHandle, why: str) -> None:
        with self._lock:
            h.ready = False
        self._terminate(h)
        if h.restarts >= self.max_restarts:
            self._retire(h, f"restart budget exhausted "
                         f"({self.max_restarts}) after: {why}")
            return
        h.restarts += 1
        self.relaunches += 1
        obs.inc("fleet.relaunches", force=True)
        log.warning(f"fleet: replica {h.rank} down ({why}); "
                    f"relaunching (restart {h.restarts}/"
                    f"{self.max_restarts}, generation "
                    f"{h.generation + 1})")
        self._launch(h)

    def _retire(self, h: ReplicaHandle, why: str) -> None:
        with self._lock:
            h.ready = False
            h.retired = True
        self._terminate(h)
        self.degrades += 1
        obs.inc("fleet.degrades", force=True)
        width = sum(1 for x in self.handles if not x.retired)
        log.warning(f"fleet: replica {h.rank} RETIRED ({why}); "
                    f"degrading to {width} replica(s) — queued work "
                    f"drains to siblings")

    # ------------------------------------------------------------------
    def _scrape(self, h: ReplicaHandle) -> None:
        """One monitor-loop scrape: endpoint discovery, /readyz
        admission, and the router's queue-depth hint."""
        if h.predict_url is None:
            path = os.path.join(self.fleet_dir,
                                _ENDPOINT_TMPL.format(rank=h.rank))
            try:
                with open(path) as f:
                    ep = json.load(f)
            except (OSError, ValueError):
                return      # not published yet
            # a stale file from the PREVIOUS generation is unlinked in
            # _launch, so whatever parses here is this generation's
            h.predict_url = ep["predict_url"]
            h.metrics_url = ep["metrics_url"]
        ready = False
        depth = h.depth
        try:
            with urllib.request.urlopen(
                    h.metrics_url + "/readyz", timeout=2.0) as r:
                ready = (r.status == 200)
            with urllib.request.urlopen(
                    h.metrics_url + "/metrics.json", timeout=2.0) as r:
                snap = json.load(r)
            for m in snap.get("metrics", []):
                if m.get("name") == "slo.queue_depth":
                    depth = float(m.get("value", 0.0))
        except Exception:
            # scrape failures degrade to "not ready" — the process
            # watchdogs (exit / stale heartbeat) decide its fate
            ready = False
        if ready and not h.ready:
            log.info(f"fleet: replica {h.rank} (generation "
                     f"{h.generation}) is ready — router admitted")
        with self._lock:
            h.ready = ready
            h.depth = depth
