"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

Capability surface of LightGBM (reference: xyzhou-puck/LightGBM — see
SURVEY.md; the mount was empty so the upstream-derived survey is the spec),
re-designed TPU-first on JAX/XLA: histogram split finding as one-hot
matmuls on the MXU, leaf-wise growth as a jitted while_loop, per-row
leaf-id partitioning, and mesh collectives (psum/psum_scatter/all_gather)
in place of the reference's socket/MPI/NCCL distributed learners.
"""
from . import obs
from .basic import Booster, Dataset, LightGBMError
from .callback import (EarlyStopException, checkpoint, early_stopping,
                       log_evaluation, record_evaluation,
                       record_metrics, reset_parameter)
from .config import Config
from .engine import CVBooster, cv, train

__version__ = "0.2.0"

__all__ = [
    "Booster", "Dataset", "LightGBMError", "Config",
    "train", "cv", "CVBooster",
    "early_stopping", "log_evaluation", "record_evaluation",
    "record_metrics", "reset_parameter", "EarlyStopException",
    "checkpoint", "CheckpointManager", "CheckpointError", "obs",
    "ModelWatcher", "PredictService", "ModelRegistry",
    "FleetSupervisor", "FleetRouter", "ReplicaModel",
]


def __getattr__(name):
    # lazy submodule-level exports (sklearn API, plotting, multi-host)
    # to keep import light; mirrors python-package/lightgbm/__init__.py
    try:
        if name in ("LGBMModel", "LGBMClassifier", "LGBMRegressor",
                    "LGBMRanker"):
            from . import sklearn as _sk
            return getattr(_sk, name)
        if name in ("plot_importance", "plot_metric", "plot_tree",
                    "create_tree_digraph"):
            from . import plotting as _pl
            return getattr(_pl, name)
        if name in ("init_multihost", "is_multihost"):
            from .parallel import multihost as _mh
            return getattr(_mh, name)
        if name in ("train_distributed", "run_worker", "ShardSpec",
                    "sync_bin_mappers"):
            from .parallel import launch as _la
            return getattr(_la, name)
        if name in ("CheckpointManager", "CheckpointError"):
            from .recovery import checkpoint as _ck
            return getattr(_ck, name)
        if name == "ModelWatcher":
            from . import serving as _sv
            return _sv.ModelWatcher
        if name in ("PredictService", "ModelRegistry",
                    "FleetSupervisor", "FleetRouter", "ReplicaModel"):
            from . import serve as _srv
            return getattr(_srv, name)
    except ImportError as e:
        raise AttributeError(
            f"module 'lightgbm_tpu' has no attribute {name!r}: {e}") from e
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name!r}")
