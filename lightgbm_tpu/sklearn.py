"""scikit-learn estimator API: LGBMModel / Classifier / Regressor / Ranker.

Reference: python-package/lightgbm/sklearn.py (UNVERIFIED — empty mount,
see SURVEY.md banner): thin estimator shells over ``train()`` — sklearn
constructor params map onto LightGBM params through the config alias
table (n_estimators→num_iterations, subsample→bagging_fraction,
reg_alpha→lambda_l1, ...), fit() builds Datasets and delegates, the
classifier label-encodes and exposes predict_proba, the ranker wires
query groups.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

try:  # inherit real sklearn base classes when available (tags, clone)
    from sklearn.base import BaseEstimator as _SKBase
    from sklearn.base import ClassifierMixin as _SKClassifier
    from sklearn.base import RegressorMixin as _SKRegressor
    _SKLEARN = True
except ImportError:  # pragma: no cover - sklearn is in the image
    _SKBase = object

    class _SKClassifier:
        pass

    class _SKRegressor:
        pass
    _SKLEARN = False

from .basic import Booster, Dataset, LightGBMError
from .engine import train

__all__ = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]


class LGBMModel(_SKBase):
    """Base sklearn-style estimator (lightgbm.LGBMModel surface)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None,
                 n_jobs: Optional[int] = None,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None
        self.best_iteration_ = -1
        self.best_score_: Dict = {}
        self.evals_result_: Dict = {}

    # -- sklearn plumbing ------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = (super().get_params(deep=deep) if _SKLEARN
                  else {k: getattr(self, k) for k in (
                      "boosting_type", "num_leaves", "max_depth",
                      "learning_rate", "n_estimators", "subsample_for_bin",
                      "objective", "class_weight", "min_split_gain",
                      "min_child_weight", "min_child_samples", "subsample",
                      "subsample_freq", "colsample_bytree", "reg_alpha",
                      "reg_lambda", "random_state", "n_jobs",
                      "importance_type")})
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            setattr(self, k, v)
            if k not in self.__init__.__code__.co_varnames:
                self._other_params[k] = v
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _make_params(self) -> Dict[str, Any]:
        p = self.get_params()
        p.pop("n_jobs", None)           # XLA owns threading
        p.pop("class_weight", None)
        p.pop("importance_type", None)
        p["boosting"] = p.pop("boosting_type", "gbdt")
        p["num_iterations"] = p.pop("n_estimators", 100)
        if p.get("random_state") is None:
            p.pop("random_state", None)
        obj = p.get("objective")
        if obj is None:
            p["objective"] = self._default_objective()
        p.setdefault("verbosity", -1)
        return p

    # -- training --------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMModel":
        params = self._make_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        y2, sample_weight = self._process_label(y, sample_weight)
        ds = Dataset(X, label=y2, weight=sample_weight,
                     init_score=init_score, group=group,
                     feature_name=feature_name,
                     categorical_feature=categorical_feature)
        valid_sets, valid_names = [], []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vy2, vw = self._process_label(
                    vy, eval_sample_weight[i] if eval_sample_weight
                    else None)
                vgroup = eval_group[i] if eval_group else None
                vinit = eval_init_score[i] if eval_init_score else None
                if np.shape(vx) == np.shape(X) \
                        and np.allclose(np.asarray(vx, dtype=np.float64),
                                        Dataset._to_matrix(X),
                                        equal_nan=True):
                    valid_sets.append(ds)
                else:
                    valid_sets.append(ds.create_valid(
                        vx, label=vy2, weight=vw, group=vgroup,
                        init_score=vinit))
                valid_names.append(eval_names[i] if eval_names
                                   else f"valid_{i}")
        self.evals_result_ = {}
        callbacks = list(callbacks or [])
        from .callback import record_evaluation
        callbacks.append(record_evaluation(self.evals_result_))
        fobj = self.objective if callable(self.objective) else None
        if fobj is not None:
            params["objective"] = "custom"
        self._Booster = train(
            params, ds, valid_sets=valid_sets or None,
            valid_names=valid_names or None, callbacks=callbacks,
            init_model=init_model, fobj=fobj)
        self.best_iteration_ = self._Booster.best_iteration
        self.best_score_ = self._Booster.best_score
        self.n_features_ = self._Booster.num_feature()
        self.n_features_in_ = self.n_features_
        self.feature_name_ = self._Booster.feature_name()
        self.fitted_ = True
        return self

    def _process_label(self, y, sample_weight):
        return np.asarray(y, dtype=np.float64).ravel(), sample_weight

    # -- inference -------------------------------------------------------
    def predict(self, X, raw_score: bool = False,
                start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kwargs) -> np.ndarray:
        """Predict (serving fast path: tree-parallel traversal, cached
        device forest, batch-shape bucketing). Extra ``kwargs`` follow
        the upstream predict-params convention — e.g.
        ``tpu_predict_chunk_rows=8192`` tunes one call's streaming
        chunk size without touching the fitted model's params."""
        return self.booster_.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)

    # -- fitted attributes ----------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError(
                "No booster found. Need to call fit beforehand.")
        return self._Booster

    @property
    def n_estimators_(self) -> int:
        return self.booster_.current_iteration()

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(
            importance_type=self.importance_type)

    @property
    def objective_(self):
        return (self.objective if self.objective is not None
                else self._default_objective())

    def __sklearn_is_fitted__(self) -> bool:
        return getattr(self, "fitted_", False)


class LGBMRegressor(_SKRegressor, LGBMModel):
    """lightgbm.LGBMRegressor"""


class LGBMClassifier(_SKClassifier, LGBMModel):
    """lightgbm.LGBMClassifier: label-encodes arbitrary class labels,
    auto-selects binary vs multiclass, exposes predict_proba."""

    def _default_objective(self) -> str:
        return ("multiclass" if getattr(self, "n_classes_", 2) > 2
                else "binary")

    def _process_label(self, y, sample_weight):
        y = np.asarray(y).ravel()
        enc = np.searchsorted(self.classes_, y)
        ok = (enc < len(self.classes_))
        enc = np.clip(enc, 0, len(self.classes_) - 1)
        if not np.all(ok & (self.classes_[enc] == y)):
            raise LightGBMError("eval_set labels contain classes unseen "
                                "in y")
        if self.class_weight is not None and sample_weight is None:
            if self.class_weight == "balanced":
                cnt = np.bincount(enc, minlength=self.n_classes_)
                w_per_class = len(y) / (self.n_classes_
                                        * np.maximum(cnt, 1))
            else:
                w_per_class = np.array(
                    [self.class_weight.get(c, 1.0)
                     for c in self.classes_], dtype=np.float64)
            sample_weight = w_per_class[enc]
        return enc.astype(np.float64), sample_weight

    def fit(self, X, y, **kwargs):
        y_arr = np.asarray(y).ravel()
        self.classes_ = np.unique(y_arr)
        self.n_classes_ = len(self.classes_)
        auto = getattr(self, "_auto_num_class", False)
        if not callable(self.objective) and self.n_classes_ > 2:
            self._other_params["num_class"] = self.n_classes_
            setattr(self, "num_class", self.n_classes_)
            self._auto_num_class = True
        elif auto:
            # a previous fit's AUTO-set class count must not leak into a
            # refit (binary, or custom-objective); a user-supplied
            # num_class is left untouched
            self._other_params.pop("num_class", None)
            if hasattr(self, "num_class"):
                del self.num_class
            self._auto_num_class = False
        return super().fit(X, y, **kwargs)

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      **kwargs) -> np.ndarray:
        p = self.booster_.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, **kwargs)
        if raw_score or p.ndim == 2:
            return p
        return np.column_stack([1.0 - p, p])

    def predict(self, X, raw_score: bool = False, **kwargs) -> np.ndarray:
        p = self.predict_proba(X, raw_score=raw_score, **kwargs)
        if raw_score or kwargs.get("pred_leaf") \
                or kwargs.get("pred_contrib"):
            return p
        return self.classes_[np.argmax(p, axis=1)]


class LGBMRanker(LGBMModel):
    """lightgbm.LGBMRanker: lambdarank with query groups."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise LightGBMError("Should set group for ranking task")
        return super().fit(X, y, group=group, **kwargs)
