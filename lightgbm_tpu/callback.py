"""Callback protocol for the training loop.

Reference: python-package/lightgbm/callback.py (UNVERIFIED — empty mount,
see SURVEY.md banner): callbacks receive a ``CallbackEnv`` namedtuple
before/after each iteration; ``EarlyStopException`` unwinds the loop.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Tuple

from .utils import log


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            parts = [
                f"{name}'s {metric}: {value:g}"
                for name, metric, value, _ in env.evaluation_result_list]
            log.info(f"[{env.iteration + 1}]\t" + "\t".join(parts))
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _callback(env: CallbackEnv) -> None:
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, []).append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    """Reset parameters (e.g. learning_rate schedule) per iteration."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal "
                        "num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(
                    env.iteration - env.begin_iteration)
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            log.warning("Early stopping is not available in dart mode or "
                        "without valid sets")
            return
        if verbose:
            log.info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds")
        first_metric[0] = env.evaluation_result_list[0][1]
        for *_head, higher_better in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y + min_delta)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y - min_delta)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, (name, metric, value, _hb) in \
                enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value,
                                                       best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != metric:
                continue
            if name == "training":
                continue  # train metric does not trigger early stopping
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info(f"Early stopping, best iteration is:\n"
                             f"[{best_iter[i] + 1}]")
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    log.info(f"Did not meet early stopping. Best iteration "
                             f"is:\n[{best_iter[i] + 1}]")
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    return _callback
