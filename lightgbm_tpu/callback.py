"""Callback protocol for the training loop.

Reference: python-package/lightgbm/callback.py (UNVERIFIED — empty mount,
see SURVEY.md banner): callbacks receive a ``CallbackEnv`` namedtuple
before/after each iteration; ``EarlyStopException`` unwinds the loop.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Tuple

from .utils import log


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            parts = [
                f"{name}'s {metric}: {value:g}"
                for name, metric, value, _ in env.evaluation_result_list]
            log.info(f"[{env.iteration + 1}]\t" + "\t".join(parts))
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _callback(env: CallbackEnv) -> None:
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, []).append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    """Reset parameters (e.g. learning_rate schedule) per iteration."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal "
                        "num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(
                    env.iteration - env.begin_iteration)
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    higher_better_list: List[bool] = []
    enabled = [True]
    first_metric = [""]

    def _make_cmp(higher_better: bool) -> Callable:
        if higher_better:
            return lambda x, y: x > y + min_delta
        return lambda x, y: x < y - min_delta

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            log.warning("Early stopping is not available in dart mode or "
                        "without valid sets")
            return
        if verbose:
            log.info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds")
        first_metric[0] = env.evaluation_result_list[0][1]
        for *_head, higher_better in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            higher_better_list.append(bool(higher_better))
            cmp_op.append(_make_cmp(higher_better))
            best_score.append(float("-inf") if higher_better
                              else float("inf"))

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        elif enabled[0] and env.evaluation_result_list \
                and len(best_score) != len(env.evaluation_result_list):
            # restored checkpoint state from a run with a different
            # metric/valid-set layout: reinitialize rather than index
            # stale lists (best-effort resume, like the score rebuild)
            log.warning(
                "early-stopping state restored from the checkpoint "
                "does not match this run's metric/valid-set layout; "
                "reinitializing early-stopping tracking")
            for lst in (best_score, best_iter, best_score_list, cmp_op,
                        higher_better_list):
                lst.clear()
            _init(env)
        if not enabled[0]:
            return
        for i, (name, metric, value, _hb) in \
                enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value,
                                                       best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != metric:
                continue
            if name == "training":
                continue  # train metric does not trigger early stopping
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info(f"Early stopping, best iteration is:\n"
                             f"[{best_iter[i] + 1}]")
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    log.info(f"Did not meet early stopping. Best iteration "
                             f"is:\n[{best_iter[i] + 1}]")
                raise EarlyStopException(best_iter[i], best_score_list[i])

    # checkpoint/resume hooks (recovery subsystem): the best-score
    # tracking above is closure state, so a resumed run must restore it
    # explicitly for bit-exact stopping decisions. cmp_op holds lambdas
    # (not picklable) and is rebuilt from the saved direction flags.
    def _get_state() -> Dict[str, Any]:
        return {
            "best_score": list(best_score),
            "best_iter": list(best_iter),
            "best_score_list": [None if s is None
                                else [tuple(r) for r in s]
                                for s in best_score_list],
            "higher_better": list(higher_better_list),
            "enabled": enabled[0],
            "first_metric": first_metric[0],
        }

    def _set_state(state: Dict[str, Any]) -> None:
        best_score[:] = [float(v) for v in state["best_score"]]
        best_iter[:] = [int(v) for v in state["best_iter"]]
        best_score_list[:] = [None if s is None
                              else [tuple(r) for r in s]
                              for s in state["best_score_list"]]
        higher_better_list[:] = [bool(b) for b in state["higher_better"]]
        cmp_op[:] = [_make_cmp(b) for b in higher_better_list]
        enabled[0] = bool(state["enabled"])
        first_metric[0] = state["first_metric"]
    _callback.order = 30
    _callback.state_key = "early_stopping"
    _callback.get_state = _get_state
    _callback.set_state = _set_state
    return _callback


def record_metrics(sink, period: int = 1) -> Callable:
    """Per-round observability sink (docs/observability.md): every
    ``period`` iterations, hand the current metrics snapshot to the
    user. ``sink`` is either a list (snapshots are appended, each
    tagged with its iteration) or a callable invoked as
    ``sink(env, snapshot)``.

    Constructing the callback turns the metrics pillar on — asking for
    per-round snapshots IS opting in (same contract as
    ``tpu_metrics=true``). Device/compile gauges are NOT refreshed per
    round (that would add a device sync to every iteration); the final
    snapshot from ``Booster.metrics()`` / ``tpu_metrics_dump`` carries
    current ones.
    """
    from . import obs
    obs.enable(metrics=True)
    if not callable(sink) and not isinstance(sink, list):
        raise TypeError("record_metrics sink should be a list or a "
                        "callable")

    def _callback(env: CallbackEnv) -> None:
        if period <= 0 or (env.iteration + 1) % period != 0:
            return
        snap = obs.snapshot(refresh_device=False)
        if callable(sink):
            sink(env, snap)
        else:
            snap["iteration"] = env.iteration
            sink.append(snap)
    # after evaluation/early-stop bookkeeping so the snapshot reflects
    # the completed round
    _callback.order = 35
    return _callback


def checkpoint(checkpoint_dir: str, interval: int = 1, keep_n: int = 3,
               manager=None) -> Callable:
    """Durable-checkpoint callback: every ``interval`` iterations,
    atomically persist COMPLETE training state — model text, iteration
    counter, bagging/feature/DART host RNG states, the exact score
    arrays, early-stopping best-score state — so
    ``lgb.train(..., resume_from=checkpoint_dir)`` continues bit-exact
    (stronger than ``init_model``, which drops RNG/best-score state).

    ``engine.train`` wires this automatically from the
    ``checkpoint_dir`` / ``checkpoint_interval`` params; pass it in
    ``callbacks=[...]`` for manual control (e.g. a shared
    ``CheckpointManager``). See docs/robustness.md.
    """
    from .recovery.checkpoint import CheckpointManager
    mgr = (manager if manager is not None
           else CheckpointManager(checkpoint_dir, keep_n=keep_n))
    peers: List[Callable] = []
    warned = [False]

    def _callback(env: CallbackEnv) -> None:
        it = env.iteration + 1
        if interval <= 0 or it % int(interval) != 0:
            return
        model = env.model
        engine = getattr(model, "_engine", None)
        if engine is None or not hasattr(engine, "export_train_state"):
            if not warned[0]:
                warned[0] = True
                log.warning(
                    "callback.checkpoint: the model has no "
                    "checkpointable training engine (cv boosters are "
                    "not checkpointable); skipping checkpoint saves")
            return
        cb_states: Dict[str, Any] = {}
        for cb in peers:
            key = getattr(cb, "state_key", None)
            if key and hasattr(cb, "get_state"):
                cb_states[key] = cb.get_state()
        # model_str is a NORMAL self-contained model save (salvageable
        # with Booster(model_str=...) for ops); resume restores the
        # engine's host trees from the exact pickled copies in the
        # engine state instead — model text rounds internal_value/
        # leaf_weight through "{:g}", which is not bit-exact
        from . import obs
        state = {
            "version": 1,
            "iteration": it,
            "model_str": model.model_to_string(),
            "engine": engine.export_train_state(),
            "callbacks": cb_states,
            "booster": {
                "best_iteration": model.best_iteration,
                "best_score": {k: dict(v)
                               for k, v in model.best_score.items()},
            },
            # metrics ride along so a resumed run CONTINUES the
            # interrupted run's counters/histograms instead of
            # restarting them at zero (engine.train imports this on
            # resume_from; docs/observability.md)
            "obs": obs.export_state(),
        }
        mgr.save(state, it)

    def _bind(callbacks: List[Callable]) -> None:
        peers[:] = [cb for cb in callbacks if cb is not _callback]
    # after early_stopping (order 30) so the saved best-score state
    # reflects this iteration's evaluation
    _callback.order = 40
    _callback.bind_callbacks = _bind
    _callback.checkpoint_manager = mgr
    return _callback
