"""Host-side tree model: flat-array binary tree.

Reference: ``Tree`` (include/LightGBM/tree.h, src/io/tree.cpp, UNVERIFIED —
empty mount, see SURVEY.md banner): internal nodes in arrays of size
``num_leaves-1`` (split_feature, threshold, left/right child with ``~leaf``
encoding), leaves in arrays of size ``num_leaves``; both bin thresholds and
real-valued thresholds kept so prediction works on raw features.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class Tree:
    """One trained tree (host numpy; device stacking happens in predict)."""

    num_leaves: int
    split_feature: np.ndarray    # [num_leaves-1] int32 (used-feature index)
    threshold_bin: np.ndarray    # [num_leaves-1] int32
    threshold_real: np.ndarray   # [num_leaves-1] float64
    default_left: np.ndarray     # [num_leaves-1] bool
    left_child: np.ndarray       # [num_leaves-1] int32 (~leaf if negative)
    right_child: np.ndarray      # [num_leaves-1] int32
    split_gain: np.ndarray       # [num_leaves-1] float32
    internal_value: np.ndarray   # [num_leaves-1] float32
    internal_count: np.ndarray   # [num_leaves-1] int64
    leaf_value: np.ndarray       # [num_leaves] float64 (shrinkage applied)
    leaf_count: np.ndarray       # [num_leaves] int64
    leaf_weight: np.ndarray      # [num_leaves] float64
    shrinkage: float = 1.0
    # categorical split support (filled when cat splits exist):
    # value-level bitsets for raw-feature predict + model text (LightGBM
    # layout: threshold_real[i] = cat idx; cat_boundaries[idx:idx+1]
    # delimit this node's uint32 words in cat_threshold)
    cat_boundaries: Optional[np.ndarray] = None
    cat_threshold: Optional[np.ndarray] = None
    is_categorical: Optional[np.ndarray] = None
    # bin-level bitsets in the device layout ([nn, W] uint32) for
    # binned-matrix prediction (engine predict / score rebuild)
    cat_bitset_bins: Optional[np.ndarray] = None

    @property
    def num_nodes(self) -> int:
        return max(self.num_leaves - 1, 0)

    def shrink(self, rate: float) -> None:
        """Tree::Shrinkage — scale leaf outputs."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        self.shrinkage *= rate

    # ------------------------------------------------------------------
    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Predict on raw feature values (used features only)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, self.leaf_value[0] if len(self.leaf_value)
                           else 0.0)
        leaf = self._leaf_index_raw(X)
        if getattr(self, "is_linear", False):
            from .learner.linear import predict_linear
            return predict_linear(self, X, leaf)
        return self.leaf_value[leaf]

    def _cat_go_left(self, cat_idx: np.ndarray,
                     vals: np.ndarray) -> np.ndarray:
        """Vectorized category-value bitset membership (NaN/negative/
        unseen values miss the set and go right)."""
        ci = np.clip(cat_idx.astype(np.int64), 0,
                     len(self.cat_boundaries) - 2)
        start = self.cat_boundaries[ci]
        nw = self.cat_boundaries[ci + 1] - start
        iv = np.where(np.isfinite(vals) & (vals >= 0), vals, -1.0) \
            .astype(np.int64)
        w = iv >> 5
        ok = (iv >= 0) & (w < nw)
        word = self.cat_threshold[np.clip(start + w, 0,
                                          len(self.cat_threshold) - 1)]
        bit = (word >> (iv & 31).astype(np.uint32)) & np.uint32(1)
        return ok & (bit > 0)

    def _leaf_index_raw(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        node = np.zeros(n, dtype=np.int64)
        active = np.ones(n, dtype=bool) if self.num_leaves > 1 else \
            np.zeros(n, dtype=bool)
        out = np.zeros(n, dtype=np.int64)
        has_cat = (self.is_categorical is not None
                   and np.any(self.is_categorical))
        # per-node missing codes (0 none / 1 zero / 2 nan), attached by
        # HostModel; without them NaN takes the default direction
        nmt = getattr(self, "node_missing_type", None)
        for _ in range(self.num_nodes + 1):
            if not active.any():
                break
            nd = node[active]
            feat = self.split_feature[nd]
            vals = X[active, feat]
            thr = self.threshold_real[nd]
            dl = self.default_left[nd]
            miss = np.isnan(vals)
            if nmt is None:
                go_left = np.where(miss, dl, vals <= thr)
            else:
                # stock semantics per missing type: none converts NaN
                # to 0.0; zero routes |x|<=1e-35 (and NaN) by default
                # direction; nan routes NaN by default direction
                mtn = nmt[nd]
                v0 = np.where(miss, 0.0, vals)
                zeroish = miss | (np.abs(v0) <= 1e-35)
                go_left = np.where(
                    mtn == 2, np.where(miss, dl, vals <= thr),
                    np.where(mtn == 1,
                             np.where(zeroish, dl, v0 <= thr),
                             v0 <= thr))
            if has_cat:
                catn = self.is_categorical[nd]
                go_left = np.where(catn, self._cat_go_left(thr, vals),
                                   go_left)
            nxt = np.where(go_left, self.left_child[nd],
                           self.right_child[nd])
            at_leaf = nxt < 0
            idx = np.flatnonzero(active)
            out[idx[at_leaf]] = -nxt[at_leaf] - 1
            node[idx] = np.maximum(nxt, 0)
            new_active = active.copy()
            new_active[idx[at_leaf]] = False
            active = new_active
        return out

    def predict_leaf_raw(self, X: np.ndarray) -> np.ndarray:
        if self.num_leaves <= 1:
            return np.zeros(X.shape[0], dtype=np.int64)
        return self._leaf_index_raw(X)

    def leaf_depths(self) -> np.ndarray:
        """Depth of each leaf (for model text's leaf_depth field)."""
        depth = np.zeros(self.num_leaves, dtype=np.int64)
        if self.num_leaves <= 1:
            return depth
        node_depth = np.zeros(self.num_nodes, dtype=np.int64)
        for nd in range(self.num_nodes):
            for child in (self.left_child[nd], self.right_child[nd]):
                if child >= 0:
                    node_depth[child] = node_depth[nd] + 1
                else:
                    depth[-child - 1] = node_depth[nd] + 1
        return depth

    # ------------------------------------------------------------------
    @staticmethod
    def rebin(t: "Tree", bin_mappers, used_features: List[int]) -> "Tree":
        """Convert a loaded model tree (ORIGINAL feature indices, real
        thresholds, value-level cat bitsets) into engine form
        (used-feature indices, bin thresholds, bin-level bitsets) against
        a dataset's bin mappers — the training-continuation seam
        (GBDT::ResetTrainingData with existing models, gbdt.cpp). Exact
        when the dataset/binning match the original training run; bin
        resolution otherwise."""
        import dataclasses as _dc
        from .utils import log as _log
        pos = {f: i for i, f in enumerate(used_features)}
        nn = t.num_nodes
        sf = np.zeros(nn, dtype=np.int32)
        tb = np.zeros(nn, dtype=np.int32)
        is_cat = t.is_categorical
        # validate every split feature BEFORE any mapper access so the
        # user sees the clean fatal, not an IndexError
        for i in range(nn):
            f = int(t.split_feature[i])
            if f not in pos:
                _log.fatal(
                    f"Cannot continue training: the loaded model splits on "
                    f"feature {f}, which is unused (trivial) in the new "
                    f"training data")
            node_cat = bool(is_cat[i]) if is_cat is not None else False
            mapper_cat = bin_mappers[f].bin_type == "categorical"
            if node_cat != mapper_cat:
                _log.fatal(
                    f"Cannot continue training: the loaded model treats "
                    f"feature {f} as "
                    f"{'categorical' if node_cat else 'numerical'} but the "
                    f"new dataset binned it as "
                    f"{'categorical' if mapper_cat else 'numerical'} — "
                    f"pass the same categorical_feature list")
        cat_bs = None
        if is_cat is not None and np.any(is_cat[:nn]):
            maxW = max((bin_mappers[int(f)].num_bin + 31) // 32
                       for f in t.split_feature[:nn])
            cat_bs = np.zeros((nn, maxW), dtype=np.uint32)
        for i in range(nn):
            f = int(t.split_feature[i])
            sf[i] = pos[f]
            mapper = bin_mappers[f]
            if is_cat is not None and is_cat[i]:
                # value-level bitset -> bin-level bitset via cat->bin map
                ci = int(t.threshold_real[i])
                words = t.cat_threshold[
                    t.cat_boundaries[ci]:t.cat_boundaries[ci + 1]]
                bits = np.unpackbits(
                    np.ascontiguousarray(words).view(np.uint8),
                    bitorder="little")
                for v in np.flatnonzero(bits):
                    b = mapper.cat_to_bin.get(int(v), -1) \
                        if mapper.cat_to_bin is not None else -1
                    if b >= 0:
                        cat_bs[i, b >> 5] |= np.uint32(1) << np.uint32(
                            b & 31)
            else:
                tb[i] = mapper.value_to_bin(float(t.threshold_real[i]))
        out = Tree(**{fl.name: getattr(t, fl.name)
                      for fl in _dc.fields(Tree)})
        out.split_feature = sf
        out.threshold_bin = tb
        out.cat_bitset_bins = cat_bs
        if getattr(t, "is_linear", False):
            # linear leaf payload: feature indices original -> used
            # (path features are always split features, so validated)
            out.is_linear = True
            out.leaf_coeff = list(t.leaf_coeff)
            out.leaf_features = [[pos[f] for f in lf]
                                 for lf in t.leaf_features]
        return out

    @staticmethod
    def from_device(tree_arrays: Dict[str, np.ndarray], shrinkage: float,
                    bin_mappers, used_features: List[int]) -> "Tree":
        """Build from grow_tree's device output (already on host)."""
        nl = int(tree_arrays["num_leaves"])
        nn = max(nl - 1, 0)
        sf = np.asarray(tree_arrays["split_feature"])[:nn].astype(np.int32)
        tb = np.asarray(tree_arrays["threshold_bin"])[:nn].astype(np.int32)
        is_cat = None
        cat_bs = None
        cat_boundaries = None
        cat_threshold = None
        if "is_cat" in tree_arrays:
            is_cat = np.asarray(tree_arrays["is_cat"])[:nn].astype(bool)
            cat_bs = np.asarray(tree_arrays["cat_bitset"])[:nn] \
                .astype(np.uint32)
            if not is_cat.any():
                is_cat = None
                cat_bs = None
        tr = np.zeros(nn, dtype=np.float64)
        bounds = [0]
        words_all: list = []
        for i in range(nn):
            mapper = bin_mappers[used_features[int(sf[i])]]
            if is_cat is not None and is_cat[i]:
                # bin-level bitset -> category-VALUE bitset (LightGBM
                # stores the raw category values, bin.h CategoricalBin)
                bits = np.unpackbits(
                    np.ascontiguousarray(cat_bs[i]).view(np.uint8),
                    bitorder="little")
                nb = len(mapper.bin_to_cat)
                bins_in = np.flatnonzero(bits[:nb])
                cats = mapper.bin_to_cat[bins_in]
                cats = cats[cats >= 0]
                nwords = (int(cats.max()) >> 5) + 1 if len(cats) else 1
                words = np.zeros(nwords, dtype=np.uint32)
                for v in cats:
                    words[int(v) >> 5] |= np.uint32(1) << np.uint32(v & 31)
                tr[i] = float(len(bounds) - 1)   # cat split index
                words_all.extend(words)
                bounds.append(len(words_all))
            else:
                tr[i] = mapper.bin_to_threshold(int(tb[i]))
        if is_cat is not None:
            cat_boundaries = np.asarray(bounds, dtype=np.int64)
            cat_threshold = np.asarray(words_all, dtype=np.uint32)
        t = Tree(
            num_leaves=nl,
            split_feature=sf,
            threshold_bin=tb,
            threshold_real=tr,
            default_left=np.asarray(tree_arrays["default_left"])[:nn],
            left_child=np.asarray(tree_arrays["left_child"])[:nn]
            .astype(np.int32),
            right_child=np.asarray(tree_arrays["right_child"])[:nn]
            .astype(np.int32),
            split_gain=np.asarray(tree_arrays["split_gain"])[:nn],
            internal_value=np.asarray(tree_arrays["internal_value"])[:nn],
            internal_count=np.asarray(tree_arrays["internal_count"])[:nn]
            .astype(np.int64),
            leaf_value=np.asarray(tree_arrays["leaf_value"])[:nl]
            .astype(np.float64),
            leaf_count=np.asarray(tree_arrays["leaf_count"])[:nl]
            .astype(np.int64),
            leaf_weight=np.asarray(tree_arrays["leaf_weight"])[:nl]
            .astype(np.float64),
            cat_boundaries=cat_boundaries,
            cat_threshold=cat_threshold,
            is_categorical=is_cat,
            cat_bitset_bins=cat_bs,
        )
        t.shrink(shrinkage)
        return t
