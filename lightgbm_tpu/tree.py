"""Host-side tree model: flat-array binary tree.

Reference: ``Tree`` (include/LightGBM/tree.h, src/io/tree.cpp, UNVERIFIED —
empty mount, see SURVEY.md banner): internal nodes in arrays of size
``num_leaves-1`` (split_feature, threshold, left/right child with ``~leaf``
encoding), leaves in arrays of size ``num_leaves``; both bin thresholds and
real-valued thresholds kept so prediction works on raw features.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class Tree:
    """One trained tree (host numpy; device stacking happens in predict)."""

    num_leaves: int
    split_feature: np.ndarray    # [num_leaves-1] int32 (used-feature index)
    threshold_bin: np.ndarray    # [num_leaves-1] int32
    threshold_real: np.ndarray   # [num_leaves-1] float64
    default_left: np.ndarray     # [num_leaves-1] bool
    left_child: np.ndarray       # [num_leaves-1] int32 (~leaf if negative)
    right_child: np.ndarray      # [num_leaves-1] int32
    split_gain: np.ndarray       # [num_leaves-1] float32
    internal_value: np.ndarray   # [num_leaves-1] float32
    internal_count: np.ndarray   # [num_leaves-1] int64
    leaf_value: np.ndarray       # [num_leaves] float64 (shrinkage applied)
    leaf_count: np.ndarray       # [num_leaves] int64
    leaf_weight: np.ndarray      # [num_leaves] float64
    shrinkage: float = 1.0
    # categorical split support (filled when cat splits exist)
    cat_boundaries: Optional[np.ndarray] = None
    cat_threshold: Optional[np.ndarray] = None
    is_categorical: Optional[np.ndarray] = None

    @property
    def num_nodes(self) -> int:
        return max(self.num_leaves - 1, 0)

    def shrink(self, rate: float) -> None:
        """Tree::Shrinkage — scale leaf outputs."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        self.shrinkage *= rate

    # ------------------------------------------------------------------
    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Predict on raw feature values (used features only)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, self.leaf_value[0] if len(self.leaf_value)
                           else 0.0)
        leaf = self._leaf_index_raw(X)
        return self.leaf_value[leaf]

    def _leaf_index_raw(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        node = np.zeros(n, dtype=np.int64)
        active = np.ones(n, dtype=bool) if self.num_leaves > 1 else \
            np.zeros(n, dtype=bool)
        out = np.zeros(n, dtype=np.int64)
        for _ in range(self.num_nodes + 1):
            if not active.any():
                break
            nd = node[active]
            feat = self.split_feature[nd]
            vals = X[active, feat]
            thr = self.threshold_real[nd]
            dl = self.default_left[nd]
            miss = np.isnan(vals)
            go_left = np.where(miss, dl, vals <= thr)
            nxt = np.where(go_left, self.left_child[nd],
                           self.right_child[nd])
            at_leaf = nxt < 0
            idx = np.flatnonzero(active)
            out[idx[at_leaf]] = -nxt[at_leaf] - 1
            node[idx] = np.maximum(nxt, 0)
            new_active = active.copy()
            new_active[idx[at_leaf]] = False
            active = new_active
        return out

    def predict_leaf_raw(self, X: np.ndarray) -> np.ndarray:
        if self.num_leaves <= 1:
            return np.zeros(X.shape[0], dtype=np.int64)
        return self._leaf_index_raw(X)

    def leaf_depths(self) -> np.ndarray:
        """Depth of each leaf (for model text's leaf_depth field)."""
        depth = np.zeros(self.num_leaves, dtype=np.int64)
        if self.num_leaves <= 1:
            return depth
        node_depth = np.zeros(self.num_nodes, dtype=np.int64)
        for nd in range(self.num_nodes):
            for child in (self.left_child[nd], self.right_child[nd]):
                if child >= 0:
                    node_depth[child] = node_depth[nd] + 1
                else:
                    depth[-child - 1] = node_depth[nd] + 1
        return depth

    # ------------------------------------------------------------------
    @staticmethod
    def from_device(tree_arrays: Dict[str, np.ndarray], shrinkage: float,
                    bin_mappers, used_features: List[int]) -> "Tree":
        """Build from grow_tree's device output (already on host)."""
        nl = int(tree_arrays["num_leaves"])
        nn = max(nl - 1, 0)
        sf = np.asarray(tree_arrays["split_feature"])[:nn].astype(np.int32)
        tb = np.asarray(tree_arrays["threshold_bin"])[:nn].astype(np.int32)
        tr = np.zeros(nn, dtype=np.float64)
        for i in range(nn):
            mapper = bin_mappers[used_features[int(sf[i])]]
            tr[i] = mapper.bin_to_threshold(int(tb[i]))
        t = Tree(
            num_leaves=nl,
            split_feature=sf,
            threshold_bin=tb,
            threshold_real=tr,
            default_left=np.asarray(tree_arrays["default_left"])[:nn],
            left_child=np.asarray(tree_arrays["left_child"])[:nn]
            .astype(np.int32),
            right_child=np.asarray(tree_arrays["right_child"])[:nn]
            .astype(np.int32),
            split_gain=np.asarray(tree_arrays["split_gain"])[:nn],
            internal_value=np.asarray(tree_arrays["internal_value"])[:nn],
            internal_count=np.asarray(tree_arrays["internal_count"])[:nn]
            .astype(np.int64),
            leaf_value=np.asarray(tree_arrays["leaf_value"])[:nl]
            .astype(np.float64),
            leaf_count=np.asarray(tree_arrays["leaf_count"])[:nl]
            .astype(np.int64),
            leaf_weight=np.asarray(tree_arrays["leaf_weight"])[:nl]
            .astype(np.float64),
        )
        t.shrink(shrinkage)
        return t
