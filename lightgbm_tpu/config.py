"""Config system: typed parameters + LightGBM-compatible alias resolution.

Reference: include/LightGBM/config.h + src/io/config_auto.cpp (UNVERIFIED —
empty mount, see SURVEY.md banner). Upstream generates the alias/bounds
tables from docs/Parameters.rst via helpers/parameter_generator.py; here a
single declarative ``_PARAMS`` table is the source of truth, and the
``Config`` dataclass is populated from it. Parameters arrive as a dict of
``key -> value`` (value may be a string, as from CLI ``k=v`` pairs) and are
alias-resolved, type-coerced, and bound-checked centrally, matching
``Config::Set``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .utils import log

# ---------------------------------------------------------------------------
# Parameter table: name -> (type, default, aliases, (min, max) or None)
# Types: "int", "float", "bool", "str", "int_list", "float_list", "str_list"
# Alias lists follow upstream config_auto.cpp's alias table.
# ---------------------------------------------------------------------------
_P = lambda typ, default, aliases=(), bounds=None: (typ, default, tuple(aliases), bounds)

_PARAMS: Dict[str, Tuple[str, Any, Tuple[str, ...], Optional[Tuple[float, float]]]] = {
    # ---- Core parameters -------------------------------------------------
    "objective": _P("str", "regression",
                    ["objective_type", "app", "application", "loss"]),
    "boosting": _P("str", "gbdt", ["boosting_type", "boost"]),
    "data_sample_strategy": _P("str", "bagging"),
    "num_iterations": _P("int", 100,
                         ["num_iteration", "n_iter", "num_tree", "num_trees",
                          "num_round", "num_rounds", "nrounds",
                          "num_boost_round", "n_estimators", "max_iter"],
                         (0, 1 << 31)),
    "learning_rate": _P("float", 0.1, ["shrinkage_rate", "eta"], (0.0, None)),
    "num_leaves": _P("int", 31, ["num_leaf", "max_leaves", "max_leaf",
                                 "max_leaf_nodes"], (2, 131072)),
    "tree_learner": _P("str", "serial", ["tree", "tree_type",
                                         "tree_learner_type"]),
    "num_threads": _P("int", 0, ["num_thread", "nthread", "nthreads",
                                 "n_jobs"]),
    "device_type": _P("str", "tpu", ["device"]),
    "seed": _P("int", 0, ["random_seed", "random_state"]),
    "deterministic": _P("bool", False),
    # ---- Learning control ------------------------------------------------
    "force_col_wise": _P("bool", False),
    "force_row_wise": _P("bool", False),
    "histogram_pool_size": _P("float", -1.0, ["hist_pool_size"]),
    "max_depth": _P("int", -1),
    "min_data_in_leaf": _P("int", 20, ["min_data_per_leaf", "min_data",
                                       "min_child_samples",
                                       "min_samples_leaf"], (0, None)),
    "min_sum_hessian_in_leaf": _P("float", 1e-3,
                                  ["min_sum_hessian_per_leaf",
                                   "min_sum_hessian", "min_hessian",
                                   "min_child_weight"], (0.0, None)),
    "bagging_fraction": _P("float", 1.0, ["sub_row", "subsample", "bagging"],
                           (0.0, 1.0)),
    "pos_bagging_fraction": _P("float", 1.0, ["pos_sub_row", "pos_subsample",
                                              "pos_bagging"], (0.0, 1.0)),
    "neg_bagging_fraction": _P("float", 1.0, ["neg_sub_row", "neg_subsample",
                                              "neg_bagging"], (0.0, 1.0)),
    "bagging_freq": _P("int", 0, ["subsample_freq"]),
    "bagging_seed": _P("int", 3, ["bagging_fraction_seed"]),
    "feature_fraction": _P("float", 1.0, ["sub_feature", "colsample_bytree"],
                           (0.0, 1.0)),
    "feature_fraction_bynode": _P("float", 1.0,
                                  ["sub_feature_bynode",
                                   "colsample_bynode"], (0.0, 1.0)),
    "feature_fraction_seed": _P("int", 2),
    "extra_trees": _P("bool", False, ["extra_tree"]),
    "extra_seed": _P("int", 6),
    "early_stopping_round": _P("int", 0, ["early_stopping_rounds",
                                          "early_stopping",
                                          "n_iter_no_change"]),
    "early_stopping_min_delta": _P("float", 0.0, [], (0.0, None)),
    "first_metric_only": _P("bool", False),
    "max_delta_step": _P("float", 0.0, ["max_tree_output", "max_leaf_output"]),
    "lambda_l1": _P("float", 0.0, ["reg_alpha", "l1_regularization"],
                    (0.0, None)),
    "lambda_l2": _P("float", 0.0, ["reg_lambda", "lambda",
                                   "l2_regularization"], (0.0, None)),
    "linear_tree": _P("bool", False, ["linear_trees"]),
    "linear_lambda": _P("float", 0.0, [], (0.0, None)),
    "min_gain_to_split": _P("float", 0.0, ["min_split_gain"], (0.0, None)),
    "drop_rate": _P("float", 0.1, ["rate_drop"], (0.0, 1.0)),
    "max_drop": _P("int", 50),
    "skip_drop": _P("float", 0.5, [], (0.0, 1.0)),
    "xgboost_dart_mode": _P("bool", False),
    "uniform_drop": _P("bool", False),
    "drop_seed": _P("int", 4),
    "top_rate": _P("float", 0.2, [], (0.0, 1.0)),
    "other_rate": _P("float", 0.1, [], (0.0, 1.0)),
    "min_data_per_group": _P("int", 100, [], (1, None)),
    "max_cat_threshold": _P("int", 32, [], (1, None)),
    "cat_l2": _P("float", 10.0, [], (0.0, None)),
    "cat_smooth": _P("float", 10.0, [], (0.0, None)),
    "max_cat_to_onehot": _P("int", 4, [], (1, None)),
    "top_k": _P("int", 20, ["topk"], (1, None)),
    "monotone_constraints": _P("int_list", [], ["mc", "monotone_constraint",
                                                "monotonic_cst"]),
    "monotone_constraints_method": _P("str", "basic",
                                      ["monotone_constraining_method",
                                       "mc_method"]),
    "monotone_penalty": _P("float", 0.0, ["monotone_splits_penalty",
                                          "ms_penalty", "mc_penalty"],
                           (0.0, None)),
    "feature_contri": _P("float_list", [], ["feature_contrib", "fc",
                                            "fp", "feature_penalty"]),
    "forcedsplits_filename": _P("str", "", ["fs", "forced_splits_filename",
                                            "forced_splits_file",
                                            "forced_splits"]),
    "refit_decay_rate": _P("float", 0.9, [], (0.0, 1.0)),
    "cegb_tradeoff": _P("float", 1.0, [], (0.0, None)),
    "cegb_penalty_split": _P("float", 0.0, [], (0.0, None)),
    "cegb_penalty_feature_lazy": _P("float_list", []),
    "cegb_penalty_feature_coupled": _P("float_list", []),
    "path_smooth": _P("float", 0.0, [], (0.0, None)),
    "interaction_constraints": _P("str", ""),
    "verbosity": _P("int", 1, ["verbose"]),
    # ---- Dataset parameters ----------------------------------------------
    "max_bin": _P("int", 255, ["max_bins"], (2, None)),
    "max_bin_by_feature": _P("int_list", []),
    "min_data_in_bin": _P("int", 3, [], (1, None)),
    "bin_construct_sample_cnt": _P("int", 200000, ["subsample_for_bin"],
                                   (1, None)),
    "data_random_seed": _P("int", 1, ["data_seed"]),
    "is_enable_sparse": _P("bool", True, ["is_sparse", "enable_sparse",
                                          "sparse"]),
    "enable_bundle": _P("bool", True, ["is_enable_bundle", "bundle"]),
    "max_conflict_rate": _P("float", 0.0, [], (0.0, 1.0)),
    "use_missing": _P("bool", True),
    "zero_as_missing": _P("bool", False),
    "feature_pre_filter": _P("bool", True),
    "pre_partition": _P("bool", False, ["is_pre_partition"]),
    "two_round": _P("bool", False, ["two_round_loading",
                                    "use_two_round_loading"]),
    "header": _P("bool", False, ["has_header"]),
    "label_column": _P("str", "", ["label"]),
    "weight_column": _P("str", "", ["weight"]),
    "group_column": _P("str", "", ["group", "group_id", "query_column",
                                   "query", "query_id"]),
    "ignore_column": _P("str", "", ["ignore_feature", "blacklist"]),
    "categorical_feature": _P("str", "", ["cat_feature",
                                          "categorical_column",
                                          "cat_column",
                                          "categorical_features"]),
    "forcedbins_filename": _P("str", ""),
    "save_binary": _P("bool", False, ["is_save_binary",
                                      "is_save_binary_file"]),
    "precise_float_parser": _P("bool", False),
    "parser_config_file": _P("str", ""),
    # ---- Predict parameters ----------------------------------------------
    "start_iteration_predict": _P("int", 0),
    "num_iteration_predict": _P("int", -1),
    "predict_raw_score": _P("bool", False, ["is_predict_raw_score",
                                            "predict_rawscore",
                                            "raw_score"]),
    "predict_leaf_index": _P("bool", False, ["is_predict_leaf_index",
                                             "leaf_index"]),
    "predict_contrib": _P("bool", False, ["is_predict_contrib", "contrib"]),
    "predict_disable_shape_check": _P("bool", False),
    "pred_early_stop": _P("bool", False),
    "pred_early_stop_freq": _P("int", 10),
    "pred_early_stop_margin": _P("float", 10.0),
    # ---- Convert parameters ----------------------------------------------
    "convert_model_language": _P("str", ""),
    "convert_model": _P("str", "gbdt_prediction.cpp",
                        ["convert_model_file"]),
    # ---- Objective parameters --------------------------------------------
    "objective_seed": _P("int", 5),
    "num_class": _P("int", 1, ["num_classes"], (1, None)),
    "is_unbalance": _P("bool", False, ["unbalance", "unbalanced_sets"]),
    "scale_pos_weight": _P("float", 1.0, [], (0.0, None)),
    "sigmoid": _P("float", 1.0, [], (0.0, None)),
    "boost_from_average": _P("bool", True),
    "reg_sqrt": _P("bool", False),
    "alpha": _P("float", 0.9, [], (0.0, None)),
    "fair_c": _P("float", 1.0, [], (0.0, None)),
    "poisson_max_delta_step": _P("float", 0.7, [], (0.0, None)),
    "tweedie_variance_power": _P("float", 1.5, [], (1.0, 2.0)),
    "lambdarank_truncation_level": _P("int", 30, [], (1, None)),
    "lambdarank_norm": _P("bool", True),
    "label_gain": _P("float_list", []),
    # Position debiasing (rank_objective.hpp position_bias_; UNVERIFIED —
    # empty mount): the reference activates it automatically when the
    # dataset carries a `position` field; the propensity exponent is
    # 1/(1 + lambdarank_position_bias_regularization). We mirror that.
    # `lambdarank_unbiased` is an EXTENSION: force debiasing keyed on
    # score rank when no explicit position field exists.
    "lambdarank_unbiased": _P("bool", False),
    # -1 = derive the propensity exponent as 1/(1+regularization)
    # (reference semantics); >=0 overrides it directly (extension).
    "lambdarank_bias_p_norm": _P("float", -1.0, [], (-1.0, None)),
    "lambdarank_position_bias_regularization": _P("float", 0.0, [],
                                                  (0.0, None)),
    # ---- Metric parameters -----------------------------------------------
    "metric": _P("str_list", [], ["metrics", "metric_types"]),
    "metric_freq": _P("int", 1, ["output_freq"], (1, None)),
    "is_provide_training_metric": _P("bool", False,
                                     ["training_metric",
                                      "is_training_metric",
                                      "train_metric"]),
    "eval_at": _P("int_list", [1, 2, 3, 4, 5],
                  ["ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at"]),
    "multi_error_top_k": _P("int", 1, [], (1, None)),
    "auc_mu_weights": _P("float_list", []),
    # ---- Network parameters ----------------------------------------------
    "num_machines": _P("int", 1, ["num_machine"], (1, None)),
    "local_listen_port": _P("int", 12400, ["local_port", "port"]),
    "time_out": _P("int", 120, [], (1, None)),
    "machine_list_filename": _P("str", "", ["machine_list_file",
                                            "machine_list", "mlist"]),
    "machines": _P("str", "", ["workers", "nodes"]),
    # ---- GPU parameters (accepted for compatibility; TPU ignores) --------
    "gpu_platform_id": _P("int", -1),
    "gpu_device_id": _P("int", -1),
    "gpu_use_dp": _P("bool", False),
    "num_gpu": _P("int", 1, [], (1, None)),
    # ---- Quantized training ----------------------------------------------
    "use_quantized_grad": _P("bool", False),
    "num_grad_quant_bins": _P("int", 4),
    "quant_train_renew_leaf": _P("bool", False),
    "stochastic_rounding": _P("bool", True),
    # ---- IO / app --------------------------------------------------------
    "task": _P("str", "train", ["task_type"]),
    "data": _P("str", "", ["train", "train_data", "train_data_file",
                           "data_filename"]),
    "valid": _P("str_list", [], ["test", "valid_data", "valid_data_file",
                                 "test_data", "test_data_file",
                                 "valid_filenames"]),
    "input_model": _P("str", "", ["model_input", "model_in"]),
    "output_model": _P("str", "LightGBM_model.txt",
                       ["model_output", "model_out"]),
    "output_result": _P("str", "LightGBM_predict_result.txt",
                        ["predict_result", "prediction_result",
                         "predict_name", "prediction_name", "pred_name",
                         "name_pred"]),
    "snapshot_freq": _P("int", -1, ["save_period"]),
    "saved_feature_importance_type": _P("int", 0),
    # ---- Fault tolerance (recovery subsystem; docs/robustness.md) --------
    # directory for durable training checkpoints (atomic tmp+rename
    # writes, sha256-verified, bounded retention); resume with
    # lgb.train(..., resume_from=<dir>). Unlike snapshot_freq (model
    # text only), checkpoints persist the COMPLETE training state —
    # RNG streams, exact scores, early-stopping best-score state — so
    # an interrupted-then-resumed run is bit-exact.
    "checkpoint_dir": _P("str", ""),
    # iterations between checkpoints (0 = checkpointing off)
    "checkpoint_interval": _P("int", 0, ["checkpoint_freq"], (0, None)),
    # newest checkpoints kept per rank; older ones are pruned
    "checkpoint_keep": _P("int", 3, [], (1, None)),
    # fault injection for fault-tolerance CI: "kill:rank=1,iter=10"
    # SIGKILLs rank 1 before iteration 10; "exn:iter=5" raises. Fires
    # once per (spec, rank) when a marker dir is available (see
    # tpu_fault_marker). Empty = off.
    "tpu_fault_inject": _P("str", ""),
    # marker directory for fault fire-once bookkeeping (defaults to
    # checkpoint_dir when unset)
    "tpu_fault_marker": _P("str", ""),
    # elastic streamed resume (docs/robustness.md "Elastic topology"):
    # may import_train_state RE-CUT streamed per-(rank, block) score
    # slots onto a shard/block layout different from the one the
    # checkpoint was written under?  "auto" re-cuts only where the
    # continued training stays bit-exact (use_quantized_grad: integer
    # level sums are cut-invariant) and fatals otherwise; "true"
    # forces the re-cut on the exact-f32 path too (recompute with a
    # documented-divergence warning — f32 histogram sums reassociate
    # under the new cut); "false" pins the strict PR-13 contract
    # (any layout change on streamed resume is a hard error).
    # Eligibility is a capability-table verdict
    # (capabilities.stream_recut_verdict / STREAM_RECUT)
    "tpu_elastic_recut": _P("str", "auto"),
    # watchdog liveness: when set, the training round loop stamps a
    # per-rank heartbeat FILE (heartbeat.train.rank<r>) under this dir
    # (mtime = liveness; throttled to ~1 Hz). train_distributed sets it
    # on every worker when a heartbeat timeout is configured and KILLS
    # + relaunches a gang whose stamp goes stale past
    # tpu_heartbeat_timeout — a hung rank becomes the already-handled
    # crash case instead of wedging forever (docs/robustness.md)
    "tpu_heartbeat_dir": _P("str", ""),
    # serve-side hot-swap: a checkpoint DIRECTORY this Booster watches;
    # each predict polls the `latest` checkpoint pointer (throttled to
    # tpu_model_watch_interval seconds) and atomically swaps the new
    # model in — warm in-engine tree adoption (zero dropped requests,
    # zero recompiles under stable shapes), host-model fallback
    # otherwise. A corrupt/half-written checkpoint keeps the previous
    # model serving and flips the serve.model_stale gauge
    # (docs/robustness.md "Hot-swap serving")
    "tpu_model_watch": _P("str", ""),
    "tpu_model_watch_interval": _P("float", 2.0, [], (0.0, None)),
    # ---- TPU-specific (new; no reference analog) -------------------------
    "tpu_rows_per_block": _P("int", 4096),
    # buffer donation for the boosting carries (docs/perf.md "Iteration
    # floor"): the per-step / fused-chunk / valid-update / streamed
    # score jits donate their loop-state inputs
    # (jax.jit(donate_argnums=...)) so XLA updates the carry in place
    # instead of copying it through every dispatch. "auto" donates on
    # the TPU backend only (the measured waste lives there; CPU test
    # runs keep today's copy semantics), "true" forces donation on any
    # backend that supports it (the CPU bit-identity tests), "false"
    # disables it everywhere (the bench.py --no-donate A/B). Donated
    # buffers are DELETED at dispatch — a stale Python reference read
    # after the call is a bug; tpu_debug_checks names the donating
    # site, and the donation-discipline linter (tools/analyze) flags
    # the static shape of that mistake. Known-bad combo, refused with
    # a warning: "true" on a non-TPU backend while a persistent
    # compilation cache is configured — this jaxlib's CPU client
    # corrupts the heap executing donating executables reloaded from
    # the cache (docs/perf.md "Iteration floor").
    "tpu_donate": _P("str", "auto"),
    "tpu_mesh_shape": _P("str", ""),
    "tpu_double_precision_hist": _P("bool", False),
    # rows per streamed chunk for two_round out-of-core file loading.
    # Small chunks are legitimate (tests force multi-chunk streaming
    # over small files with a few hundred rows); the floor only guards
    # against order-of-magnitude typos like 5-for-5M, and the default
    # is tuned for parser throughput
    "tpu_stream_chunk_rows": _P("int", 500000, [], (100, None)),
    # leaves expanded per growth round; 1 = exact reference leaf-wise
    # order, larger batches fuse K leaf histograms into one data scan
    "tpu_leaf_batch": _P("int", 32, [], (1, 256)),
    "tpu_use_pallas": _P("bool", True),
    # GOSS histogram-only row compaction (default on): one sort moves
    # the sampled rows into a fixed-size buffer so HISTOGRAM scans
    # shrink to ~(top+other)*n rows (the reference's bag subsets rows
    # physically; the masked formulation scans everything with zero
    # weights); the full-row partition/score update stays masked.
    # Falls back to the masked path for meshes/EFB/linear trees/leaf
    # renewal objectives.
    "tpu_goss_compact": _P("bool", True),
    # boosting iterations fused into one device dispatch (lax.scan) when
    # the pure-jit path applies (no callbacks/valid sets/host bagging)
    "tpu_fuse_iters": _P("int", 40, [], (1, 1000)),
    # data-parallel histogram reduction: "scatter" (psum_scatter, each
    # device owns F/D features — the reference's ReduceScatter layout) or
    # "psum" (full replicated reduce)
    "tpu_hist_reduce": _P("str", "scatter"),
    # measured-default quantized training (VERDICT r4 item 2): turn on
    # use_quantized_grad automatically (in GBDT.__init__) when the
    # round-5 A/B's validated regime applies — >= 500k rows, gbdt
    # boosting, objective in {binary, regression, multiclass,
    # multiclassova, cross_entropy} — where it showed equal-or-better
    # holdout AUC at equal rounds with +18-36% throughput
    # (docs/perf.md "quantized by default"). Any explicit
    # use_quantized_grad setting wins; smaller data keeps exact f32
    # gradients (bit-compatibility with the reference's default path).
    "tpu_auto_quantize": _P("bool", True),
    # out-of-core training (boosting/streaming.py): "auto" streams when
    # the binned matrix would exceed ~60% of device HBM (the resident
    # engine fatals at 92%); "true" forces the streaming engine;
    # "false" always stays resident (and hits the HBM guard when too
    # big). With tree_learner=data the streamed path SHARDS rows over
    # the mesh (each rank streams only its own blocks; one packed
    # collective per tree level — docs/perf.md "Streamed x sharded"),
    # and auto engages when the PER-RANK shard would still exceed the
    # budget. Streaming supports single-output objectives on numerical
    # features, incl. bagging/GOSS/quantized gradients — see
    # StreamingGBDT's docstring for the full contract.
    "tpu_streaming": _P("str", "auto"),
    # rows per streamed block (0 = auto: ~256 MB of binned data);
    # applies per RANK under sharded streaming — a rank whose row
    # range would yield zero blocks fatals at construction
    "tpu_stream_block_rows": _P("int", 0),
    # communication/compute overlap on the streamed hot path
    # (docs/perf.md "Communication/compute overlap"): "auto"/"true"
    # stages the next block's host->device upload on a worker thread
    # while the device sweeps the current one, dispatches the
    # per-level histogram collective without a blocking host sync,
    # and lets the round-end score sweep drain behind the next
    # round's first level sweep; "false" restores fully synchronous
    # per-block dispatch (the A/B arm). Bit-identical either way BY
    # CONSTRUCTION — accumulation order, reduce payloads and score
    # arithmetic are unchanged; only where the HOST blocks moves.
    # Checkpoint exports drain pending updates first in both modes.
    "tpu_stream_overlap": _P("str", "auto"),
    # quantized-histogram collective wire: pack each (g,h) level-sum
    # pair into one int32 (g high 16 bits, h low 16) so the psum /
    # psum_scatter payload drops to 2/3 (docs/perf.md packed-wire
    # design; shared helper learner/collective.py — the resident
    # data-parallel learner AND the sharded streaming engine both
    # reduce through it). Exact: a per-round guard psum bounds the
    # global level sums and falls back to the f32 reduce on any
    # overflow risk or negative hessian. No effect without
    # use_quantized_grad + a mesh.
    "tpu_hist_packed_wire": _P("bool", True),
    # per-iteration finite checks on tree outputs/scores (the aux
    # NaN-guard subsystem; costs a host sync per iteration)
    "tpu_debug_checks": _P("bool", False),
    # checkify-based ON-DEVICE validation (SURVEY.md §5 sanitizer
    # analog): each iteration, a jitted jax.experimental.checkify pass
    # validates scores and the objective's gradients/hessians
    # (finite, hessians non-negative) and surfaces the FIRST failure
    # with iteration context instead of silently training NaN trees
    "tpu_debug": _P("bool", False),
    # when set, wrap training in a jax.profiler trace (view with
    # TensorBoard / xprof) — the §5 tracing subsystem; the reference's
    # analog is the global function timers + GPU_DEBUG timing
    "tpu_profile_dir": _P("str", ""),
    # ---- observability subsystem (lightgbm_tpu/obs/;
    # docs/observability.md) -------------------------------------------
    # structured metrics: per-round phase timers, predict latency
    # histograms, cache-hit counters, compile/HBM gauges — read them
    # via Booster.metrics(), tpu_metrics_dump, or task=dump_metrics.
    # Off by default (~zero overhead off; <3% on when enabled)
    "tpu_metrics": _P("bool", False),
    # host-span tracing: write a Chrome-trace JSON (open in Perfetto /
    # chrome://tracing) of the nested obs spans — round loop, predict
    # chunks, ingest streaming, checkpoint writes — to this directory
    # at the end of training. Complements tpu_profile_dir (device-side
    # xprof) with the host orchestration view
    "tpu_trace_dir": _P("str", ""),
    # append one JSONL metrics-snapshot line to this path when
    # training finishes (implies tpu_metrics); the same schema
    # bench.py --metrics-json and scripts/check.sh consume
    "tpu_metrics_dump": _P("str", ""),
    # ---- active observability plane (obs/slo.py, obs/server.py,
    # obs/aggregate.py; docs/observability.md) -------------------------
    # live metrics endpoint: serve GET /metrics (Prometheus text),
    # /metrics.json, /healthz and /readyz on 127.0.0.1:<port> from a
    # background daemon thread (implies tpu_metrics + windowed SLOs).
    # 0 = off. Binds localhost ONLY; a port already in use warns and
    # disables the endpoint instead of crashing the run
    "tpu_metrics_port": _P("int", 0, [], (0, 65535)),
    # rolling-SLI window for the slo.* gauges (seconds; ring of 30
    # time buckets). Process-global once the tracker starts
    "tpu_slo_window_s": _P("float", 0.0, [], (0.0, None)),
    # SLO thresholds (0 = gauge-only, no threshold): a rolling predict
    # p99 above tpu_slo_predict_p99_ms (milliseconds), or a windowed
    # predict error ratio above tpu_slo_error_ratio, flips the
    # slo.breached{slo=...} gauge to 1 and counts the transition in
    # slo.breaches{slo=...}
    "tpu_slo_predict_p99_ms": _P("float", 0.0, [], (0.0, None)),
    "tpu_slo_error_ratio": _P("float", 0.0, [], (0.0, 1.0)),
    # /healthz + /readyz staleness: a heartbeat.train / heartbeat.serve
    # gauge older than this many seconds reads as a wedged loop -> 503
    # (0 = the 60 s default)
    "tpu_heartbeat_timeout": _P("float", 0.0, [], (0.0, None)),
    # per-rank metrics aggregation for train_distributed gangs: each
    # worker appends its end-of-run snapshot to
    # <dir>/rank_<r>.jsonl (implies tpu_metrics) and the driver merges
    # them into <dir>/merged.jsonl — counters sum, gauges keep latest,
    # histograms bucket-add — plus the dist.round_time_spread
    # straggler gauge (docs/observability.md)
    "tpu_metrics_rank_dir": _P("str", ""),
    # ---- serving fast path (ops/predict.py + GBDT.predict) -----------
    # level-synchronous tree-parallel forest traversal: all T trees
    # advance one level per step as one batched MXU contraction (or a
    # batched gather off-TPU / for very wide trees) instead of a
    # per-tree lax.scan — O(max_depth) steps instead of O(T*depth).
    # false = the legacy per-tree scan (bit-identical outputs either
    # way; tests/test_predict_engine.py pins it)
    "tpu_predict_parallel_trees": _P("bool", True),
    # pad predict batches up to power-of-two row buckets so arbitrary
    # request sizes hit a BOUNDED traversal compile cache; padded rows
    # are dropped before returning (results unchanged)
    "tpu_predict_buckets": _P("bool", True),
    # rows per device chunk for large scoring jobs: bigger requests
    # stream in fixed-size chunks (one compiled shape) with
    # double-buffered async device->host copies
    "tpu_predict_chunk_rows": _P("int", 65536, [], (1024, None)),
    # stacked-forest device cache: memoize contiguous tree-range stacks
    # on the engine so repeat predict calls on an unchanged model skip
    # host re-stacking and HBM re-upload entirely (invalidated on any
    # model mutation)
    "tpu_predict_cache": _P("bool", True),
    # ---- serving service (lightgbm_tpu/serve/; docs/serving.md) ------
    # adaptive micro-batching latency budget: the dispatch loop
    # coalesces concurrent submit() requests for one model until the
    # OLDEST request has waited this many milliseconds (or the batch
    # row cap below fills), then dispatches them as one bucketed
    # predict. 0 = dispatch immediately (no coalescing window)
    "tpu_serve_batch_budget_ms": _P("float", 5.0, [], (0.0, None)),
    # row cap per coalesced dispatch: a batch flushes early the moment
    # its accumulated rows reach this cap (requests larger than the cap
    # still dispatch alone — the engine chunks them internally)
    "tpu_serve_max_batch_rows": _P("int", 8192, [], (128, None)),
    # multi-model LRU (serve/registry.py): how many tenants' stacked
    # forests may be device-resident at once; the least-recently-used
    # model's device stack is released past the cap (the Booster stays
    # registered — the next request re-stacks, compiling nothing)
    "tpu_serve_cache_models": _P("int", 8, [], (1, None)),
    # byte cap for the same LRU, against the shared utils/hbm.py
    # stacked-forest estimate. 0 = auto: SERVE_HBM_FRACTION of the
    # device HBM limit where the runtime reports one, uncapped
    # otherwise
    "tpu_serve_cache_bytes": _P("int", 0, [], (0, None)),
    # tree-sharded predict (serve/shard.py): shard the stacked [T,...]
    # forest axis over the local mesh with NamedSharding for forests
    # too large for one device's HBM. "auto" engages when one model's
    # stacked estimate exceeds SERVE_HBM_FRACTION of a device; "true"
    # forces it whenever >= 2 local devices exist; "false" never.
    # Host-model (linear_tree, streaming) and DART predicts demote to
    # the unsharded path per capabilities.SHARDED_PREDICT
    "tpu_serve_shard_trees": _P("str", "auto"),
    # ---- device-accelerated ingest (ops/ingest.py; docs/perf.md
    # "Ingest") -------------------------------------------------------
    # bin ASSIGNMENT of the full raw matrix on the accelerator (bin
    # boundary FINDING stays host-side on the sample): "auto" takes the
    # device path on a TPU backend for dense numeric input; "true"
    # forces it on any backend (what the bit-equality tests do);
    # "false" keeps the host binning loop. The device path is
    # bit-identical to the host path for every float32-representable
    # value (ops/ingest.py's exclusive-f32 boundary trick); genuinely-
    # float64 values within half an f32 ulp of a bin edge may land one
    # bin off — set "false" for strict f64 edge semantics.
    "tpu_ingest_device": _P("str", "auto"),
    # raw rows per streamed H2D ingest chunk (every chunk the same
    # padded shape -> the assignment kernel compiles once)
    "tpu_ingest_chunk_rows": _P("int", 262144, [], (4096, None)),
    # host-fallback binning threads for the per-column numpy loop
    # (0 = auto: one per core, capped); only engages on large matrices
    "tpu_ingest_threads": _P("int", 0, [], (0, 256)),
    # persistent XLA compilation cache directory (jax
    # jax_compilation_cache_dir): warm-start repeat jobs so the second
    # construct+engine-init of the same shape compiles ZERO programs
    # (production retrains pay cold compiles on every job otherwise)
    "tpu_compile_cache_dir": _P("str", ""),
    # leaf-histogram storage: "pool" keeps the [L+1, F, B, 3] carry and
    # derives siblings by subtraction (the reference's HistogramPool);
    # "rebuild" computes BOTH children per round in one scan — the masks
    # pack into the matmul N dim, so the second child rides the MXU's
    # 128-lane padding — bounding memory to O(leaf_batch * F * B)
    "tpu_hist_mode": _P("str", "pool"),
    # leaf-ordered device row partition (ops/partition.py): rows ride
    # the grow-loop carry physically grouped by leaf, and each round's
    # histogram scans only the elected children's padded row spans
    # (pow2-bucketed budgets; siblings by pool subtraction) instead of
    # a masked full scan — the reference CUDADataPartition's "fewer
    # rows" lever. Trees are structurally identical to the masked path
    # (bit-exact under use_quantized_grad). "auto" engages where the
    # repartition move pays for itself (Pallas pool path, large
    # un-compacted source); "true" forces it wherever the move
    # machinery exists; "false" keeps masked full scans.
    "tpu_hist_partition": _P("str", "auto"),
}

def parse_interaction_constraints(spec) -> List[List[int]]:
    """Parse interaction_constraints: ``"[0,1,2],[2,3]"`` (reference CLI
    form), a Python list of lists, or its str() — into feature-index
    groups."""
    if spec is None or spec == "" or spec == []:
        return []
    if isinstance(spec, (list, tuple)):
        return [[int(f) for f in grp] for grp in spec]
    import re
    return [[int(x) for x in grp.replace(" ", "").split(",") if x != ""]
            for grp in re.findall(r"\[([\d,\s]*)\]", str(spec))]


# alias -> canonical name
_ALIASES: Dict[str, str] = {}
for _name, (_t, _d, _al, _b) in _PARAMS.items():
    for _a in _al:
        _ALIASES[_a] = _name
del _name, _t, _d, _al, _b

_TRUE_STRINGS = {"true", "1", "t", "yes", "y", "+", "on"}
_FALSE_STRINGS = {"false", "0", "f", "no", "n", "-", "off"}

# Parameters accepted for upstream compatibility but NOT acted on:
# setting a NON-DEFAULT value warns once per distinct (name, value) —
# a fresh run with a DIFFERENT value re-warns, while the 2-3 Config
# objects one train() call builds from the same params don't repeat it
# (never silently ignored — reference parity per config_auto.cpp is
# "every documented param acts"; tests/test_param_audit.py asserts this
# table + source references cover the whole _PARAMS table).
# name -> what's missing.
UNIMPLEMENTED_PARAMS: Dict[str, str] = {
    "parser_config_file": "custom text-parser plugins are not supported",
}
_WARNED_PARAM_VALUES: set = set()

# Parameters whose upstream effect legitimately DISSOLVES on this
# backend: they are implementation/performance hints whose correct
# TPU/XLA behavior is "no action" — accepted silently (warning on every
# config that sets n_jobs would be pure noise). name -> why it
# dissolves. The audit test requires every _PARAMS entry to be either
# consumed in source, warned-on (UNIMPLEMENTED_PARAMS), or listed here.
DISSOLVED_PARAMS: Dict[str, str] = {
    "num_threads": "no host thread pool; XLA owns device parallelism",
    "force_col_wise": "histogram layout is fixed by the TPU kernel "
                      "(feature-major bins_t + row-major bins)",
    "force_row_wise": "same as force_col_wise",
    "histogram_pool_size": "the histogram pool is a device array sized "
                           "by num_leaves (tpu_hist_mode picks "
                           "pool/rebuild); no LRU cache to bound",
    "is_enable_sparse": "sparse inputs are binned column-wise natively; "
                        "there is no dense/sparse bin representation "
                        "switch",
    "feature_pre_filter": "an upstream binning-time optimization "
                          "(pre-dropping features that cannot satisfy "
                          "min_data_in_leaf); the split search enforces "
                          "min_data_in_leaf exactly",
    "precise_float_parser": "numpy's float parser is already "
                            "round-trip precise",
    "pre_partition": "row sharding is derived from the mesh, not "
                     "pre-partitioned input files",
    "num_machines": "the host set comes from jax.distributed, not a "
                    "machine count param",
    "time_out": "socket timeouts have no analog; collectives are "
                "compiled XLA ops",
    "machine_list_filename": "host discovery via jax.distributed "
                             "coordinator, not a machine list file",
    "machines": "same as machine_list_filename",
    "local_listen_port": "no sockets; ICI/DCN transport is managed by "
                         "the runtime",
    "gpu_platform_id": "GPU-only knob; this is the TPU backend",
    "gpu_device_id": "GPU-only knob; this is the TPU backend",
    "gpu_use_dp": "GPU-only knob (tpu_double_precision_hist is the "
                  "analog here)",
    "num_gpu": "GPU-only knob (mesh size is the analog)",
    "deterministic": "runs are deterministic by construction (counter-"
                     "based RNG keys, fixed reduction orders per "
                     "backend)",
    "save_binary": "CLI task=save_binary / Dataset.save_binary cover "
                   "this; the load-time side effect flag is not needed",
}

_OBJECTIVE_ALIASES = {
    # objective-name aliases, per src/objective/objective_function.cpp
    "regression": "regression", "regression_l2": "regression",
    "l2": "regression", "mean_squared_error": "regression",
    "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary", "binary_logloss": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg", "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg", "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "custom": "custom", "none": "custom", "null": "custom", "na": "custom",
}


def _coerce(name: str, typ: str, value: Any) -> Any:
    """Coerce a raw (possibly string) value to the declared type."""
    if typ == "int":
        if isinstance(value, bool):
            return int(value)
        return int(float(value))  # "1e3" style strings work, as in upstream
    if typ == "float":
        return float(value)
    if typ == "bool":
        if isinstance(value, bool):
            return value
        if isinstance(value, (int, float)):
            return bool(value)
        s = str(value).strip().lower()
        if s in _TRUE_STRINGS:
            return True
        if s in _FALSE_STRINGS:
            return False
        log.fatal(f'Parameter "{name}": cannot parse bool from "{value}"')
    if typ == "str":
        return str(value)
    if typ in ("int_list", "float_list", "str_list"):
        elem = {"int_list": int, "float_list": float, "str_list": str}[typ]
        if isinstance(value, str):
            value = [v for v in value.replace(",", " ").split() if v]
        elif not isinstance(value, (list, tuple)):
            value = [value]
        return [elem(v) for v in value]
    raise AssertionError(f"unknown param type {typ}")


def _check_bounds(name: str, value: Any, bounds) -> None:
    if bounds is None or not isinstance(value, (int, float)):
        return
    lo, hi = bounds
    if lo is not None and value < lo:
        log.fatal(f'Parameter "{name}"={value} should be >= {lo}')
    if hi is not None and value > hi:
        log.fatal(f'Parameter "{name}"={value} should be <= {hi}')


@dataclasses.dataclass
class Config:
    """Resolved, typed parameter set (mirrors LightGBM's ``Config`` struct)."""

    # populated dynamically from _PARAMS in __init__
    def __init__(self, params: Optional[Dict[str, Any]] = None, **kwargs):
        merged: Dict[str, Any] = dict(params or {})
        merged.update(kwargs)
        for name, (typ, default, _aliases, _bounds) in _PARAMS.items():
            setattr(self, name, list(default) if isinstance(default, list)
                    else default)
        self.raw_params: Dict[str, Any] = {}
        self.update(merged)

    def update(self, params: Dict[str, Any]) -> None:
        """Alias-resolve, coerce, bound-check and apply ``params``."""
        resolved: Dict[str, Any] = {}
        for key, value in params.items():
            canonical = _ALIASES.get(key, key)
            if canonical in resolved and resolved[canonical] != value:
                log.warning(
                    f"Parameter {key} (alias of {canonical}) set multiple "
                    f"times; using {resolved[canonical]}")
                continue
            resolved[canonical] = value
        for name, value in resolved.items():
            if value is None:
                continue
            if name not in _PARAMS:
                # unknown params pass through silently like upstream's
                # pass-through of unrecognized keys to Dataset/predict configs
                self.raw_params[name] = value
                continue
            typ, _default, _aliases, bounds = _PARAMS[name]
            coerced = _coerce(name, typ, value)
            _check_bounds(name, coerced, bounds)
            setattr(self, name, coerced)
            self.raw_params[name] = coerced
        self._post_process()

    def _post_process(self) -> None:
        """Cross-parameter fixups, mirroring Config::CheckParamConflict."""
        obj = str(self.objective).lower()
        if obj in _OBJECTIVE_ALIASES:
            self.objective = _OBJECTIVE_ALIASES[obj]
        boosting_aliases = {"gbdt": "gbdt", "gbrt": "gbdt", "dart": "dart",
                            "rf": "rf", "random_forest": "rf", "goss": "goss"}
        b = str(self.boosting).lower()
        if b in boosting_aliases:
            self.boosting = boosting_aliases[b]
        if self.boosting == "goss":
            # upstream maps boosting=goss -> gbdt + data_sample_strategy=goss
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        # tpu_auto_quantize's actual switch lives in GBDT.__init__ —
        # the validated policy is size-gated (>= 500k rows, where the
        # A/B measured it), and row count is unknown here
        self._quantize_auto = False
        learner_aliases = {"serial": "serial", "feature": "feature",
                           "feature_parallel": "feature", "data": "data",
                           "data_parallel": "data", "voting": "voting",
                           "voting_parallel": "voting"}
        tl = str(self.tree_learner).lower()
        if tl not in learner_aliases:
            log.fatal(f"Unknown tree learner type {self.tree_learner}")
        self.tree_learner = learner_aliases[tl]
        if str(self.tpu_hist_reduce) not in ("scatter", "psum"):
            log.fatal(f"Unknown tpu_hist_reduce {self.tpu_hist_reduce!r} "
                      f"(expected 'scatter' or 'psum')")
        if str(self.tpu_hist_mode) not in ("pool", "rebuild"):
            log.fatal(f"Unknown tpu_hist_mode {self.tpu_hist_mode!r} "
                      f"(expected 'pool' or 'rebuild')")
        self.tpu_streaming = coerce_tristate(self.tpu_streaming,
                                             "tpu_streaming")
        self.tpu_stream_overlap = coerce_tristate(self.tpu_stream_overlap,
                                                  "tpu_stream_overlap")
        self.tpu_donate = coerce_tristate(self.tpu_donate, "tpu_donate")
        self.tpu_ingest_device = coerce_tristate(self.tpu_ingest_device,
                                                 "tpu_ingest_device")
        self.tpu_hist_partition = coerce_tristate(self.tpu_hist_partition,
                                                  "tpu_hist_partition")
        self.tpu_serve_shard_trees = coerce_tristate(
            self.tpu_serve_shard_trees, "tpu_serve_shard_trees")
        self.tpu_elastic_recut = coerce_tristate(self.tpu_elastic_recut,
                                                 "tpu_elastic_recut")
        setup_compile_cache(self.tpu_compile_cache_dir)
        # observability knobs engage process-wide (enable-only: the 2-3
        # Config objects one train() builds must not flip it back off)
        from . import obs
        obs.configure_from_config(self)
        for m in (self.monotone_constraints or []):
            if int(m) not in (-1, 0, 1):
                log.fatal("monotone_constraints must be -1, 0 or 1, "
                          f"got {m}")
        tms = str(self.tpu_mesh_shape).strip()
        if tms:
            try:
                nd = int(tms)
            except ValueError:
                log.fatal(f"tpu_mesh_shape must be a device count, got "
                          f"{tms!r} (N-d mesh shapes like '2x4' are not "
                          f"supported yet)")
            else:
                if nd < 1:
                    log.fatal(f"tpu_mesh_shape must be >= 1, got {nd}")
        mcm = str(self.monotone_constraints_method).lower()
        if mcm not in ("basic", "intermediate", "advanced"):
            log.fatal(f"Unknown monotone_constraints_method {mcm!r}")
        dev = str(self.device_type).lower()
        # cpu/gpu/cuda requests run on the TPU/XLA backend here
        if dev in ("cpu", "gpu", "cuda"):
            self.device_type = "tpu"
        log.set_verbosity(self.verbosity)
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            log.fatal("Cannot set is_unbalance and scale_pos_weight at the "
                      "same time")
        for name, detail in UNIMPLEMENTED_PARAMS.items():
            _t, default, _a, _b = _PARAMS[name]
            val = getattr(self, name)
            dedup_key = (name, repr(val))
            if (name in self.raw_params and val != default
                    and dedup_key not in _WARNED_PARAM_VALUES):
                _WARNED_PARAM_VALUES.add(dedup_key)
                log.warning(f"{name} is accepted but not implemented "
                            f"({detail}); the setting has no effect")

    # -- helpers used across the framework ---------------------------------
    @property
    def num_tree_per_iteration(self) -> int:
        from .capabilities import MULTI_TREE_OBJECTIVES
        if self.objective in MULTI_TREE_OBJECTIVES:
            return max(1, self.num_class)
        return 1

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in _PARAMS}

    @staticmethod
    def canonical_name(key: str) -> str:
        return _ALIASES.get(key, key)

    @staticmethod
    def param_names() -> List[str]:
        return list(_PARAMS)


def coerce_bool(value: Any) -> bool:
    """Public string-aware bool coercion ('false'/'0'/'off' are False)."""
    return _coerce("<bool>", "bool", value)


_MISSING = object()


def get_param(params: Dict[str, Any], name: str,
              default: Any = _MISSING) -> Any:
    """Alias-resolved, type-coerced, bound-checked read of ONE declared
    parameter from a raw params dict — the sanctioned accessor for
    dict-shaped reads outside ``Config`` (``Dataset.params``, the
    launcher's user params). The config-knob-drift checker
    (``python -m tools.analyze``; docs/static-analysis.md) flags raw
    ``params.get("tpu_...")`` reads, which re-encode each knob's
    default/coercion inline and rot when the declaration moves.

    An absent (or ``None``) knob returns the ``_PARAMS``-declared
    default — pass ``default=`` only to override that (e.g. a
    caller-level kwarg taking precedence)."""
    if name not in _PARAMS:
        log.fatal(f"get_param: {name!r} is not a declared parameter")
    typ, declared, _aliases, bounds = _PARAMS[name]
    value = params.get(name, _MISSING)
    if value is _MISSING:
        for key, v in params.items():
            if _ALIASES.get(key, key) == name:
                value = v
                break
    if value is _MISSING or value is None:
        if default is not _MISSING:
            return default
        return list(declared) if isinstance(declared, list) else declared
    coerced = _coerce(name, typ, value)
    _check_bounds(name, coerced, bounds)
    return coerced


_TRISTATE_VALUES = {"true": "true", "1": "true", "on": "true",
                    "yes": "true",
                    "false": "false", "0": "false", "off": "false",
                    "no": "false",
                    "auto": "auto"}


def coerce_tristate(value: Any, name: str = "parameter") -> str:
    """Normalize an auto/true/false knob to its canonical spelling,
    accepting the same bool spellings coerce_bool does ('on'/'1'/'yes',
    'off'/'0'/'no') — Config validation and Dataset-side param reads
    share this one accept-list."""
    v = _TRISTATE_VALUES.get(str(value).strip().lower())
    if v is None:
        log.fatal(f"Unknown {name} {value!r} (expected 'auto', "
                  f"'true'/'1'/'on'/'yes' or 'false'/'0'/'off'/'no')")
    return v


# the one directory the persistent compile cache is pointed at; set-once
# per process (jax's cache is a process-global — flipping it mid-run
# would silently split the cache)
_COMPILE_CACHE_DIR: Optional[str] = None


def setup_compile_cache(path) -> None:
    """Point jax's persistent compilation cache at ``path`` (the
    ``tpu_compile_cache_dir`` warm-start knob): a second same-shape run
    in a fresh process reloads every XLA program from disk instead of
    recompiling, collapsing cold-start ``engine_init_s`` /
    first-iteration compile time. Idempotent; an empty path is a no-op;
    a second DIFFERENT path warns and keeps the first (the cache dir is
    process-global in jax)."""
    global _COMPILE_CACHE_DIR
    path = str(path or "").strip()
    if not path:
        return
    if _COMPILE_CACHE_DIR is not None:
        if _COMPILE_CACHE_DIR != path:
            log.warning(
                f"tpu_compile_cache_dir={path!r} ignored: the persistent "
                f"compile cache is already at {_COMPILE_CACHE_DIR!r} "
                f"(process-global; restart to move it)")
        return
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception as e:    # older jax without this config name
        log.warning(f"tpu_compile_cache_dir: persistent compilation "
                    f"cache unavailable on this jax ({e})")
        return
    # the cache is LIVE from here: record it before the optional tuning
    # below, so a partial failure can never leave an active cache that
    # a later different path would silently re-point
    _COMPILE_CACHE_DIR = path
    try:
        # cache even quick compiles: the warm-start contract is "second
        # run compiles nothing", not "second run compiles only the big
        # ones" — and entry write cost is trivial next to any compile
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    except Exception as e:    # tuning knobs absent: cache still works
        log.warning(f"tpu_compile_cache_dir: cache enabled but "
                    f"min-compile-time/entry-size tuning unavailable "
                    f"({e}); small programs may not be cached")


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse a reference-style config FILE (k=v lines, '#' comments)."""
    with open(path) as f:
        return parse_config_str(f.read())


def parse_config_str(text: str) -> Dict[str, str]:
    """Parse CLI-style ``key=value`` lines (config file format)."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line or "=" not in line:
            continue
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip()
    return out
