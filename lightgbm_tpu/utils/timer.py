"""Phase timers + profiler hooks.

Reference: the reference's global timer (include/LightGBM/utils/log.h
CHECK/timer macros + `Log::Debug` per-phase timings, UNVERIFIED — empty
mount, see SURVEY.md banner). TPU-side, deep kernel profiling belongs to
``jax.profiler`` (trace viewer / xprof); these wall-clock phase timers
cover the host orchestration the profiler doesn't attribute.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

from . import log

_ACCUM: Dict[str, float] = defaultdict(float)
_COUNT: Dict[str, int] = defaultdict(int)


@contextlib.contextmanager
def timed(name: str) -> Iterator[None]:
    """Accumulate wall time under ``name`` (nestable)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _ACCUM[name] += time.perf_counter() - t0
        _COUNT[name] += 1


def timer_totals() -> Dict[str, float]:
    return dict(_ACCUM)


def reset_timers() -> None:
    _ACCUM.clear()
    _COUNT.clear()


def log_timers() -> None:
    """Debug-log accumulated phase times (the reference prints its
    global timer table at shutdown in debug builds)."""
    for name in sorted(_ACCUM, key=lambda k: -_ACCUM[k]):
        log.debug(f"{name}: {_ACCUM[name]:.3f}s "
                  f"({_COUNT[name]} calls)")


def start_trace(log_dir: str) -> None:
    """Begin a jax.profiler trace (view with TensorBoard/xprof)."""
    import jax
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    import jax
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Trace a block when ``log_dir`` is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()
