"""Back-compat shim over the observability subsystem + profiler hooks.

The phase-timer implementation that used to live here (its own
``_ACCUM``/``_COUNT`` dicts on ``perf_counter``) is gone: the obs
subsystem's span histograms are the one clock and one format
(``lightgbm_tpu/obs``, docs/observability.md). ``timed(name)`` now IS
``obs.span(name, force=True)`` — forced, because a caller reaching for
an explicit timer has asked for a measurement regardless of the global
``tpu_metrics`` gate — and the totals/log helpers read the registry's
histograms.

Reference lineage unchanged: the reference's global timer macros
(include/LightGBM/utils/log.h, UNVERIFIED — empty mount, see SURVEY.md
banner) printing per-phase timings in debug builds.

The ``jax.profiler`` hooks (deep device-side kernel traces for
TensorBoard/xprof via ``tpu_profile_dir``) still live here; obs spans
cover the HOST orchestration the device profiler does not attribute.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Iterator, Optional

from . import log


def timed(name: str):
    """Accumulate wall time under ``name`` (nestable). Records into the
    obs histogram of the same name (always — see module docstring) and,
    when tracing is on, a Chrome-trace span."""
    from .. import obs
    return obs.span(name, force=True)


def timer_totals() -> Dict[str, float]:
    """Total seconds per histogram name from the obs registry (the old
    accumulated-phase-times dict, same keys)."""
    from ..obs.metrics import Histogram, registry
    out: Dict[str, float] = {}
    for m in registry().metrics():
        if isinstance(m, Histogram):
            out[m.name] = out.get(m.name, 0.0) + m.sum
    return out


def reset_timers() -> None:
    """Clear the collected phase timers — the registry's HISTOGRAMS
    only. Counters and gauges (cumulative compile.requests, restart
    telemetry, bench gauges) are not timers and survive."""
    from ..obs.metrics import registry
    registry().reset(kind="histogram")


def log_timers() -> None:
    """Debug-log accumulated phase times from the obs registry (the
    reference prints its global timer table at shutdown in debug
    builds)."""
    from ..obs.metrics import Histogram, registry
    hists = [m for m in registry().metrics() if isinstance(m, Histogram)]
    for m in sorted(hists, key=lambda m: -m.sum):
        log.debug(f"{m.name}: {m.sum:.3f}s ({m.count} calls)")


def start_trace(log_dir: str) -> None:
    """Begin a jax.profiler trace (view with TensorBoard/xprof)."""
    import jax
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    import jax
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Trace a block when ``log_dir`` is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()
