"""Test/diagnostic instrumentation: XLA compile counting.

The serving guarantees are pinned by tests, not just measured: batch-
shape bucketing promises a BOUNDED compile cache under arbitrary
request sizes, and the stacked-forest cache promises zero re-stack /
re-upload on repeat predicts. This module gives tests the two probes
those assertions need:

- :class:`CompileWatch` — counts XLA compile requests between enter and
  exit via ``jax.monitoring`` events. A jit cache hit records nothing;
  every fresh trace->lower->compile records at least one event, so
  ``watch.compiles == 0`` is exactly "no new program was built" (a
  persistent-compilation-cache hit still counts as a compile request —
  it is a jit cache miss, which is what bucketing bounds).
- :func:`predict_program_cache_size` — the number of distinct compiled
  forest-traversal programs (re-exported from ops/predict.py).
"""
from __future__ import annotations

from typing import List

# any event under this prefix marks one compile request reaching the
# compilation-cache layer (observed: one fresh jit compile fires 1-3 of
# them; a jit cache hit fires none)
_COMPILE_EVENT_PREFIX = "/jax/compilation_cache/compile_requests"


class CompileWatch:
    """Context manager counting XLA compile requests.

    >>> with CompileWatch() as w:
    ...     booster.predict(X)
    >>> assert w.compiles == 0   # warm path: no fresh programs

    ``compiles`` is the number of compile-request events seen — compare
    against zero (exact) or use as an upper-bound proxy; one logical
    compile can fire a small handful of events, so assert ``== 0`` or
    ``<= bound`` with slack, never an exact nonzero count.
    """

    def __init__(self, name: str = "") -> None:
        self.name = name
        self.compiles = 0
        self.events: List[str] = []
        self._active = False

    def assert_compiles(self, at_most: int = 0) -> None:
        """Assert at most ``at_most`` compile requests were seen,
        failing with the captured event list (the warm-start pin:
        ``w.assert_compiles(0)`` after a second same-shape
        construct+engine-init reads "no new XLA program was built")."""
        if self.compiles > at_most:
            compile_events = [e for e in self.events if
                              e.startswith(_COMPILE_EVENT_PREFIX)]
            raise AssertionError(
                f"CompileWatch{f' {self.name!r}' if self.name else ''}: "
                f"{self.compiles} compile request(s), expected at most "
                f"{at_most}. Events: {compile_events[:10]}")

    def _listener(self, event: str, **kwargs) -> None:
        if not self._active:
            return
        self.events.append(event)
        if event.startswith(_COMPILE_EVENT_PREFIX):
            self.compiles += 1

    def __enter__(self) -> "CompileWatch":
        from jax import monitoring
        monitoring.register_event_listener(self._listener)
        self._active = True
        return self

    def __exit__(self, *exc) -> None:
        # stop counting FIRST: even if the unregister below fails, the
        # listener goes inert rather than polluting later watches — and
        # never clear_event_listeners(), which would wipe listeners we
        # do not own
        self._active = False
        try:
            # unregister lives in jax._src.monitoring on the pinned jax
            from jax._src import monitoring as _m
            _m._unregister_event_listener_by_callback(self._listener)
        except Exception:
            pass


def donation_enabled(config) -> bool:
    """Resolve the ``tpu_donate`` tristate against the live backend.

    Buffer donation (``jax.jit(donate_argnums=...)``) lets XLA update
    the boosting carries in place instead of copying them through
    every dispatch (docs/perf.md "Iteration floor"). "auto" donates on
    the TPU backend only — the profiled ``%copy`` waste lives there
    and CPU tier-1 runs keep today's copy semantics; "true" forces it
    on any backend (this jaxlib's CPU client honors donation, which is
    what makes the donation-on/off bit-identity tests real); "false"
    disables it everywhere (the ``bench.py --no-donate`` A/B arm).

    KNOWN-BAD COMBINATION, forced off with a warning: a non-TPU
    backend with a persistent compilation cache configured. This
    jaxlib's (0.4.37) CPU client intermittently corrupts the heap
    executing a donating executable DESERIALIZED from the cache —
    segfaults/aborts detonating later in unrelated native code.
    Reproduced: donating train runs pass 100% against a cold cache and
    crash most multi-train processes against a warm one; donation off
    or cache off are each individually stable. TPU PJRT keeps both
    (donation + persistent cache is the standard accelerator
    combination upstream)."""
    v = str(getattr(config, "tpu_donate", "auto"))
    if v == "false":
        return False
    import jax
    if jax.default_backend() == "tpu":
        return True                           # auto and true alike
    if v != "true":
        return False
    if getattr(jax.config, "jax_compilation_cache_dir", None):
        from . import log
        log.warning(
            "tpu_donate=true ignored: this backend "
            f"({jax.default_backend()}) intermittently crashes "
            "executing donating executables reloaded from the "
            "persistent compilation cache "
            f"({jax.config.jax_compilation_cache_dir}); unset the "
            "cache (jax_compilation_cache_dir) to force donation "
            "off-TPU — docs/perf.md 'Iteration floor'")
        return False
    return True


def donation_guard(fn, site: str):
    """``tpu_debug_checks`` use-after-donate guard for a donating jit.

    A donated buffer is DELETED when its dispatch is issued, so a
    caller that re-reads a stale Python reference gets XLA's generic
    ``RuntimeError: Array has been deleted`` wherever the read happens
    to land — far from the donating call. This wrapper checks every
    argument buffer BEFORE dispatch and fails with the donating site
    named, turning the latent crash into an actionable error. Debug
    path only (one ``is_deleted`` flag read per leaf); the production
    wrappers call the jit directly."""
    import jax

    from . import log

    def guarded(*args):
        for leaf in jax.tree.leaves(args):
            if getattr(leaf, "is_deleted", None) is not None \
                    and leaf.is_deleted():
                log.fatal(
                    f"tpu_debug: use-after-donate at {site} — an "
                    f"argument's buffer was already donated to an "
                    f"earlier dispatch and deleted; re-reading a stale "
                    f"reference is a bug (reassign before reading, or "
                    f"set tpu_donate=false)")
        return fn(*args)

    return guarded


def predict_program_cache_size() -> int:
    """Distinct compiled forest-traversal programs held by this process
    (the quantity batch-shape bucketing bounds)."""
    from ..ops.predict import predict_program_cache_size as _sz
    return _sz()


def ingest_program_cache_size() -> int:
    """Distinct compiled device bin-assignment programs (ops/ingest.py)
    held by this process — fixed-shape chunking promises ONE per
    (chunk_rows, features, bins) family, and a second same-shape
    ``Dataset.construct`` must not add any (test_ingest.py pins both)."""
    from ..ops.ingest import ingest_program_cache_size as _sz
    return _sz()
