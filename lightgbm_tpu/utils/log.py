"""Logging shim mirroring LightGBM's ``Log`` class.

Reference: include/LightGBM/utils/log.h (UNVERIFIED — empty mount, see
SURVEY.md banner): four levels (Fatal/Warning/Info/Debug) gated by the
``verbosity`` config param, plus a registerable callback so the host
language owns the sink (LGBM_RegisterLogCallback).
"""
from __future__ import annotations

import sys
from typing import Callable, Optional

# verbosity semantics match LightGBM: <0 fatal only, 0 += warning,
# 1 += info (default), >1 += debug.
_FATAL = -1
_WARNING = 0
_INFO = 1
_DEBUG = 2

_verbosity: int = 1
_callback: Optional[Callable[[str], None]] = None


class LightGBMError(Exception):
    """Error raised by the framework (mirrors lightgbm.basic.LightGBMError)."""


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = int(level)


def get_verbosity() -> int:
    return _verbosity


def register_callback(cb: Optional[Callable[[str], None]]) -> None:
    """Route log lines to ``cb`` instead of stderr (None restores stderr)."""
    global _callback
    _callback = cb


def _emit(msg: str) -> None:
    if _callback is not None:
        _callback(msg)
    else:
        print(msg, file=sys.stderr, flush=True)


def debug(msg: str) -> None:
    if _verbosity >= _DEBUG:
        _emit(f"[LightGBM-TPU] [Debug] {msg}")


def info(msg: str) -> None:
    if _verbosity >= _INFO:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def warning(msg: str) -> None:
    if _verbosity >= _WARNING:
        _emit(f"[LightGBM-TPU] [Warning] {msg}")


def fatal(msg: str) -> None:
    """Log and raise — mirrors Log::Fatal which throws std::runtime_error."""
    raise LightGBMError(msg)
