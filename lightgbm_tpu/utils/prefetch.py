"""Shared host<->device pipelining primitives (the two overlap idioms).

Two places in the codebase overlap transfers with device compute, and
before this module they each hand-rolled the same bookkeeping:

- predict's chunked traversal (gbdt._run_forest_chunks, PR 7) issues
  ``copy_to_host_async`` on chunk *i*'s output before dispatching
  chunk *i+1*, draining the oldest result once two are in flight;
- the streamed trainer (boosting/streaming.py) uploads bins block
  *i+1* while the device sweeps block *i*, blocking on the PREVIOUS
  block's sweep output before deleting its bins upload.

Both are the same structure — a depth-bounded in-flight window — so it
lives here once (:class:`InflightWindow`), and the upload direction
gains a one-step-lookahead staging thread (:class:`BlockPrefetcher`)
so the ``device_put`` of the NEXT block (host-side slice + pad + wire
transfer) runs concurrently with the current block's dispatch instead
of serializing in front of it.

THREADING CONTRACT: the staging callable handed to
:class:`BlockPrefetcher` runs on a background worker thread. It must
only *stage data* (slice/pad/``jax.device_put``) — it must NEVER
dispatch a cross-device collective (or anything that reaches one): on
a gang, per-rank collective launch order would then be a
thread-scheduling accident and the ranks deadlock. The
``tools/analyze`` collective-safety checker enforces this statically
(the ``thread:`` finding class).
"""
from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Optional, Sequence

__all__ = ["InflightWindow", "BlockPrefetcher"]


class InflightWindow:
    """Depth-bounded in-flight completion window.

    ``push(item)`` appends ``item`` and then completes (oldest-first)
    until at most ``depth`` items remain pending — so at the moment of
    a push, ``depth + 1`` items are briefly in flight: the one just
    dispatched plus the retained tail. ``depth=1`` is the classic
    double buffer both call sites used. ``drain()`` completes
    everything (the checkpoint-export / end-of-plan barrier).

    ``complete`` receives one pushed item and is where the caller
    blocks on device work and frees transient buffers
    (``jax.block_until_ready`` + ``.delete()`` on the trainer path,
    ``np.asarray`` of an async D2H copy on the predict path).
    """

    def __init__(self, depth: int, complete: Callable[[Any], None]):
        self.depth = max(0, int(depth))
        self._complete = complete
        self._q: deque = deque()

    def __len__(self) -> int:
        return len(self._q)

    def push(self, item: Any) -> None:
        self._q.append(item)
        while len(self._q) > self.depth:
            self._complete(self._q.popleft())

    def drain(self) -> None:
        while self._q:
            self._complete(self._q.popleft())


class BlockPrefetcher:
    """One-ahead staging of a cyclic upload schedule.

    The streamed trainer's sweeps (every level sweep, the final sweep,
    and then the next round's sweeps) all iterate the IDENTICAL
    step-major ``(rank, block)`` schedule — so a single cyclic
    prefetcher never stages a block that will not be consumed: items
    staged past one sweep's end are exactly the next sweep's first
    items. Only at the very end of training do up to ``lookahead + 1``
    staged uploads go unconsumed (bounded, block-sized transients).

    ``take(expect=...)`` returns the staged result for the next
    schedule item, keeping ``lookahead`` further stage calls running
    on the worker thread; ``expect`` pins the consumer's iteration
    order to the schedule — any drift is a loud error, not a silently
    wrong block. With ``threaded=False`` the stage callable runs
    inline on the caller's thread at ``take`` time — bit-for-bit the
    pre-pipelining dispatch order (the ``tpu_stream_overlap=false``
    arm), with the same loud schedule check.

    See the module docstring for the staging-thread contract: ``stage``
    must only slice/pad/``device_put`` — never reach a collective.
    """

    def __init__(self, stage: Callable[[Any], Any],
                 schedule: Iterable[Any], lookahead: int = 1,
                 threaded: bool = True):
        self._stage = stage
        self._schedule: Sequence[Any] = list(schedule)
        if not self._schedule:
            raise ValueError("BlockPrefetcher needs a non-empty "
                             "schedule")
        self._look = max(1, int(lookahead))
        self._pos = 0
        self._pending: deque = deque()   # (item, future)
        self._pool: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=1,
                               thread_name_prefix="h2d-prefetch")
            if threaded else None)

    def _next_item(self) -> Any:
        item = self._schedule[self._pos % len(self._schedule)]
        self._pos += 1
        return item

    def take(self, expect: Any = None) -> Any:
        if self._pool is None:
            item = self._next_item()
            if expect is not None and item != expect:
                raise RuntimeError(
                    f"BlockPrefetcher schedule drift: consumer asked "
                    f"for {expect!r} but the schedule yields {item!r}")
            return self._stage(item)
        while len(self._pending) <= self._look:
            item = self._next_item()
            self._pending.append(
                (item, self._pool.submit(self._stage, item)))
        item, fut = self._pending.popleft()
        if expect is not None and item != expect:
            raise RuntimeError(
                f"BlockPrefetcher schedule drift: consumer asked for "
                f"{expect!r} but the schedule yields {item!r}")
        return fut.result()

    def close(self) -> None:
        """Cancel/free staged-but-unconsumed work and stop the worker.
        Staged device buffers are ``.delete()``d when they expose it
        (jax arrays do) so end-of-training leftovers do not pin HBM."""
        while self._pending:
            _item, fut = self._pending.popleft()
            if not fut.cancel():
                try:
                    res = fut.result()
                except Exception:
                    continue
                if hasattr(res, "delete"):
                    try:
                        res.delete()
                    except Exception:
                        pass
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
