"""Subpackage: utils."""
