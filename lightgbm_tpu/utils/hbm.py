"""Shared HBM budgeting: one limit probe + one size estimate.

Three gates reason about the same quantity — "how much HBM does the
binned dataset occupy device-resident?" — and their numeric agreement
is load-bearing: a dataset the device-ingest gate (io/dataset.py)
keeps on the accelerator must never be one the auto-streaming gate
(boosting/__init__.py) then hands to the host-block engine, or the
device copy sits orphaned in HBM for the whole run. The engine's own
capacity guard (boosting/gbdt.py) fatals on the same estimate. Keeping
the probe, the estimate and the thresholds here means the gates cannot
drift apart.
"""
from __future__ import annotations

from typing import Optional

# auto-streaming engages above this fraction of HBM (with margin for
# histograms/score/partition); the device-ingest gate stands down at
# the same line so the two autos stay disjoint
STREAM_HBM_FRACTION = 0.6

# the resident engine fatals (actionable message instead of an opaque
# device OOM) above this fraction
ENGINE_HBM_FRACTION = 0.92


def hbm_bytes_limit() -> Optional[int]:
    """``bytes_limit`` of device 0, or None (CPU / older runtimes that
    expose no memory stats — every caller treats None as "no gate")."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit") or None
    except Exception:
        return None
    if limit is not None:
        # every gate probe refreshes the obs gauge, so the limit the
        # HBM-budget decisions reasoned about is the one the metrics
        # snapshot shows (obs/telemetry.py refreshes the in-use/peak
        # side at snapshot time)
        from .. import obs
        if obs.enabled():
            obs.set_gauge("hbm.bytes_limit", float(limit))
    return limit


def binned_device_bytes(n_rows: int, n_features: int, itemsize: int,
                        with_transposed: bool = True) -> int:
    """Device-resident footprint of a binned dataset: the row-major
    bins plus (Pallas path) the same-size feature-major int8 tile."""
    return (int(n_rows) * int(n_features) * int(itemsize)
            * (2 if with_transposed else 1))
