"""Shared HBM budgeting: one limit probe + one size estimate.

Three gates reason about the same quantity — "how much HBM does the
binned dataset occupy device-resident?" — and their numeric agreement
is load-bearing: a dataset the device-ingest gate (io/dataset.py)
keeps on the accelerator must never be one the auto-streaming gate
(boosting/__init__.py) then hands to the host-block engine, or the
device copy sits orphaned in HBM for the whole run. The engine's own
capacity guard (boosting/gbdt.py) fatals on the same estimate. Keeping
the probe, the estimate and the thresholds here means the gates cannot
drift apart.
"""
from __future__ import annotations

from typing import Optional

# auto-streaming engages above this fraction of HBM (with margin for
# histograms/score/partition); the device-ingest gate stands down at
# the same line so the two autos stay disjoint
STREAM_HBM_FRACTION = 0.6

# the resident engine fatals (actionable message instead of an opaque
# device OOM) above this fraction
ENGINE_HBM_FRACTION = 0.92

# serving-side budgets share the same probe: tree-sharded predict
# engages (tpu_serve_shard_trees=auto) when ONE model's stacked forest
# would exceed this fraction of a single device's HBM, and the
# multi-model LRU's auto byte cap (tpu_serve_cache_bytes=0) bounds the
# SUM of resident stacks to the same fraction — the two serve gates
# reason about the same estimate, so a forest the shard gate splits is
# never one the cache gate would have admitted whole
SERVE_HBM_FRACTION = 0.5


def hbm_bytes_limit() -> Optional[int]:
    """``bytes_limit`` of device 0, or None (CPU / older runtimes that
    expose no memory stats — every caller treats None as "no gate")."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit") or None
    except Exception:
        return None
    if limit is not None:
        # every gate probe refreshes the obs gauge, so the limit the
        # HBM-budget decisions reasoned about is the one the metrics
        # snapshot shows (obs/telemetry.py refreshes the in-use/peak
        # side at snapshot time)
        from .. import obs
        if obs.enabled():
            obs.set_gauge("hbm.bytes_limit", float(limit))
    return limit


def binned_device_bytes(n_rows: int, n_features: int, itemsize: int,
                        with_transposed: bool = True) -> int:
    """Device-resident footprint of a binned dataset: the row-major
    bins plus (Pallas path) the same-size feature-major int8 tile."""
    return (int(n_rows) * int(n_features) * int(itemsize)
            * (2 if with_transposed else 1))


def stacked_forest_bytes(n_trees: int, num_leaves: int,
                         cat_bitset_words: int = 0) -> int:
    """Device-resident footprint of one stacked forest
    (``GBDT._stack_model_list`` layout): per tree, four ``[Ln]`` int32
    node tables plus a bool default-left column and the ``[L]`` f32
    leaf values (plus the categorical bitset planes when present).
    The serve-side gates — the multi-model LRU's byte cap
    (serve/registry.py) and the tree-shard auto policy
    (serve/shard.py) — both budget against THIS estimate, keeping
    their judgments of "how big is a resident model" from drifting
    apart the way the dataset gates once did."""
    T = max(int(n_trees), 0)
    L = max(int(num_leaves), 1)
    Ln = max(L - 1, 1)
    per_tree = (Ln * 4 * 4      # split_feature/threshold/left/right i32
                + Ln * 1        # default_left bool
                + L * 4         # leaf_value f32
                + 4 + 4)        # num_leaves + class index i32
    if cat_bitset_words > 0:
        per_tree += Ln * (1 + 4 * int(cat_bitset_words))  # is_cat+bitset
    return T * per_tree
