"""Distributed training launcher — the ``dask.py`` analog.

Reference: ``python-package/lightgbm/dask.py`` (UNVERIFIED — empty
mount, see SURVEY.md banner) automates the multi-worker story: align
data partitions to workers, wire up ``machines``/ports, launch
concurrent per-worker training, return the (identical) model from
worker 0. Its transport is the socket collective layer.

TPU-native redesign: ``jax.distributed`` is the cluster fabric and the
SPMD learners already speak mesh collectives, so the launcher's job
collapses to three things this module provides:

1. :func:`train_distributed` — fork/join N localhost processes (the
   in-box testing + single-host-multi-process story; a real pod runs
   one process per host with the same worker body via
   :func:`run_worker`);
2. **automatic bin-boundary sync** — every process samples its own
   row shard, the samples are all-gathered
   (``multihost_utils.process_allgather``) and every process builds
   IDENTICAL BinMappers from the union sample (the reference
   ``DatasetLoader``'s distributed sample sync, dataset_loader.cpp —
   UNVERIFIED). No rank-0 broadcast needed: same bytes in, same
   mappers out, deterministically;
3. model collection from rank 0.

Pod recipe (multi-host hardware): run YOUR script once per host;
in it call ``run_worker(rank=None, ...)`` (auto-discovery on TPU
pods) or pass coordinator/rank explicitly. ``train_distributed``
itself is the localhost many-process convenience wrapper around it.
"""
from __future__ import annotations

import multiprocessing as mp
import socket
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import numpy as np

from ..utils import log
from ..utils.log import LightGBMError


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclass
class ShardSpec:
    """What ``data_fn`` returns: this process's row shard."""

    data: np.ndarray                      # [n_local, F] raw features
    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None
    init_score: Optional[np.ndarray] = None


def sync_bin_mappers(X_local: np.ndarray, params: Dict,
                     categorical_idx=None):
    """Distributed bin-boundary sync: identical BinMappers on every
    process, built from an all-gathered cross-process sample.

    Each process's sample quota is PROPORTIONAL to its shard's row
    count (``bin_construct_sample_cnt * n_local / n_total``) so uneven
    shards don't bias bin boundaries toward small shards'
    distributions — the reference samples proportionally at the loader
    level (``dataset_loader.cpp`` sample-indices contract, SURVEY §2.1,
    UNVERIFIED). The fixed-size padded samples ride one
    ``process_allgather``, and each process runs the same binning code
    on the same union sample — bit-identical mappers with no broadcast
    step.
    """
    import jax
    from jax.experimental import multihost_utils

    from ..io.binning import mappers_from_params

    p = params
    total_cnt = int(p.get("bin_construct_sample_cnt", 200000))
    nproc = jax.process_count()
    n_local, F = X_local.shape
    rng = np.random.default_rng(
        int(p.get("data_random_seed", 1)) + 7919 * jax.process_index())
    # shard row counts first: every process derives ALL ranks' sample
    # sizes from the same gathered counts, so quotas are proportional
    # to shard size and no second counts gather is needed
    n_cnt = np.zeros((1,), np.int64) + n_local
    g_n = np.asarray(multihost_utils.process_allgather(n_cnt)) \
        .reshape(nproc).astype(np.int64)
    n_total = max(1, int(g_n.sum()))
    k_all = np.minimum(
        np.maximum(1, (total_cnt * g_n) // n_total), g_n).astype(int)
    k = int(k_all[jax.process_index()])
    idx = (rng.choice(n_local, size=k, replace=False) if k < n_local
           else np.arange(n_local))
    g_cnt = k_all
    slot = max(1, int(g_cnt.max()))
    samp = np.full((slot, F), np.nan, np.float64)
    samp[:k] = np.asarray(X_local, np.float64)[idx]
    g_samp = np.asarray(multihost_utils.process_allgather(samp)) \
        .reshape(nproc, slot, F)
    union = np.concatenate([g_samp[r, :g_cnt[r]] for r in range(nproc)])
    # total_sample_cnt semantics: the union IS the sample; sparse
    # implicit-zero accounting applies within it only
    return mappers_from_params(union, p, categorical_idx=categorical_idx,
                               sample_cnt=len(union))


def run_worker(params: Dict, data_fn: Callable[[int, int], ShardSpec],
               num_boost_round: int = 100, *,
               rank: Optional[int] = None,
               num_processes: Optional[int] = None,
               coordinator: Optional[str] = None,
               platform: Optional[str] = None,
               categorical_feature="auto"):
    """The per-process worker body (call once per host on a pod).

    Joins the ``jax.distributed`` job, fetches this process's shard
    from ``data_fn(rank, num_processes)``, syncs bin boundaries across
    all processes, trains the data-parallel learner, and returns the
    Booster (identical on every rank — the SPMD program IS the sync).
    """
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    from .multihost import init_multihost
    if rank is not None or coordinator is not None:
        init_multihost(coordinator, num_processes, rank)
    else:
        init_multihost()    # TPU pod auto-discovery

    import lightgbm_tpu as lgb

    rank = jax.process_index()
    nproc = jax.process_count()
    shard = data_fn(rank, nproc)
    if not isinstance(shard, ShardSpec):
        shard = ShardSpec(**shard) if isinstance(shard, dict) \
            else ShardSpec(*shard)
    params = dict(params)
    params.setdefault("tree_learner", "data")
    ds = lgb.Dataset(shard.data, label=shard.label,
                     weight=shard.weight, group=shard.group,
                     init_score=shard.init_score,
                     params=dict(params),
                     categorical_feature=categorical_feature)
    # automatic bin-boundary sync (closes the manual mapper-sharing
    # contract multihost.py documented through round 3)
    cat_idx = ds._resolve_categorical(
        ds._resolve_feature_names(shard.data.shape[1]))
    ds.bin_mappers = sync_bin_mappers(shard.data, params, cat_idx)
    return lgb.train(params, ds, num_boost_round=num_boost_round)


def _spawn_main(rank, nproc, port, params, data_fn, num_boost_round,
                platform, categorical_feature, queue):
    try:
        # children inherit the parent's env; a fake-device-count flag
        # (e.g. the test suite's 8-device CPU mesh) would multiply the
        # world size — each localhost worker gets ONE device
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" in flags:
            os.environ["XLA_FLAGS"] = " ".join(
                f for f in flags.split()
                if "host_platform_device_count" not in f)
        bst = run_worker(params, data_fn, num_boost_round, rank=rank,
                         num_processes=nproc,
                         coordinator=f"localhost:{port}",
                         platform=platform,
                         categorical_feature=categorical_feature)
        if rank == 0:
            queue.put(("ok", bst.model_to_string()))
    except Exception as e:          # surface the real worker error
        import traceback
        queue.put(("err", f"rank {rank}: {e}\n"
                   f"{traceback.format_exc()}"))
        raise


def train_distributed(params: Dict,
                      data_fn: Callable[[int, int], ShardSpec],
                      n_processes: int, num_boost_round: int = 100, *,
                      platform: Optional[str] = "cpu",
                      categorical_feature="auto",
                      timeout: float = 900.0):
    """Train over ``n_processes`` localhost processes and return the
    rank-0 Booster (the dask.py ``_train`` analog).

    Args:
      params: lightgbm params (``tree_learner`` defaults to ``data``).
      data_fn: module-level picklable callable ``(rank, n_processes) ->
        ShardSpec`` (or dict of its fields) producing each process's
        row shard — the partition→worker alignment step.
      n_processes: localhost world size (one CPU device each by
        default; on real multi-host hardware run one process per host
        yourself via :func:`run_worker` instead).
      platform: force a JAX platform in the workers ("cpu" default —
        this environment exposes one TPU chip, which cannot be shared
        by N processes; pass None on a real pod).
      timeout: seconds to wait for the workers.
    """
    ctx = mp.get_context("spawn")     # fork would inherit JAX state
    port = _free_port()
    queue = ctx.Queue()
    procs = [ctx.Process(
        target=_spawn_main,
        args=(r, n_processes, port, params, data_fn, num_boost_round,
              platform, categorical_feature, queue))
        for r in range(n_processes)]
    for p in procs:
        p.start()
    # poll: fail FAST when a worker dies before rank 0 reports (e.g. a
    # non-importable data_fn under spawn) instead of sitting out the
    # full timeout — the dask.py analog of surfacing worker loss
    import queue as _queue
    import time as _time
    result = None
    deadline = _time.monotonic() + timeout
    while result is None and _time.monotonic() < deadline:
        try:
            result = queue.get(timeout=2.0)
        except _queue.Empty:
            dead = [(i, p.exitcode) for i, p in enumerate(procs)
                    if not p.is_alive() and p.exitcode not in (0, None)]
            if dead:
                break
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    if result is None:
        # a dying worker may have flushed its ('err', traceback) into
        # the queue between our last poll and the liveness check —
        # prefer that real error over the generic message
        try:
            result = queue.get_nowait()
        except Exception:
            pass
    if result is None:
        dead = [(i, p.exitcode) for i, p in enumerate(procs)
                if p.exitcode not in (0, None)]
        raise LightGBMError(
            "distributed training produced no result "
            + (f"(worker ranks/exitcodes {dead} died — is data_fn a "
               f"module-level importable callable? spawn re-imports "
               f"its module in each worker)" if dead else
               "(workers timed out before rank 0 reported; re-run "
               "with verbosity>=1 for worker logs)"))
    status, payload = result
    if status != "ok":
        raise LightGBMError(f"distributed worker failed: {payload}")
    import lightgbm_tpu as lgb
    bst = lgb.Booster(model_str=payload)
    log.info(f"distributed training done: {n_processes} processes, "
             f"{bst.num_trees()} trees collected from rank 0")
    return bst
