"""Distributed training launcher — the ``dask.py`` analog.

Reference: ``python-package/lightgbm/dask.py`` (UNVERIFIED — empty
mount, see SURVEY.md banner) automates the multi-worker story: align
data partitions to workers, wire up ``machines``/ports, launch
concurrent per-worker training, return the (identical) model from
worker 0. Its transport is the socket collective layer.

TPU-native redesign: ``jax.distributed`` is the cluster fabric and the
SPMD learners already speak mesh collectives, so the launcher's job
collapses to three things this module provides:

1. :func:`train_distributed` — fork/join N localhost processes (the
   in-box testing + single-host-multi-process story; a real pod runs
   one process per host with the same worker body via
   :func:`run_worker`);
2. **automatic bin-boundary sync** — every process samples its own
   row shard, the samples are all-gathered
   (``multihost_utils.process_allgather``) and every process builds
   IDENTICAL BinMappers from the union sample (the reference
   ``DatasetLoader``'s distributed sample sync, dataset_loader.cpp —
   UNVERIFIED). No rank-0 broadcast needed: same bytes in, same
   mappers out, deterministically;
3. model collection from rank 0.

Pod recipe (multi-host hardware): run YOUR script once per host;
in it call ``run_worker(rank=None, ...)`` (auto-discovery on TPU
pods) or pass coordinator/rank explicitly. ``train_distributed``
itself is the localhost many-process convenience wrapper around it.

Out-of-core composition: with ``tpu_streaming`` ("true", or "auto"
when even the per-rank binned shard exceeds HBM) each worker routes
onto the SHARDED streaming engine — its shard's bins stay in host RAM
and stream through the device block by block, with ONE packed
collective of the accumulated histograms per tree level
(docs/perf.md "Streamed x sharded"). Same ``data_fn`` row-shard
contract, same rank-0 model collection; datasets beyond one host's
RAM x beyond one device's HBM become a worker-count question.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import re
import socket
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ..config import get_param
from ..utils import log
from ..utils.log import LightGBMError

# set in the driver's environment (inherited by spawned workers) for
# EVERY launcher-spawned gang: a worker seeing it skips its fresh-run
# fault-marker clearing — marker hygiene is driver-owned here (one
# clear before the first gang, no per-rank race, and a from-scratch
# relaunch replaying the fault iteration honors the already-fired
# marker instead of re-dying on it every attempt). Direct lgb.train /
# run_worker users keep the worker-side clearing.
_RELAUNCH_ENV = "LGBM_TPU_GANG_RELAUNCH"

_HB_FILE_RE = re.compile(r"^heartbeat\.train\.rank(\d+)$")


def strip_fake_device_flags() -> None:
    """Drop any ``--xla_force_host_platform_device_count`` flag from
    this process's ``XLA_FLAGS``. Spawned children inherit the
    parent's env; a fake-device-count flag (e.g. the test suite's
    8-device CPU mesh) would multiply a worker's world size — each
    localhost worker/replica gets ONE device. Call BEFORE the first
    jax import in any spawned-process main."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" in flags:
        os.environ["XLA_FLAGS"] = " ".join(
            f for f in flags.split()
            if "host_platform_device_count" not in f)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clear_heartbeat_files(hb_dir: Optional[str]) -> None:
    """Remove per-rank heartbeat stamp files before (re)launching a
    gang — a stale file from the previous gang would read as an
    instantly-hung rank and kill every relaunch on sight."""
    if not hb_dir:
        return
    try:
        names = os.listdir(hb_dir)
    except OSError:
        return
    for name in names:
        if _HB_FILE_RE.match(name):
            try:
                os.unlink(os.path.join(hb_dir, name))
            except OSError:
                pass


def _stale_heartbeats(hb_dir: Optional[str],
                      timeout: float) -> List[Tuple[int, float]]:
    """(rank, age_seconds) for every heartbeat file older than
    ``timeout``. A rank with NO file yet is starting up (compiling,
    binning) — that is the overall gang timeout's job, not a hang."""
    if not hb_dir or timeout <= 0:
        return []
    import time as _time
    try:
        names = os.listdir(hb_dir)
    except OSError:
        return []
    now = _time.time()
    stale = []
    for name in names:
        m = _HB_FILE_RE.match(name)
        if not m:
            continue
        try:
            age = now - os.stat(os.path.join(hb_dir, name)).st_mtime
        except OSError:
            continue
        if age > timeout:
            stale.append((int(m.group(1)), round(age, 1)))
    return sorted(stale)


def _clear_rank_snapshots_beyond(rank_dir: Optional[str],
                                 width: int) -> None:
    """Remove per-rank metrics snapshots for ranks >= the LIVE gang
    width before any (re)launch — a gang relaunched narrower (R'=2
    after R=4) must not merge the previous topology's rank_2/rank_3
    snapshots into merged.jsonl as if those ranks were still
    members."""
    if not rank_dir:
        return
    rank_re = re.compile(r"^rank_(\d+)\.jsonl$")
    try:
        names = os.listdir(rank_dir)
    except OSError:
        return
    stale = []
    for name in names:
        m = rank_re.match(name)
        if m and int(m.group(1)) >= width:
            stale.append(name)
    for name in stale:
        try:
            os.remove(os.path.join(rank_dir, name))
        except OSError:
            pass
    if stale:
        log.warning(f"tpu_metrics_rank_dir {rank_dir} held "
                    f"{len(stale)} snapshot file(s) for ranks beyond "
                    f"the live width {width}; cleared before launch")


def _gone_ranks(gone_dirs: List[str], hb_dir: Optional[str],
                width: int, early_dead, hb_strikes: Dict[int, int],
                strikes_needed: int = 2) -> List[int]:
    """Ranks whose HOST is gone, from two signals: explicit
    ``.host_gone.rank<r>`` markers (the ``resize`` chaos fault, or an
    operator touch-file), and the spawn-failure heuristic — a rank
    that died on its own without EVER stamping a heartbeat this
    attempt collects a strike; ``strikes_needed`` consecutive strikes
    read as "that machine cannot even start a worker". Mutates
    ``hb_strikes`` (stamped ranks reset)."""
    from ..recovery.faults import host_gone_ranks
    gone = set()
    for d in gone_dirs:
        gone.update(host_gone_ranks(d))
    if hb_dir:
        # "consecutive" means exactly that: ANY rank that stamped a
        # heartbeat this attempt proved its host can start a worker —
        # its strike count resets even when the gang failed for an
        # unrelated reason and the rank never re-entered early_dead
        for r in list(hb_strikes):
            if os.path.exists(
                    os.path.join(hb_dir, f"heartbeat.train.rank{r}")):
                hb_strikes.pop(r, None)
        for r, _code in early_dead:
            if not os.path.exists(
                    os.path.join(hb_dir, f"heartbeat.train.rank{r}")):
                hb_strikes[r] = hb_strikes.get(r, 0) + 1
        gone.update(r for r, s in hb_strikes.items()
                    if s >= strikes_needed)
    return sorted(r for r in gone if 0 <= r < width)


@dataclass
class ShardSpec:
    """What ``data_fn`` returns: this process's row shard."""

    data: np.ndarray                      # [n_local, F] raw features
    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None
    init_score: Optional[np.ndarray] = None


def sync_bin_mappers(X_local: np.ndarray, params: Dict,
                     categorical_idx=None):
    """Distributed bin-boundary sync: identical BinMappers on every
    process, built from an all-gathered cross-process sample.

    Each process's sample quota is PROPORTIONAL to its shard's row
    count (``bin_construct_sample_cnt * n_local / n_total``) so uneven
    shards don't bias bin boundaries toward small shards'
    distributions — the reference samples proportionally at the loader
    level (``dataset_loader.cpp`` sample-indices contract, SURVEY §2.1,
    UNVERIFIED). The fixed-size padded samples ride one
    ``process_allgather``, and each process runs the same binning code
    on the same union sample — bit-identical mappers with no broadcast
    step.
    """
    import jax
    from jax.experimental import multihost_utils

    from ..io.binning import mappers_from_params

    p = params
    total_cnt = int(p.get("bin_construct_sample_cnt", 200000))
    nproc = jax.process_count()
    n_local, F = X_local.shape
    rng = np.random.default_rng(
        int(p.get("data_random_seed", 1)) + 7919 * jax.process_index())
    # shard row counts first: every process derives ALL ranks' sample
    # sizes from the same gathered counts, so quotas are proportional
    # to shard size and no second counts gather is needed
    n_cnt = np.zeros((1,), np.int64) + n_local
    g_n = np.asarray(multihost_utils.process_allgather(n_cnt)) \
        .reshape(nproc).astype(np.int64)
    n_total = max(1, int(g_n.sum()))
    k_all = np.minimum(
        np.maximum(1, (total_cnt * g_n) // n_total), g_n).astype(int)
    k = int(k_all[jax.process_index()])
    idx = (rng.choice(n_local, size=k, replace=False) if k < n_local
           else np.arange(n_local))
    g_cnt = k_all
    slot = max(1, int(g_cnt.max()))
    samp = np.full((slot, F), np.nan, np.float64)
    samp[:k] = np.asarray(X_local, np.float64)[idx]
    g_samp = np.asarray(multihost_utils.process_allgather(samp)) \
        .reshape(nproc, slot, F)
    union = np.concatenate([g_samp[r, :g_cnt[r]] for r in range(nproc)])
    # total_sample_cnt semantics: the union IS the sample; sparse
    # implicit-zero accounting applies within it only
    return mappers_from_params(union, p, categorical_idx=categorical_idx,
                               sample_cnt=len(union))


def run_worker(params: Dict, data_fn: Callable[[int, int], ShardSpec],
               num_boost_round: int = 100, *,
               rank: Optional[int] = None,
               num_processes: Optional[int] = None,
               coordinator: Optional[str] = None,
               platform: Optional[str] = None,
               categorical_feature="auto",
               resume_from: Optional[str] = None):
    """The per-process worker body (call once per host on a pod).

    Joins the ``jax.distributed`` job, fetches this process's shard
    from ``data_fn(rank, num_processes)``, syncs bin boundaries across
    all processes, trains the data-parallel learner, and returns the
    Booster (identical on every rank — the SPMD program IS the sync).

    ``resume_from``: checkpoint directory to resume from (every rank
    restores its OWN per-rank checkpoint; ranks agree on the resume
    iteration via an allgather — recovery/checkpoint.py).
    """
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    from .multihost import init_multihost
    if rank is not None or coordinator is not None:
        init_multihost(coordinator, num_processes, rank)
    else:
        init_multihost()    # TPU pod auto-discovery

    import lightgbm_tpu as lgb

    rank = jax.process_index()
    nproc = jax.process_count()
    # rank-tag this process's trace stream BEFORE training records any
    # span: with tpu_trace_dir set, each worker exports
    # rank_<r>.trace.json (rank-keyed pid + process_name rows) that
    # scripts/trace_merge.py rebases into one gang-wide timeline
    from ..obs import set_trace_rank
    set_trace_rank(rank)
    shard = data_fn(rank, nproc)
    if not isinstance(shard, ShardSpec):
        shard = ShardSpec(**shard) if isinstance(shard, dict) \
            else ShardSpec(*shard)
    params = dict(params)
    params.setdefault("tree_learner", "data")
    ds = lgb.Dataset(shard.data, label=shard.label,
                     weight=shard.weight, group=shard.group,
                     init_score=shard.init_score,
                     params=dict(params),
                     categorical_feature=categorical_feature)
    # automatic bin-boundary sync (closes the manual mapper-sharing
    # contract multihost.py documented through round 3)
    cat_idx = ds._resolve_categorical(
        ds._resolve_feature_names(shard.data.shape[1]))
    ds.bin_mappers = sync_bin_mappers(shard.data, params, cat_idx)
    bst = lgb.train(params, ds, num_boost_round=num_boost_round,
                    resume_from=resume_from)
    # per-rank metrics for the gang-wide view (obs/aggregate.py): each
    # worker appends its rank-tagged snapshot; the train_distributed
    # driver merges them after the gang joins. Best-effort — a full
    # disk must not fail a training run that already succeeded
    rank_dir = str(get_param(params, "tpu_metrics_rank_dir")
                   or "").strip()
    if rank_dir:
        from ..obs.aggregate import dump_rank_snapshot
        try:
            dump_rank_snapshot(rank_dir, rank)
        except Exception as e:
            log.warning(f"tpu_metrics_rank_dir: cannot write rank "
                        f"{rank} snapshot under {rank_dir!r}: {e}")
    return bst


def _spawn_main(rank, nproc, port, params, data_fn, num_boost_round,
                platform, categorical_feature, queue, resume_from):
    try:
        strip_fake_device_flags()
        bst = run_worker(params, data_fn, num_boost_round, rank=rank,
                         num_processes=nproc,
                         coordinator=f"localhost:{port}",
                         platform=platform,
                         categorical_feature=categorical_feature,
                         resume_from=resume_from)
        if rank == 0:
            queue.put(("ok", bst.model_to_string()))
    except Exception as e:          # surface the real worker error
        import traceback
        queue.put(("err", f"rank {rank}: {e}\n"
                   f"{traceback.format_exc()}"))
        raise


def _gang_once(params: Dict, data_fn, n_processes: int,
               num_boost_round: int, platform, categorical_feature,
               timeout: float, resume_from: Optional[str],
               hb_dir: Optional[str] = None,
               hb_timeout: float = 0.0):
    """One fork/join pass over a fresh worker gang on a fresh port.
    Returns ``(result, dead, early_dead)``: the ("ok", model_str) /
    ("err", payload) queue result or None when the gang died or timed
    out without reporting, the post-teardown dead rank/exitcode list
    for the error message, and ``early_dead`` — the ranks that died ON
    THEIR OWN before teardown (a teardown-terminated survivor must not
    feed the degrade heuristic's spawn-failure strikes).

    ``hb_dir``/``hb_timeout``: the heartbeat watchdog — workers stamp
    per-rank heartbeat files each round (engine.train via
    ``tpu_heartbeat_dir``); a stamp stale past ``hb_timeout`` means a
    HUNG rank (wedged pre-collective, stuck DMA): the gang is torn
    down like a crashed one and the caller's restart loop relaunches
    it. Hangs otherwise wedge forever — no exit code, no queue
    result — and only the blunt overall ``timeout`` would catch them.
    """
    ctx = mp.get_context("spawn")     # fork would inherit JAX state
    port = _free_port()
    queue = ctx.Queue()
    _clear_heartbeat_files(hb_dir)
    procs = [ctx.Process(
        target=_spawn_main,
        args=(r, n_processes, port, params, data_fn, num_boost_round,
              platform, categorical_feature, queue, resume_from))
        for r in range(n_processes)]
    for p in procs:
        p.start()
    # poll: fail FAST when a worker dies before rank 0 reports (e.g. a
    # non-importable data_fn under spawn, or an injected worker kill)
    # instead of sitting out the full timeout — the dask.py analog of
    # surfacing worker loss
    import queue as _queue
    import time as _time
    result = None
    early_dead = []
    deadline = _time.monotonic() + timeout
    while result is None and _time.monotonic() < deadline:
        try:
            result = queue.get(timeout=2.0)
        except _queue.Empty:
            dead = [(i, p.exitcode) for i, p in enumerate(procs)
                    if not p.is_alive() and p.exitcode not in (0, None)]
            if dead:
                early_dead = dead
                break
            stale = _stale_heartbeats(hb_dir, hb_timeout)
            if stale:
                from .. import obs
                # forced: the watchdog fires before any Config can
                # flip metrics on, like the restart counters
                obs.inc("watchdog.restarts", force=True)
                log.warning(
                    f"heartbeat watchdog: rank(s) "
                    f"{[r for r, _ in stale]} stale for "
                    f"{[a for _, a in stale]}s "
                    f"(> {hb_timeout:.1f}s) — killing the gang as "
                    f"hung")
                result = ("err",
                          f"heartbeat watchdog: rank(s) {stale} went "
                          f"stale past {hb_timeout:.1f}s — presumed "
                          f"hung pre-collective; gang killed for "
                          f"relaunch")
                break
        except Exception as e:
            # a worker killed MID-put leaves a truncated pickle in the
            # queue pipe; that is a gang failure to recover from — it
            # must reach the teardown + restart loop below, not escape
            # as a raw unpickling traceback that leaks hung workers
            result = ("err", f"worker result was undeliverable "
                      f"({type(e).__name__}: {e}) — a worker likely "
                      f"died while reporting")
            break
    # tear the gang down. On a clean result the workers exit on their
    # own (grant a grace join); on a dead/failed gang the survivors are
    # stuck in collectives waiting for the lost rank and will NEVER
    # exit, so don't sit out per-process joins — escalate to terminate
    # -> kill immediately (restart latency is the backoff, not this)
    clean = result is not None and result[0] == "ok"
    grace = 10.0 if clean else 0.5
    deadline = _time.monotonic() + grace
    for p in procs:
        p.join(timeout=max(0.0, deadline - _time.monotonic()))
        if p.is_alive():
            p.terminate()
    for p in procs:
        p.join(timeout=5)
        if p.is_alive():
            p.kill()
            p.join(timeout=5)
    if result is None:
        # a dying worker may have flushed its ('err', traceback) into
        # the queue between our last poll and the liveness check —
        # prefer that real error over the generic message. Only an
        # EMPTY queue is expected here; a real unpickling error must
        # surface, not vanish into a generic timeout message.
        try:
            result = queue.get_nowait()
        except _queue.Empty:
            pass
        except Exception as e:
            result = ("err", f"worker result was undeliverable "
                      f"({type(e).__name__}: {e}) — a worker likely "
                      f"died while reporting")
    dead = [(i, p.exitcode) for i, p in enumerate(procs)
            if p.exitcode not in (0, None)]
    if not early_dead:
        # a worker can die between the last poll and teardown; ranks
        # the TEARDOWN terminated show SIGTERM/SIGKILL exit codes and
        # are excluded (they were alive — not a spawn failure)
        early_dead = [(i, c) for i, c in dead
                      if c not in (-15, -9)] if not clean else []
    return result, dead, early_dead


def train_distributed(params: Dict,
                      data_fn: Callable[[int, int], ShardSpec],
                      n_processes: int, num_boost_round: int = 100, *,
                      platform: Optional[str] = "cpu",
                      categorical_feature="auto",
                      timeout: float = 900.0,
                      max_restarts: int = 0,
                      restart_backoff: float = 1.0,
                      checkpoint_dir: Optional[str] = None,
                      checkpoint_interval: int = 0,
                      resume: Union[bool, str] = "auto",
                      heartbeat_timeout: Optional[float] = None):
    """Train over ``n_processes`` localhost processes and return the
    rank-0 Booster (the dask.py ``_train`` analog).

    Args:
      params: lightgbm params (``tree_learner`` defaults to ``data``).
      data_fn: module-level picklable callable ``(rank, n_processes) ->
        ShardSpec`` (or dict of its fields) producing each process's
        row shard — the partition→worker alignment step.
      n_processes: localhost world size (one CPU device each by
        default; on real multi-host hardware run one process per host
        yourself via :func:`run_worker` instead).
      platform: force a JAX platform in the workers ("cpu" default —
        this environment exposes one TPU chip, which cannot be shared
        by N processes; pass None on a real pod).
      timeout: seconds to wait for the workers (per attempt).
      max_restarts: automatic gang restarts after a worker death or
        timeout. Each restart terminates the gang, waits an
        exponential backoff, and relaunches every rank on a FRESH
        coordinator port; with a checkpoint dir holding a valid rank-0
        checkpoint the gang resumes from it, otherwise it restarts the
        run from scratch. 0 preserves the old fail-fast behavior.
      restart_backoff: base seconds for the exponential restart
        backoff (doubles per attempt, capped at 30 s).
      checkpoint_dir / checkpoint_interval: convenience for setting the
        same-named params on every worker (periodic durable per-rank
        checkpoints; docs/robustness.md). ``checkpoint_dir`` in
        ``params`` works identically.
      resume: "auto" (default) resumes from the newest valid rank-0
        checkpoint in the checkpoint dir when one exists — so re-running
        the SAME call after a whole-driver crash/preemption continues
        the job instead of wiping its checkpoints. False forces a fresh
        run (stale checkpoints are cleared); True requires a resumable
        checkpoint and raises when the dir holds none.
      heartbeat_timeout: heartbeat watchdog (seconds; also readable
        from params' ``tpu_heartbeat_timeout``). Workers stamp
        per-rank heartbeat files every round; a stamp stale past this
        timeout marks the rank HUNG (wedged pre-collective) and the
        gang is killed and relaunched through the same restart/backoff
        path a crash takes — so give it restart budget via
        ``max_restarts``. Set it above the worst cold-compile +
        per-round time; 0/None disables (hangs then only hit the
        blunt overall ``timeout``).
    """
    from ..recovery.restart import (backoff_seconds,
                                    has_resumable_checkpoint,
                                    is_bind_failure)
    params = dict(params)
    if checkpoint_dir:
        params["checkpoint_dir"] = str(checkpoint_dir)
    if checkpoint_interval > 0:
        # independent of HOW checkpoint_dir was supplied (kwarg or
        # params) — the dir may come from params with the cadence here
        params["checkpoint_interval"] = int(checkpoint_interval)
    ckpt_dir = str(params.get("checkpoint_dir") or "") or None

    # heartbeat watchdog wiring: give every worker a stamp-file dir and
    # remember the staleness budget the poll loop enforces
    hb_timeout = (float(heartbeat_timeout)
                  if heartbeat_timeout is not None
                  else float(get_param(params, "tpu_heartbeat_timeout")
                             or 0))
    if 0 < hb_timeout < 3.0:
        # workers stamp at most ~1 Hz (obs.set_heartbeat_file's
        # throttle): a timeout at or below the stamp interval would
        # read every HEALTHY rank as hung and kill each gang right
        # after its first stamp
        log.warning(f"heartbeat_timeout={hb_timeout:g}s is below the "
                    f"~1 Hz stamp cadence; raising to 3s")
        hb_timeout = 3.0
    hb_dir = (str(get_param(params, "tpu_heartbeat_dir") or "").strip()
              or None)
    if hb_timeout > 0 and not hb_dir:
        if ckpt_dir:
            hb_dir = ckpt_dir
        else:
            import tempfile
            hb_dir = tempfile.mkdtemp(prefix="lgbm_tpu_hb_")
    if hb_timeout > 0:
        params["tpu_heartbeat_dir"] = hb_dir
        os.makedirs(hb_dir, exist_ok=True)

    # cross-driver resume: a preempted/killed DRIVER re-running the
    # same call must continue the job, not clear its checkpoints
    resume_from = None
    if resume not in (False, True, "auto"):
        raise LightGBMError(f"resume must be True, False or 'auto', "
                            f"got {resume!r}")
    if resume in (True, "auto") and ckpt_dir \
            and has_resumable_checkpoint(ckpt_dir):
        resume_from = ckpt_dir
        log.info(f"resuming distributed training from the newest "
                 f"checkpoint in {ckpt_dir}")
    if resume is True and resume_from is None:
        raise LightGBMError(
            f"resume=True but {ckpt_dir!r} holds no valid rank-0 "
            f"checkpoint to resume from")
    if resume is False and ckpt_dir:
        # clear driver-side BEFORE the first launch: if the gang died
        # before any worker reached its own fresh-run clearing, the
        # restart path's has_resumable_checkpoint would adopt the old
        # run the caller explicitly asked to discard
        from ..recovery.checkpoint import clear_checkpoint_dir
        cleared = clear_checkpoint_dir(ckpt_dir)
        if cleared:
            log.warning(f"resume=False: cleared {cleared} stale "
                        f"checkpoint(s) from {ckpt_dir}")

    # fresh run claiming a rank-metrics dir: stale rank_*.jsonl from a
    # previous (possibly larger) gang would otherwise merge as live
    # members — yesterday's rank_3 joining today's 2-rank gang view
    rank_dir = str(get_param(params, "tpu_metrics_rank_dir")
                   or "").strip()
    if rank_dir and resume_from is None:
        import glob as _glob
        import os as _os
        stale = [p for pat in ("rank_*.jsonl", "merged.jsonl")
                 for p in _glob.glob(_os.path.join(rank_dir, pat))]
        for p in stale:
            try:
                _os.remove(p)
            except OSError:
                pass
        if stale:
            log.warning(f"tpu_metrics_rank_dir {rank_dir} held "
                        f"{len(stale)} snapshot file(s) from a "
                        f"previous run; cleared for this fresh run")

    # fault-marker hygiene is DRIVER-owned under the launcher: clear
    # stale fire-once markers for the whole gang once, before any
    # worker exists (no per-rank race), and have every worker — first
    # launch, bind retry, or relaunch alike — keep markers via the
    # relaunch env var. Worker-side clearing would race a first gang
    # that never reaches engine.train (a genuine bind-race loss) into
    # skipping the clear entirely.
    fi_spec = str(get_param(params, "tpu_fault_inject") or "").strip()
    fault_marker_dir = (str(get_param(params, "tpu_fault_marker") or "")
                        or ckpt_dir)
    if fi_spec and fault_marker_dir and resume_from is None:
        from ..recovery.faults import clear_fault_markers
        cleared = clear_fault_markers(fault_marker_dir)
        if cleared:
            log.warning(f"tpu_fault_inject: cleared {cleared} stale "
                        f"fire-once marker(s) from {fault_marker_dir} "
                        f"for this fresh run")

    import random as _random

    # decorrelated-jitter state for the restart backoff: N drivers (or
    # gang re-runs) sleeping IDENTICAL exponential delays would
    # stampede the coordinator port in lockstep every attempt — the
    # bind-retry counter below measures exactly those collisions
    _backoff_rng = _random.Random()
    _backoff_prev = 0.0
    attempt = 0           # restart attempts consumed (not bind retries)

    # elastic topology (docs/robustness.md "Elastic topology"): the
    # gang's LIVE width. A rank whose HOST is permanently gone — a
    # `.host_gone.rank<r>` marker from the resize chaos fault or an
    # operator, or repeated deaths without ever stamping a heartbeat —
    # narrows the gang instead of burning max_restarts relaunching at
    # full strength; the relaunched workers re-shard the rows over the
    # new width and the streamed resume path re-cuts the checkpoint
    # onto the new topology.
    live_width = int(n_processes)
    if live_width < 1:
        raise LightGBMError(f"n_processes must be >= 1, got "
                            f"{n_processes}")
    gone_dirs = [d for d in dict.fromkeys(
        (fault_marker_dir, ckpt_dir, hb_dir)) if d]
    from ..recovery.faults import clear_host_gone_markers
    if resume_from is None:
        # fresh run: yesterday's host loss must not shrink today's gang
        for d in gone_dirs:
            clear_host_gone_markers(d)
    hb_strikes: Dict[int, int] = {}

    def _apply_degrade(early_dead) -> bool:
        """Consume host-gone evidence; True = the gang narrowed and
        the caller should relaunch WITHOUT burning a restart attempt."""
        nonlocal live_width, resume_from
        gone = _gone_ranks(gone_dirs,
                           hb_dir if hb_timeout > 0 else None,
                           live_width, early_dead, hb_strikes)
        if not gone:
            return False
        if len(gone) >= live_width:
            raise LightGBMError(
                f"every live rank's host is gone ({gone}); nothing "
                f"left to degrade the gang to")
        from .. import obs
        # forced: degrades fire in the driver, before any worker
        # Config can flip metrics on — like the restart counters
        obs.inc("watchdog.degrades", len(gone), force=True)
        for d in gone_dirs:
            clear_host_gone_markers(d, ranks=gone)
        live_width -= len(gone)
        hb_strikes.clear()
        resume_from = (ckpt_dir if ckpt_dir
                       and has_resumable_checkpoint(ckpt_dir)
                       else None)
        if resume_from:
            # a FORCED-streaming job whose re-cut the capability table
            # refuses (exact f32 without the tpu_elastic_recut opt-in)
            # would fatal on EVERY narrower relaunch and burn
            # max_restarts — exactly what degrade exists to avoid.
            # Predict the verdict and restart from scratch instead.
            from .. import capabilities
            if capabilities.forced_engine(params) == "streaming":
                v, why = capabilities.stream_recut_verdict_params(
                    params)
                if v == capabilities.FATAL:
                    log.warning(
                        f"degrade-and-continue: the streamed "
                        f"checkpoint cannot be re-cut onto the "
                        f"narrower topology ({why}); restarting from "
                        f"scratch at the reduced width instead of "
                        f"burning restarts on a refused resume")
                    resume_from = None
        log.warning(
            f"degrade-and-continue: host(s) of rank(s) {gone} are "
            f"permanently gone; relaunching the gang at width "
            f"{live_width} "
            + (f"resuming from the newest topology-complete "
               f"checkpoint in {resume_from}" if resume_from else
               "with no resumable checkpoint — restarting the run "
               "from scratch at the reduced width"))
        return True

    # a marker already on disk at entry (e.g. resume="auto" after the
    # driver itself died mid-incident) narrows the FIRST gang too —
    # "missing host at gang start" must not cost a full-width attempt
    _apply_degrade([])
    try:
        os.environ[_RELAUNCH_ENV] = "1"
        while True:
            # stale-rank snapshot hygiene on EVERY (re)launch: a
            # narrower relaunch must not merge the wider topology's
            # rank_<r>.jsonl as live gang members
            _clear_rank_snapshots_beyond(rank_dir, live_width)
            result = None
            # the coordinator port race (_free_port -> jax.distributed
            # bind) loses when another process grabs the probed port
            # first; a bind failure retries on a fresh port WITHOUT
            # consuming a restart attempt
            for bind_attempt in range(3):
                result, dead, early_dead = _gang_once(
                    params, data_fn, live_width, num_boost_round,
                    platform, categorical_feature, timeout, resume_from,
                    hb_dir=hb_dir if hb_timeout > 0 else None,
                    hb_timeout=hb_timeout)
                if (result is not None and result[0] == "err"
                        and is_bind_failure(result[1])
                        and bind_attempt < 2):
                    from .. import obs
                    obs.inc("restart.bind_retries", force=True)
                    log.warning(
                        "coordinator port was reclaimed before bind "
                        "(the _free_port race); relaunching the worker "
                        "gang on a fresh port")
                    continue
                break
            if result is not None and result[0] == "ok":
                bst_str = result[1]
                break
            if result is not None:
                failure = LightGBMError(
                    f"distributed worker failed: {result[1]}")
            else:
                failure = LightGBMError(
                    "distributed training produced no result "
                    + (f"(worker ranks/exitcodes {dead} died — is "
                       f"data_fn a module-level importable callable? "
                       f"spawn re-imports its module in each worker)"
                       if dead else
                       "(workers timed out before rank 0 reported; "
                       "re-run with verbosity>=1 for worker logs)"))
            if _apply_degrade(early_dead):
                continue      # narrower relaunch; no attempt consumed
            attempt += 1
            if attempt > max_restarts:
                raise failure
            resume_from = (ckpt_dir if ckpt_dir
                           and has_resumable_checkpoint(ckpt_dir)
                           else None)
            # forced: gang restarts are exactly the restart-loop signal
            # the obs subsystem exists to surface, and the launcher
            # runs before any Config can flip tpu_metrics on
            from .. import obs
            obs.inc("restart.attempts", force=True)
            if resume_from:
                obs.inc("restart.resumes", force=True)
            delay = backoff_seconds(attempt, restart_backoff,
                                    rng=_backoff_rng,
                                    prev=_backoff_prev)
            _backoff_prev = delay
            log.warning(
                f"distributed training attempt {attempt} of "
                f"{max_restarts + 1} failed ({failure}); "
                + (f"resuming every rank from the newest checkpoint in "
                   f"{resume_from} " if resume_from else
                   "no resumable checkpoint — restarting from scratch ")
                + f"on a fresh port after {delay:.1f}s backoff")
            import time as _time
            _time.sleep(delay)
    finally:
        os.environ.pop(_RELAUNCH_ENV, None)

    # gang-wide metrics view: merge the per-rank snapshots the workers
    # dumped (counters sum, gauges latest, histograms bucket-add) into
    # <dir>/merged.jsonl and surface the straggler gauge on the driver
    rank_dir = str(get_param(params, "tpu_metrics_rank_dir")
                   or "").strip()
    if rank_dir:
        from ..obs.aggregate import merge_rank_dir
        try:
            merged = merge_rank_dir(rank_dir)
            if merged is None:
                log.warning(f"tpu_metrics_rank_dir={rank_dir!r}: no "
                            f"rank snapshots to merge")
            else:
                spread = next(
                    (m.get("value") for m in merged["metrics"]
                     if m.get("name") == "dist.round_time_spread"),
                    None)
                log.info(
                    f"merged {len(merged.get('merged_from_ranks', []))}"
                    f" rank snapshot(s) into {rank_dir}/merged.jsonl"
                    + (f" (round_time_spread={spread:.2f})"
                       if spread else ""))
        except Exception as e:
            log.warning(f"tpu_metrics_rank_dir: merge under "
                        f"{rank_dir!r} failed: {e}")

    import lightgbm_tpu as lgb
    bst = lgb.Booster(model_str=bst_str)
    log.info(f"distributed training done: {live_width} processes"
             + (f" (degraded from {n_processes} — "
                f"{n_processes - live_width} host(s) lost)"
                if live_width != n_processes else "")
             + f", {bst.num_trees()} trees collected from rank 0"
             + (f" ({attempt} restart(s))" if attempt else ""))
    return bst
