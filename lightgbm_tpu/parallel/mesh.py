"""Device mesh + collective shim: the Network layer, TPU-native.

Reference: src/network/network.cpp + linkers (UNVERIFIED — empty mount,
see SURVEY.md banner): the reference hand-implements Allreduce
(recursive-halving/doubling), Bruck AllGather and ReduceScatter over TCP
sockets / MPI, with rank discovery from a machine list.

TPU-native replacement (SURVEY.md §5 "Distributed communication backend"):
the ``jax.sharding.Mesh`` IS the machine list — rank discovery, topology
and transport all collapse into XLA collectives (psum / all_gather /
psum_scatter) over ICI (intra-slice) or DCN (multi-slice). This module
keeps learner code transport-agnostic: learners name a mesh axis and call
``lax`` collectives; tests run the same program on 8 fake CPU devices
(``--xla_force_host_platform_device_count=8``), the driver dry-runs it on
a virtual mesh, and real pods just change the device list.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # public API location varies across JAX versions
    _shard_map_impl = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import \
        shard_map as _shard_map_impl  # type: ignore

import inspect as _inspect

_SHARD_MAP_PARAMS = frozenset(
    _inspect.signature(_shard_map_impl).parameters)


def shard_map(f, **kwargs):
    """shard_map with the replication-check kwarg normalized: newer JAX
    renamed ``check_rep`` -> ``check_vma``; accept either spelling and
    translate to whatever the installed runtime supports."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _SHARD_MAP_PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map_impl(f, **kwargs)

DATA_AXIS = "data"
FEATURE_AXIS = "feature"

__all__ = ["Mesh", "NamedSharding", "P", "shard_map", "DATA_AXIS",
           "FEATURE_AXIS", "create_data_mesh", "num_devices",
           "shard_rows", "replicate", "local_mesh_positions"]


def local_mesh_positions(mesh: Mesh):
    """(positions, devices) of THIS process's addressable devices in
    mesh-flat order — the rank ids a multi-process engine computes for
    locally (the streaming engine's shard layout; one device per
    process on CPU gangs, all of them single-process)."""
    me = jax.process_index()
    flat = list(mesh.devices.flat)
    pos = [i for i, d in enumerate(flat) if d.process_index == me]
    return pos, [flat[i] for i in pos]


def num_devices() -> int:
    return jax.device_count()


def create_data_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the data axis (rows sharded, features replicated)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (DATA_AXIS,))


def create_feature_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the feature axis (columns sharded, rows replicated)
    — the feature-parallel learner's layout."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (FEATURE_AXIS,))


def create_2d_mesh(data: int, feature: int) -> Mesh:
    """2-D mesh for combined data x feature sharding (voting/feature
    learners at scale)."""
    devs = np.array(jax.devices()[:data * feature]).reshape(data, feature)
    return Mesh(devs, (DATA_AXIS, FEATURE_AXIS))


def put(mesh: Mesh, arr, spec: P):
    """Place ``arr`` with the given spec. Under a MULTI-HOST mesh the
    array is assembled from per-process local chunks
    (``jax.make_array_from_process_local_data``): for row-sharded specs
    each process contributes its OWN row shard (the reference's
    rank-aware ``pre_partition`` load, dataset_loader.cpp) and every
    process must hold the SAME padded shard shape; for replicated specs
    every process must pass identical data. Feature-sharded layouts
    (feature-parallel) have no process-local semantics here — the
    engine rejects that learner multi-host."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(arr))
    return jax.device_put(arr, sharding)


def shard_rows(mesh: Mesh, arr, extra_dims: int = 1):
    """Place an array with its leading (row) axis sharded over DATA_AXIS."""
    spec = P(DATA_AXIS, *([None] * (extra_dims - 1))) if extra_dims > 1 \
        else P(DATA_AXIS)
    return put(mesh, arr, spec)


def replicate(mesh: Mesh, arr):
    return put(mesh, arr, P())
