"""Subpackage: parallel."""
