"""Multi-host (multi-process) training entry.

Reference: the reference's distributed launch story — ``machine_list`` /
``machines`` + ``local_listen_port`` + rank discovery over sockets/MPI
(src/network/linkers_socket.cpp, dask.py's cluster orchestration,
UNVERIFIED — empty mount, see SURVEY.md banner).

TPU-native replacement: ``jax.distributed.initialize`` IS the machine
list. Each host process calls :func:`init_multihost` once before any
device use; after that, ``jax.devices()`` spans the whole slice/pod, and
every learner in this framework (data/voting/feature-parallel) runs
unchanged — the ``Mesh`` simply contains remote devices, histogram
reductions ride ICI within a slice and DCN across slices, exactly where
the reference rides its socket ReduceScatter. There is no separate
"dask" code path to maintain: sharded arrays + collectives are the
transport.

On Cloud TPU pods the coordinator/rank/process-count are discovered from
the TPU metadata automatically (argument-free call); explicit arguments
mirror the reference's machine_list semantics for other clusters.
"""
from __future__ import annotations

from typing import Optional

from ..utils import log


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Join the multi-host training job (call once per host process).

    Equivalent of the reference's ``machines=ip1:port,ip2:port`` +
    ``machine_list_file`` rank discovery: on TPU pods call with no
    arguments (auto-discovery); elsewhere pass the coordinator's
    ``ip:port``, the world size, and this process's rank.
    """
    import jax
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # the usual cause: some JAX computation (even device_count())
        # already initialized the LOCAL backend
        log.fatal(
            f"init_multihost must be the FIRST JAX call in the process "
            f"(before any Dataset/Booster construction, device queries, "
            f"or is_multihost()): {e}")
    log.info(f"multi-host initialized: process {jax.process_index()} of "
             f"{jax.process_count()}, {jax.device_count()} global / "
             f"{jax.local_device_count()} local devices")


def is_multihost() -> bool:
    """NB: initializes the local backend if nothing has yet — only call
    AFTER init_multihost (or in single-process jobs)."""
    import jax
    return jax.process_count() > 1


def global_mesh():
    """A 1-D data mesh over every device in the job (all hosts) — the
    same construction the learners use."""
    from .mesh import create_data_mesh
    return create_data_mesh()
