"""Multi-host (multi-process) training entry.

Reference: the reference's distributed launch story — ``machine_list`` /
``machines`` + ``local_listen_port`` + rank discovery over sockets/MPI
(src/network/linkers_socket.cpp, dask.py's cluster orchestration,
UNVERIFIED — empty mount, see SURVEY.md banner).

TPU-native replacement: ``jax.distributed.initialize`` IS the machine
list. Each host process calls :func:`init_multihost` once — BEFORE any
other JAX use — after which ``jax.devices()`` spans the whole slice/pod
and ``create_data_mesh()`` builds the global mesh. The data placement
layer (``parallel.mesh.put``) then assembles global arrays from
per-process local chunks via ``jax.make_array_from_process_local_data``:
each process constructs its ``Dataset`` from its OWN row shard (the
reference's rank-aware ``pre_partition`` load, dataset_loader.cpp), and
the SPMD learners consume the resulting global arrays. Cross-process
bin-boundary consistency is AUTOMATIC through the launcher layer
(``parallel.launch``: union-sample ``sync_bin_mappers``); hand-wired
jobs can still share mappers manually (``Dataset.save_binary`` on rank
0, or a ``reference=`` dataset).

Validated by a REAL 4-process localhost run in CI
(tests/test_multihost.py): four processes join one ``jax.distributed``
job on the CPU backend via ``train_distributed``, each ingests its own
row shard with synced bin mappers, trains ``tree_learner=data``, and
the model matches a single-process run on the same global data. Mean-statistic
init scores (L2/binary/poisson family) sync across processes like the
reference's ``Network::GlobalSyncUpByMean`` (boosting/gbdt.py);
percentile-based init scores warn and use the local shard.
"""
from __future__ import annotations

from typing import Optional

from ..utils import log
from ..utils.log import LightGBMError


# substrings (lowercased) that identify a TRANSIENT coordinator error
# worth retrying: the coordinator process is still coming up, or the
# connection dropped. "Already initialized" / misuse errors are not
# transient and raise immediately.
_TRANSIENT_TOKENS = ("timeout", "timed out", "deadline", "unavailable",
                     "connection", "refused", "temporarily", "reset")


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None, *,
                   connect_retries: int = 2,
                   retry_backoff: float = 1.0) -> None:
    """Join the multi-host training job (call once per host process,
    before ANY other JAX use).

    Equivalent of the reference's ``machines=ip1:port,ip2:port`` +
    ``machine_list_file`` rank discovery: on TPU pods call with no
    arguments (auto-discovery); elsewhere pass the coordinator's
    ``ip:port``, the world size, and this process's rank.

    Transient coordinator-connect failures (the coordinator not up
    yet, dropped connections) retry up to ``connect_retries`` times
    with exponential backoff before raising; non-transient errors
    (double initialization, JAX already used) raise immediately. Every
    failure mode — including timeout/connection errors that are not
    ``RuntimeError`` — surfaces as the same actionable
    ``LightGBMError``.
    """
    import time

    import jax
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    from .. import obs
    for conn_attempt in range(connect_retries + 1):
        try:
            # forced span: gang-join latency is restart-loop telemetry
            # (like the forced connect-retry counter below) and fires
            # before any Config can flip tpu_metrics on
            with obs.span("multihost/init", force=True,
                          attempt=conn_attempt):
                jax.distributed.initialize(**kwargs)
            break
        except (RuntimeError, TimeoutError, ConnectionError, OSError) as e:
            transient = any(tok in str(e).lower()
                            for tok in _TRANSIENT_TOKENS)
            if transient and conn_attempt < connect_retries:
                from .. import obs
                obs.inc("multihost.connect_retries", force=True)
                # a failed initialize leaves jax's distributed global
                # state partially set (client assigned before connect),
                # and a second initialize() would fail with the
                # non-transient "called once" error — reset it first
                try:
                    jax.distributed.shutdown()
                except Exception:
                    pass
                from ..recovery.restart import backoff_seconds
                delay = backoff_seconds(conn_attempt + 1, retry_backoff)
                log.warning(
                    f"coordinator connect attempt {conn_attempt + 1} of "
                    f"{connect_retries + 1} failed ({e}); retrying in "
                    f"{delay:.1f}s")
                time.sleep(delay)
                continue
            raise LightGBMError(
                f"jax.distributed.initialize failed: {e}. Common causes: "
                f"JAX was already used in this process (init_multihost "
                f"must be the first JAX call), initialize() was called "
                f"twice, or the coordinator at {coordinator_address!r} "
                f"is unreachable.") from e
    from .. import obs
    obs.set_gauge("multihost.process_count", jax.process_count(),
                  force=True)
    obs.set_gauge("multihost.process_index", jax.process_index(),
                  force=True)
    log.info(f"multi-host initialized: process {jax.process_index()} of "
             f"{jax.process_count()}, {jax.device_count()} global / "
             f"{jax.local_device_count()} local devices")


def is_multihost() -> bool:
    """NB: initializes the local backend if nothing has yet — only call
    AFTER init_multihost (or in single-process jobs)."""
    import jax
    return jax.process_count() > 1
