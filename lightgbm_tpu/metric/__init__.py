"""Evaluation metrics.

Reference: src/metric/*.hpp + ``Metric::CreateMetric`` (src/metric/metric
.cpp, UNVERIFIED — empty mount, see SURVEY.md banner). Metrics consume the
prediction-space output (after the objective's convert_output) except the
loglosses, which consume probabilities, matching reference behavior.

Host-side NumPy: metrics run once per ``metric_freq`` iterations on
already-computed scores, so they are not on the hot path; sort-based
metrics (AUC, NDCG) are simplest and exactly reproducible on host.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import log

EPS = 1e-15


class Metric:
    name = "base"
    higher_better = False

    def __init__(self, config):
        self.config = config

    def eval(self, pred: np.ndarray, label: np.ndarray,
             weight: Optional[np.ndarray],
             query_boundaries: Optional[np.ndarray] = None) -> List[Tuple[str, float]]:
        """Returns a list of (metric_name, value)."""
        raise NotImplementedError

    @staticmethod
    def _avg(values: np.ndarray, weight: Optional[np.ndarray]) -> float:
        if weight is None:
            return float(np.mean(values))
        return float(np.sum(values * weight) / np.sum(weight))


def _simple(name: str, higher: bool, fn) -> type:
    class _M(Metric):
        def eval(self, pred, label, weight, query_boundaries=None):
            return [(name, self._avg(fn(self, pred, label), weight))]
    _M.name = name
    _M.higher_better = higher
    _M.__name__ = f"Metric_{name}"
    return _M


L2Metric = _simple("l2", False, lambda s, p, y: (p - y) ** 2)
RMSEMetric = _simple("rmse", False, lambda s, p, y: (p - y) ** 2)
L1Metric = _simple("l1", False, lambda s, p, y: np.abs(p - y))
MAPEMetric = _simple("mape", False,
                     lambda s, p, y: np.abs(p - y) / np.maximum(np.abs(y), 1))
PoissonMetric = _simple("poisson", False,
                        lambda s, p, y: p - y * np.log(np.maximum(p, EPS)))
GammaMetric = _simple(
    "gamma", False,
    lambda s, p, y: y / np.maximum(p, EPS)
    + np.log(np.maximum(p, EPS)) - 1 - np.log(np.maximum(y, EPS)))
GammaDevianceMetric = _simple(
    "gamma_deviance", False,
    lambda s, p, y: 2.0 * (np.log(np.maximum(p, EPS) / np.maximum(y, EPS))
                           + y / np.maximum(p, EPS) - 1))


class RMSEMetricSqrt(RMSEMetric):
    def eval(self, pred, label, weight, query_boundaries=None):
        [(n, v)] = super().eval(pred, label, weight, query_boundaries)
        return [("rmse", float(np.sqrt(v)))]


class QuantileMetric(Metric):
    name = "quantile"

    def eval(self, pred, label, weight, query_boundaries=None):
        a = self.config.alpha
        d = label - pred
        loss = np.where(d >= 0, a * d, (a - 1.0) * d)
        return [("quantile", self._avg(loss, weight))]


class HuberMetric(Metric):
    name = "huber"

    def eval(self, pred, label, weight, query_boundaries=None):
        a = self.config.alpha
        d = np.abs(pred - label)
        loss = np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
        return [("huber", self._avg(loss, weight))]


class FairMetric(Metric):
    name = "fair"

    def eval(self, pred, label, weight, query_boundaries=None):
        c = self.config.fair_c
        d = np.abs(pred - label)
        loss = c * c * (d / c - np.log1p(d / c))
        return [("fair", self._avg(loss, weight))]


class TweedieMetric(Metric):
    name = "tweedie"

    def eval(self, pred, label, weight, query_boundaries=None):
        rho = self.config.tweedie_variance_power
        p = np.maximum(pred, EPS)
        loss = (-label * np.power(p, 1 - rho) / (1 - rho)
                + np.power(p, 2 - rho) / (2 - rho))
        return [("tweedie", self._avg(loss, weight))]


class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, pred, label, weight, query_boundaries=None):
        p = np.clip(pred, EPS, 1 - EPS)
        y = (label > 0).astype(np.float64)
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [("binary_logloss", self._avg(loss, weight))]


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, pred, label, weight, query_boundaries=None):
        y = (label > 0).astype(np.float64)
        err = ((pred > 0.5) != (y > 0)).astype(np.float64)
        return [("binary_error", self._avg(err, weight))]


class AUCMetric(Metric):
    name = "auc"
    higher_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        y = (label > 0).astype(np.float64)
        w = np.ones_like(y) if weight is None else weight
        order = np.argsort(pred, kind="mergesort")
        y, w, p = y[order], w[order], pred[order]
        # rank-sum with midrank tie handling
        pos_w = np.sum(w * y)
        neg_w = np.sum(w * (1 - y))
        if pos_w == 0 or neg_w == 0:
            return [("auc", 0.5)]
        cum_neg = np.cumsum(w * (1 - y))
        # group ties: average cum_neg within tied prediction blocks
        _, idx, inv = np.unique(p, return_index=True, return_inverse=True)
        start_neg = np.concatenate([[0.0], cum_neg])[idx]
        end_neg = np.concatenate(
            [cum_neg[np.concatenate([idx[1:] - 1, [len(p) - 1]])]])
        mid = (start_neg + end_neg) / 2.0
        auc = float(np.sum(w * y * mid[inv]) / (pos_w * neg_w))
        return [("auc", auc)]


class AUCMuMetric(Metric):
    """Multiclass AUC-mu (Kleiman & Page; reference:
    src/metric/multiclass_metric.hpp AucMuMetric, UNVERIFIED): mean over
    class pairs (i, j) of the binary AUC separating class-i rows from
    class-j rows, scored by pred[:, i] - pred[:, j]; optional
    ``auc_mu_weights`` flat (num_class x num_class) weight matrix."""

    name = "auc_mu"
    higher_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        pred = np.asarray(pred)
        if pred.ndim != 2:
            return [("auc_mu", 0.5)]
        K = pred.shape[1]
        label = np.asarray(label).astype(np.int64)
        wm = None
        aw = getattr(self.config, "auc_mu_weights", None)
        if aw:
            wm = np.asarray(aw, dtype=np.float64).reshape(K, K)
        auc_bin = AUCMetric(self.config)
        total, wsum = 0.0, 0.0
        for i in range(K):
            for j in range(i + 1, K):
                m = (label == i) | (label == j)
                if not m.any() or (label[m] == i).all() \
                        or (label[m] == j).all():
                    continue
                s = pred[m, i] - pred[m, j]
                y = (label[m] == i).astype(np.float64)
                w = None if weight is None else weight[m]
                a = auc_bin.eval(s, y, w)[0][1]
                pw = wm[i, j] if wm is not None else 1.0
                total += pw * a
                wsum += pw
        return [("auc_mu", total / wsum if wsum else 0.5)]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    higher_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        y = (label > 0).astype(np.float64)
        w = np.ones_like(y) if weight is None else weight
        order = np.argsort(-pred, kind="mergesort")
        y, w = y[order], w[order]
        tp = np.cumsum(w * y)
        total = np.cumsum(w)
        total_pos = tp[-1]
        if total_pos == 0:
            return [("average_precision", 0.0)]
        precision = tp / np.maximum(total, EPS)
        recall_delta = np.diff(np.concatenate([[0.0], tp])) / total_pos
        return [("average_precision", float(np.sum(precision * recall_delta)))]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, pred, label, weight, query_boundaries=None):
        # pred: [n, K] probabilities
        idx = label.astype(np.int64)
        p = np.clip(pred[np.arange(len(idx)), idx], EPS, 1.0)
        return [("multi_logloss", self._avg(-np.log(p), weight))]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, pred, label, weight, query_boundaries=None):
        k = self.config.multi_error_top_k
        idx = label.astype(np.int64)
        if k <= 1:
            err = (np.argmax(pred, axis=1) != idx).astype(np.float64)
        else:
            true_p = pred[np.arange(len(idx)), idx][:, None]
            rank = np.sum(pred > true_p, axis=1)
            err = (rank >= k).astype(np.float64)
        return [("multi_error", self._avg(err, weight))]


class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, pred, label, weight, query_boundaries=None):
        p = np.clip(pred, EPS, 1 - EPS)
        loss = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        return [("cross_entropy", self._avg(loss, weight))]


class KLDivMetric(Metric):
    name = "kullback_leibler"

    def eval(self, pred, label, weight, query_boundaries=None):
        p = np.clip(pred, EPS, 1 - EPS)
        y = np.clip(label, EPS, 1 - EPS)
        loss = (y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p)))
        return [("kullback_leibler", self._avg(loss, weight))]


# ---------------------------------------------------------------------------
# Ranking metrics (src/metric/rank_metric.hpp + dcg_calculator.cpp,
# UNVERIFIED)
# ---------------------------------------------------------------------------
def _label_gains(config, max_label: int) -> np.ndarray:
    if config.label_gain:
        g = np.asarray(config.label_gain, dtype=np.float64)
        if len(g) <= max_label:
            log.fatal("label_gain table shorter than max label")
        return g
    return (2.0 ** np.arange(max_label + 1)) - 1.0


def _dcg_at_k(labels: np.ndarray, scores: np.ndarray, k: int,
              gains: np.ndarray) -> float:
    order = np.argsort(-scores, kind="mergesort")
    top = labels[order[:k]].astype(np.int64)
    discounts = 1.0 / np.log2(np.arange(2, len(top) + 2))
    return float(np.sum(gains[top] * discounts))


class NDCGMetric(Metric):
    name = "ndcg"
    higher_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        if query_boundaries is None:
            log.fatal("ndcg metric requires query information")
        ks = self.config.eval_at
        gains = _label_gains(self.config, int(label.max()))
        results = {k: [] for k in ks}
        for qi in range(len(query_boundaries) - 1):
            s, e = query_boundaries[qi], query_boundaries[qi + 1]
            ql, qp = label[s:e], pred[s:e]
            ideal = np.sort(ql)[::-1].astype(np.int64)
            for k in ks:
                idcg = float(np.sum(
                    gains[ideal[:k]]
                    / np.log2(np.arange(2, min(k, len(ideal)) + 2))))
                if idcg > 0:
                    results[k].append(_dcg_at_k(ql, qp, k, gains) / idcg)
                else:
                    results[k].append(1.0)  # all-zero-label query counts as 1
        return [(f"ndcg@{k}", float(np.mean(results[k]))) for k in ks]


class MAPMetric(Metric):
    name = "map"
    higher_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        if query_boundaries is None:
            log.fatal("map metric requires query information")
        ks = self.config.eval_at
        results = {k: [] for k in ks}
        for qi in range(len(query_boundaries) - 1):
            s, e = query_boundaries[qi], query_boundaries[qi + 1]
            ql = (label[s:e] > 0).astype(np.float64)
            order = np.argsort(-pred[s:e], kind="mergesort")
            rel = ql[order]
            cum = np.cumsum(rel)
            prec = cum / np.arange(1, len(rel) + 1)
            for k in ks:
                nrel = rel[:k].sum()
                results[k].append(
                    float(np.sum(prec[:k] * rel[:k]) / nrel)
                    if nrel > 0 else 0.0)
        return [(f"map@{k}", float(np.mean(results[k]))) for k in ks]


_REGISTRY: Dict[str, type] = {
    "l2": L2Metric, "mse": L2Metric, "mean_squared_error": L2Metric,
    "regression": L2Metric, "regression_l2": L2Metric,
    "rmse": RMSEMetricSqrt, "root_mean_squared_error": RMSEMetricSqrt,
    "l2_root": RMSEMetricSqrt,
    "l1": L1Metric, "mae": L1Metric, "mean_absolute_error": L1Metric,
    "regression_l1": L1Metric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "auc_mu": AUCMuMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "cross_entropy": CrossEntropyMetric, "xentropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyMetric,
    "xentlambda": CrossEntropyMetric,
    "kullback_leibler": KLDivMetric, "kldiv": KLDivMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric,
    "rank_xendcg": NDCGMetric, "xendcg": NDCGMetric,
    "map": MAPMetric, "mean_average_precision": MAPMetric,
}

_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber",
    "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metric(name: str, config) -> Optional[Metric]:
    name = name.strip().lower()
    if name in ("", "na", "null", "none", "custom"):
        return None
    if name.startswith("ndcg@") or name.startswith("map@"):
        base, k = name.split("@", 1)
        import copy
        cfg = copy.copy(config)
        cfg.eval_at = [int(k)]
        return _REGISTRY[base](cfg)
    if name not in _REGISTRY:
        log.fatal(f"Unknown metric {name}")
    return _REGISTRY[name](config)


def metrics_for_config(config) -> List[Metric]:
    """Resolve the configured metric list (default = objective's metric)."""
    names = list(config.metric)
    if not names:
        default = _DEFAULT_FOR_OBJECTIVE.get(config.objective)
        names = [default] if default else []
    out = []
    for n in names:
        m = create_metric(n, config)
        if m is not None:
            out.append(m)
    return out


def eval_metric_rows(objective, metrics, name, raw, label, weight,
                     query_boundaries, num_class: int):
    """Shared eval helper: convert a raw-score matrix/vector through
    the objective and run every metric, returning the engine.eval_set
    contract — ``(data_name, metric_name, value, higher_better)``
    tuples. Both boosting engines (resident GBDT and streaming) call
    this so their eval semantics cannot drift."""
    import jax.numpy as jnp
    raw = np.asarray(raw, np.float64)
    if num_class == 1 and raw.ndim == 2:
        raw = raw[:, 0]
    pred = np.asarray(objective.convert_output(jnp.asarray(raw)))
    label = None if label is None else np.asarray(label)
    weight = None if weight is None else np.asarray(weight)
    out = []
    for m in metrics:
        for mname, value in m.eval(pred, label, weight,
                                   query_boundaries):
            out.append((name, mname, value, m.higher_better))
    return out
