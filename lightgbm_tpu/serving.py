"""Serve-side zero-downtime model hot-swap (``tpu_model_watch``).

The continuous-training loop (ROADMAP item 5) ends at a serving
process that must pick up freshly published models WITHOUT dropping a
request or recompiling its warm predict path: a trainer publishes
atomic, sha256-verified checkpoints (recovery/checkpoint.py) and the
server polls the ``latest`` pointer, adopting each new model the
moment it verifies.

Design:

- **Polling rides the predict path** (no background thread): each
  ``Booster.predict`` first calls :meth:`ModelWatcher.maybe_swap`,
  which is one monotonic-clock read when inside the poll interval
  (``tpu_model_watch_interval``, default 2 s). Swap and predict run on
  the same thread, so a request observes either the old or the new
  model atomically — ZERO dropped requests by construction. THREADING
  CONTRACT: warm adoption mutates the live engine (models list,
  caches), so predicts must serialize against swaps. The watcher owns
  that contract as code, not convention: :attr:`ModelWatcher.swap_lock`
  is a reentrant lock adoption runs under, ``Booster.predict`` wraps
  its whole model read (poll + traversal) in it, and the serving
  service's dispatch loop (serve/service.py) acquires the same lock
  around each coalesced batch — a multi-threaded server gets
  old-or-new atomicity per request for free
  (tests/test_serve_queue.py pins concurrent swap-under-load).
  Predicts on one watched booster therefore SERIALIZE — deliberate:
  the engine's predict path mutates shared caches and was never safe
  to run concurrently on one engine; scale throughput with the
  service's coalescing (one dispatch serves many requests) or more
  processes, not more threads per booster.
- **Warm adoption**: when the serving Booster has a resident engine
  and the checkpoint carries pickled trees from a compatible engine
  (GBDT / StreamingGBDT — DART/RF carry mutable per-tree state and
  take the host-model path), the watcher swaps the engine's tree list
  in place and invalidates the stacked-forest cache. The engine is
  pinned to STABLE predict shapes (pow2-padded tree count, config
  num_leaves) so successive models in the same size bucket reuse every
  compiled program — zero warm-path recompiles, CompileWatch-pinned.
  Warm adoption requires the server to share the trainer's binning
  pipeline (the adopted trees' ``threshold_bin`` values are only
  meaningful against the same BinMappers — true for a trainer serving
  its own models, or a server constructed over the same dataset/params;
  a model-file-loaded Booster takes the host-model path, which uses
  real-valued thresholds and has no such coupling).
- **Graceful degradation**: a corrupt or half-written newest
  checkpoint NEVER takes the server down — the loader falls back to
  the newest valid file (possibly the one already serving), the
  previous model keeps serving, and the ``serve.model_stale`` gauge
  flips to 1 (with ``serve.swap_failures`` counting) until a good
  checkpoint lands. ``train.freshness_lag_s`` tracks how far behind
  the served model is at every poll.

Metrics (forced — swap events are rare and must be visible even with
the metrics pillar off; docs/observability.md catalogue):
``serve.swaps``, ``serve.swap_failures``, ``serve.model_stale``,
``serve.model_iteration``, ``train.freshness_lag_s``.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from . import obs
from .recovery.checkpoint import CheckpointError, CheckpointManager
from .utils import log

__all__ = ["ModelWatcher"]

# engines whose checkpointed tree lists are safe to adopt in place:
# plain additive forests (DART rescales trees in place per iteration,
# RF folds a bias — their checkpoints swap via model_str instead)
_WARM_ENGINES = ("GBDT", "StreamingGBDT")


class ModelWatcher:
    """Polls one checkpoint directory and hot-swaps its newest valid
    model into a serving Booster (wired by the ``tpu_model_watch``
    param, or explicitly via ``Booster.watch_checkpoints``)."""

    def __init__(self, directory: str, interval: float = 2.0,
                 rank: int = 0):
        self.dir = str(directory)
        self.interval = max(float(interval), 0.0)
        self.rank = int(rank)
        self._mgr = CheckpointManager(self.dir, rank=self.rank)
        # per-watcher jitter source: N fleet replicas watching ONE
        # checkpoint dir must not stat/unpickle in lockstep after each
        # publish (thundering herd on the shared filesystem) — each
        # poll waits interval * U(0.8, 1.2), desynchronizing replicas
        # that started together within a few polls
        self._jitter = random.Random()
        self._next_wait = self.interval
        # the swap/predict serialization point (module docstring
        # THREADING CONTRACT): reentrant so a predict already holding
        # it can poll-and-swap on its own thread without deadlock
        self.swap_lock = threading.RLock()
        # first-adoption baseline: publishes from BEFORE the watch
        # started only adopt when they are not behind the model the
        # booster already holds (see the forward rule in maybe_swap)
        self._install_ns = time.time_ns()
        self._last_poll = 0.0
        self._last_sig: Optional[tuple] = None
        self._loaded_iteration = -1      # iteration currently serving
        self._loaded_key: Optional[tuple] = None   # (it, mtime_ns, size)
        self._loaded_mtime: Optional[float] = None
        self.swaps = 0
        self.failures = 0
        self.stale = False

    # ------------------------------------------------------------------
    def _file_id(self, path: str) -> Optional[tuple]:
        try:
            st = os.stat(path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _signature(self) -> tuple:
        """Cheap change detector: (latest-pointer text, newest NAMED
        checkpoint iteration, newest file's (mtime_ns, size)). Any
        publish — good, torn, a clobbered pointer, or a REPUBLISH at
        the same iteration (continuous training retrains N rounds
        every cycle, so successive models share an iteration count) —
        changes it; nothing changed means no load attempt, so
        steady-state polls cost a few stats, not an unpickle."""
        ptr = None
        try:
            with open(self._mgr.latest_pointer) as f:
                ptr = f.read().strip()
        except OSError:
            pass
        its = self._mgr.iterations()
        newest = its[-1] if its else None
        newest_id = (self._file_id(self._mgr.path(newest))
                     if newest is not None else None)
        return (ptr, newest, newest_id)

    def _newest_named_iteration(self) -> int:
        its = self._mgr.iterations()
        return its[-1] if its else -1

    # ------------------------------------------------------------------
    def maybe_swap(self, booster, force: bool = False) -> bool:
        """Poll (rate-limited unless ``force``) and swap if a new
        checkpoint verifies. Returns True when a swap happened. Never
        raises for checkpoint-side problems — a serving process must
        keep serving the previous model through ANY publish failure."""
        now = time.monotonic()
        if not force and now - self._last_poll < self._next_wait:
            return False
        self._last_poll = now
        # draw the NEXT poll's jittered wait (interval=0 stays 0 —
        # tests and force-poll callers poll every call)
        self._next_wait = self.interval * self._jitter.uniform(0.8, 1.2)
        try:
            sig = self._signature()
        except Exception:
            return False
        if sig == self._last_sig and not force:
            self._refresh_freshness()
            return False
        newest_id = sig[2]        # newest NAMED file's (mtime_ns, size)
        swapped = False
        try:
            state = self._mgr.load()
        except CheckpointError as e:
            # deterministic verification failure: nothing valid AT ALL
            # (or dir empty) — keep serving, and COMMIT the signature
            # (the same bytes fail the same way; re-unpickling every
            # poll would be waste)
            self._last_sig = sig
            if sig[1] is not None:       # something IS published
                self.failures += 1
                obs.inc("serve.swap_failures", force=True)
                log.warning(f"model watch: no valid checkpoint in "
                            f"{self.dir} ({e}); keeping the current "
                            f"model")
            self._update_stale(newest_id)
            return False
        except Exception as e:
            # TRANSIENT failure (I/O blip, memory pressure mid-
            # unpickle): keep serving but do NOT commit the signature —
            # the next poll must retry this same publish, or a one-off
            # error would pin the server on the old model until the
            # NEXT publish with no staleness alert
            self.failures += 1
            obs.inc("serve.swap_failures", force=True)
            log.warning(f"model watch: cannot read {self.dir} ({e}); "
                        f"keeping the current model (will retry)")
            self._update_stale(newest_id)
            return False
        it = int(state.get("iteration", -1))
        path = state.get("_checkpoint_path")
        file_id = self._file_id(path) if path else None
        key = (it, file_id)
        # adopt only FORWARD: a checkpoint file no older than the one
        # serving. The loader's corruption fallback can hand back an
        # OLDER on-disk checkpoint than the model already in memory
        # (newest torn, previous still on disk) — swapping to it would
        # silently downgrade the served model; staleness flags it
        # instead and the next good publish moves forward again. A
        # REPUBLISH at the same iteration (continuous training) is a
        # newer file and swaps normally. FIRST adoption baselines
        # against the model the booster already holds: a publish from
        # BEFORE the watch started (a trainer watching its own
        # checkpoint dir finds its latest ROUND-BOUNDARY snapshot — a
        # prefix of the model in memory) must not downgrade it; it
        # adopts only when not behind (iteration >=), while anything
        # published AFTER the watch started adopts unconditionally.
        if self._loaded_key is None:
            forward = (file_id is not None
                       and file_id[0] >= self._install_ns) \
                or it >= self._booster_iteration(booster)
        else:
            forward = (self._loaded_key[1] is None
                       or (file_id is not None
                           and file_id[0] >= self._loaded_key[1][0]))
        if key != self._loaded_key and forward:
            try:
                # adoption mutates the live engine: hold the swap lock
                # so a concurrent predict (another thread on this
                # booster, or the service dispatch loop) sees the old
                # or the new model, never a mid-swap engine
                with self.swap_lock:
                    self._adopt(booster, state)
                self._loaded_iteration = it
                self._loaded_key = key
                self._loaded_mtime = self._ckpt_mtime(state)
                self.swaps += 1
                obs.inc("serve.swaps", force=True)
                obs.set_gauge("serve.model_iteration", it, force=True)
                log.info(f"model watch: hot-swapped to checkpoint "
                         f"iteration {it} from {self.dir} "
                         f"(swap #{self.swaps})")
                swapped = True
            except Exception as e:
                self.failures += 1
                obs.inc("serve.swap_failures", force=True)
                log.warning(f"model watch: cannot adopt checkpoint "
                            f"iteration {it} ({e}); keeping the "
                            f"current model (will retry)")
                # like a transient LOAD failure: do not commit the
                # signature, so the next poll retries this publish
                # instead of pinning on the old model until the next
                self._update_stale(newest_id)
                self._refresh_freshness()
                return False
        self._last_sig = sig
        self._update_stale(newest_id)
        self._refresh_freshness()
        return swapped

    @staticmethod
    def _booster_iteration(booster) -> int:
        try:
            return int(booster.current_iteration())
        except Exception:
            return -1

    def _update_stale(self, newest_id: Optional[tuple]) -> None:
        """Stale = the newest PUBLISHED file is not the one serving —
        a torn newest write the loader skipped, a fallback the watcher
        refused to downgrade to, or an adoption failure. An empty dir
        (nothing published yet) is not stale."""
        adopted_id = (self._loaded_key[1] if self._loaded_key
                      else None)
        self._set_stale(newest_id is not None
                        and newest_id != adopted_id)

    # ------------------------------------------------------------------
    def _ckpt_mtime(self, state: Dict[str, Any]) -> Optional[float]:
        path = state.get("_checkpoint_path")
        if not path:
            return None
        try:
            return os.stat(path).st_mtime
        except OSError:
            return None

    def _refresh_freshness(self) -> None:
        """train.freshness_lag_s = age of the checkpoint the served
        model came from — the end-to-end publish->serve lag the chaos
        benchmark reports, and the gauge that keeps growing while a
        corrupt publisher leaves the server pinned on an old model."""
        if self._loaded_mtime is not None:
            obs.set_gauge("train.freshness_lag_s",
                          max(0.0, time.time() - self._loaded_mtime),
                          force=True)

    def _set_stale(self, stale: bool) -> None:
        stale = bool(stale)
        if stale != self.stale:
            log.warning(f"model watch: serving model is now "
                        f"{'STALE' if stale else 'fresh'} "
                        f"(iteration {self._loaded_iteration}, newest "
                        f"published {self._newest_named_iteration()})")
        self.stale = stale
        obs.set_gauge("serve.model_stale", 1.0 if stale else 0.0,
                      force=True)

    # ------------------------------------------------------------------
    def _adopt(self, booster, state: Dict[str, Any]) -> None:
        """Swap the checkpoint's model into ``booster`` — warm
        in-engine tree adoption where safe, host-model rebuild
        otherwise. Raises on an unusable checkpoint (caught by
        maybe_swap: the previous model keeps serving)."""
        est = state.get("engine") or {}
        trees = est.get("models")
        eng = getattr(booster, "_engine", None)
        if (eng is not None and trees is not None
                and est.get("engine") in _WARM_ENGINES
                and type(eng).__name__ in _WARM_ENGINES
                # tree count must factor through THIS engine's
                # num_class (a multiclass checkpoint adopted into a
                # binary server would traverse the wrong class slots —
                # it takes the host-model path instead)
                and int(state.get("iteration", -1))
                * max(eng.num_class, 1) == len(trees)):
            # warm path: adopt the exact pickled trees; the stacked-
            # forest cache rebuilds once (a cache MISS, not a compile —
            # shapes stay bucketed via _stable_predict_shapes)
            eng.models = list(trees)
            eng.iter_ = len(eng.models) // max(eng.num_class, 1)
            if est.get("init_scores") is not None:
                eng.init_scores = np.asarray(est["init_scores"],
                                             np.float64)
            if hasattr(eng, "_invalidate_forest_cache"):
                eng._invalidate_forest_cache()
            else:
                eng._models_version = getattr(eng, "_models_version",
                                              0) + 1
            eng._hm_cache = (None, None)
            eng._stable_predict_shapes = True
            # an earlier swap may have taken the host-model path and
            # set _from_model, which predict() checks FIRST — leaving
            # it would make this (and every later) warm swap invisible
            booster._from_model = None
        else:
            model_str = state.get("model_str")
            if not model_str:
                raise CheckpointError(
                    "checkpoint carries neither adoptable engine trees "
                    "nor model_str")
            from .io.model_text import load_model_string
            booster._from_model = load_model_string(model_str)
        bstate = state.get("booster") or {}
        booster.best_iteration = int(bstate.get("best_iteration", -1))
        booster._host_model_cache = None
