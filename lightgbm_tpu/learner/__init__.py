"""Subpackage: learner."""
