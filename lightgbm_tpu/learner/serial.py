"""Serial (single-device) leaf-wise tree learner.

Reference: ``SerialTreeLearner::Train`` (src/treelearner/serial_tree_learner
.cpp, UNVERIFIED — empty mount, see SURVEY.md banner): best-first growth —
repeat ``num_leaves - 1`` times: construct the smaller new leaf's
histogram, derive the sibling by SUBTRACTION from the parent, find each
leaf's best split, expand the globally best leaf, partition its rows.

TPU-first design (SURVEY.md §7.1):
- The reference's ``DataPartition`` per-leaf index buckets become a per-row
  ``leaf_id`` vector; splitting a leaf is a masked ``where`` update — no
  dynamic shapes.
- The whole growth loop is ONE ``lax.while_loop`` inside jit; tree
  structure lives in fixed-size flat arrays exactly like the reference's
  ``Tree`` (left/right child, ``~leaf`` encoding for leaf children).
- The histogram pool (``HistogramPool`` LRU in the reference) becomes a
  dense ``[num_leaves, F, B, 3]`` array — every active leaf's histogram is
  retained so sibling subtraction is a slice. For very wide datasets this
  trades memory for simplicity; a pooled variant can come later.
- Leaf-membership masking makes each histogram a full-data scan; the
  subtraction trick still halves the work. A partition-gather variant
  (contiguous row slices per leaf, as the reference keeps) is the planned
  optimization once correctness is locked.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histogram
from ..ops.split import (NEG_INF, SplitConfig, calc_leaf_output,
                         find_best_split)


@dataclasses.dataclass(frozen=True)
class GrowConfig:
    """Static tree-growth hyperparameters."""

    num_leaves: int = 31
    max_depth: int = -1
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    num_bins: int = 256
    rows_per_block: int = 1024
    precise_histogram: bool = False
    # mesh axis to reduce histograms over (data-parallel learner): rows are
    # sharded across this axis and every histogram / leaf-sum becomes a
    # psum — the TPU-native replacement for the reference's ReduceScatter
    # over sockets (data_parallel_tree_learner.cpp, SURVEY.md §3.4)
    axis_name: str = ""

    @property
    def split_config(self) -> SplitConfig:
        return SplitConfig(
            lambda_l1=self.lambda_l1, lambda_l2=self.lambda_l2,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            min_gain_to_split=self.min_gain_to_split,
            max_delta_step=self.max_delta_step)


class GrowState(NamedTuple):
    """while_loop carry for one tree's growth."""

    split_idx: jnp.ndarray          # i: next internal node index
    num_leaves: jnp.ndarray         # leaves allocated so far
    has_split: jnp.ndarray          # any valid split pending?
    leaf_id: jnp.ndarray            # [n] int32 per-row leaf assignment
    leaf_hist: jnp.ndarray          # [L, F, B, 3]
    leaf_sums: jnp.ndarray          # [L, 3] (grad, hess, count)
    leaf_depth: jnp.ndarray         # [L]
    best_gain: jnp.ndarray          # [L]
    best_feature: jnp.ndarray       # [L]
    best_threshold: jnp.ndarray     # [L]
    best_default_left: jnp.ndarray  # [L] bool
    best_left_sums: jnp.ndarray     # [L, 3]
    best_right_sums: jnp.ndarray    # [L, 3]
    # tree structure (mirrors Tree's flat arrays, src/io/tree.cpp)
    split_feature: jnp.ndarray      # [L-1]
    threshold_bin: jnp.ndarray      # [L-1]
    default_left: jnp.ndarray       # [L-1] bool
    left_child: jnp.ndarray         # [L-1] (node idx, or ~leaf if < 0)
    right_child: jnp.ndarray        # [L-1]
    split_gain: jnp.ndarray         # [L-1]
    internal_value: jnp.ndarray     # [L-1]
    internal_count: jnp.ndarray     # [L-1]
    leaf_value: jnp.ndarray         # [L]
    leaf_count: jnp.ndarray         # [L]
    leaf_weight: jnp.ndarray        # [L]  (sum_hess)
    leaf_parent: jnp.ndarray        # [L]
    leaf_is_left: jnp.ndarray       # [L] bool


def _masked_gains(state_gain, leaf_depth, num_leaves, max_depth):
    L = state_gain.shape[0]
    active = jnp.arange(L, dtype=jnp.int32) < num_leaves
    gains = jnp.where(active, state_gain, NEG_INF)
    if max_depth > 0:
        gains = jnp.where(leaf_depth < max_depth, gains, NEG_INF)
    return gains


@functools.partial(jax.jit, static_argnames=("cfg",))
def grow_tree(bins: jax.Array, vals: jax.Array, feat_num_bin: jax.Array,
              feat_has_nan: jax.Array, allowed_feature: jax.Array,
              cfg: GrowConfig) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """Grow one leaf-wise tree.

    Args:
      bins: ``[n_rows, F]`` uint8/16 binned matrix (row count must be a
        multiple of ``cfg.rows_per_block``; pad rows carry zero vals).
      vals: ``[n_rows, 3]`` float32 (grad*mask, hess*mask, mask).
      feat_num_bin / feat_has_nan: ``[F]`` per-feature bin metadata.
      allowed_feature: ``[F]`` bool feature-sampling mask for this tree.
      cfg: static growth config.

    Returns:
      (tree dict of fixed-size arrays + ``num_leaves`` actually used,
       per-row ``leaf_id``).
    """
    n_rows, F = bins.shape
    L = cfg.num_leaves
    B = cfg.num_bins
    scfg = cfg.split_config

    def hist_fn(v):
        h = build_histogram(bins, v, num_bins=B,
                            rows_per_block=cfg.rows_per_block,
                            precise=cfg.precise_histogram)
        if cfg.axis_name:
            h = jax.lax.psum(h, cfg.axis_name)
        return h

    def best_fn(hist, sums):
        return find_best_split(hist, sums, feat_num_bin, feat_has_nan,
                               allowed_feature, scfg)

    root_hist = hist_fn(vals)
    root_sums = jnp.sum(vals, axis=0)
    if cfg.axis_name:
        root_sums = jax.lax.psum(root_sums, cfg.axis_name)
    root_best = best_fn(root_hist, root_sums)

    def set0(arr, value):
        return arr.at[0].set(value)

    i32 = jnp.int32
    state = GrowState(
        split_idx=jnp.array(0, i32),
        num_leaves=jnp.array(1, i32),
        has_split=jnp.isfinite(root_best["gain"]),
        leaf_id=jnp.zeros(n_rows, dtype=i32),
        leaf_hist=set0(jnp.zeros((L, F, B, 3), jnp.float32), root_hist),
        leaf_sums=set0(jnp.zeros((L, 3), jnp.float32), root_sums),
        leaf_depth=jnp.zeros(L, i32),
        best_gain=set0(jnp.full(L, NEG_INF), root_best["gain"]),
        best_feature=set0(jnp.zeros(L, i32), root_best["feature"]),
        best_threshold=set0(jnp.zeros(L, i32), root_best["threshold_bin"]),
        best_default_left=set0(jnp.zeros(L, jnp.bool_),
                               root_best["default_left"]),
        best_left_sums=set0(jnp.zeros((L, 3), jnp.float32),
                            root_best["left_sums"]),
        best_right_sums=set0(jnp.zeros((L, 3), jnp.float32),
                             root_best["right_sums"]),
        split_feature=jnp.zeros(max(L - 1, 1), i32),
        threshold_bin=jnp.zeros(max(L - 1, 1), i32),
        default_left=jnp.zeros(max(L - 1, 1), jnp.bool_),
        left_child=jnp.zeros(max(L - 1, 1), i32),
        right_child=jnp.zeros(max(L - 1, 1), i32),
        split_gain=jnp.zeros(max(L - 1, 1), jnp.float32),
        internal_value=jnp.zeros(max(L - 1, 1), jnp.float32),
        internal_count=jnp.zeros(max(L - 1, 1), jnp.float32),
        leaf_value=set0(jnp.zeros(L, jnp.float32),
                        calc_leaf_output(root_sums[0], root_sums[1],
                                         cfg.lambda_l1, cfg.lambda_l2,
                                         cfg.max_delta_step)),
        leaf_count=set0(jnp.zeros(L, jnp.float32), root_sums[2]),
        leaf_weight=set0(jnp.zeros(L, jnp.float32), root_sums[1]),
        leaf_parent=jnp.full(L, -1, i32),
        leaf_is_left=jnp.zeros(L, jnp.bool_),
    )

    def cond(s: GrowState):
        return (s.split_idx < L - 1) & s.has_split

    def body(s: GrowState) -> GrowState:
        gains = _masked_gains(s.best_gain, s.leaf_depth, s.num_leaves,
                              cfg.max_depth)
        best_leaf = jnp.argmax(gains).astype(i32)
        gain = gains[best_leaf]
        node = s.split_idx
        new_leaf = s.num_leaves

        feature = s.best_feature[best_leaf]
        tbin = s.best_threshold[best_leaf]
        dleft = s.best_default_left[best_leaf]
        lsums = s.best_left_sums[best_leaf]
        rsums = s.best_right_sums[best_leaf]

        # ---- partition: update per-row leaf ids (DataPartition::Split) ----
        col = jnp.take(bins, feature, axis=1).astype(i32)
        is_missing = feat_has_nan[feature] & (col == feat_num_bin[feature] - 1)
        goes_left = jnp.where(is_missing, dleft, col <= tbin)
        in_leaf = s.leaf_id == best_leaf
        leaf_id = jnp.where(in_leaf & ~goes_left, new_leaf, s.leaf_id)

        # ---- histograms: build smaller child, subtract for sibling -------
        left_smaller = lsums[2] <= rsums[2]
        smaller_leaf = jnp.where(left_smaller, best_leaf, new_leaf)
        small_mask = (leaf_id == smaller_leaf).astype(jnp.float32)
        small_hist = hist_fn(vals * small_mask[:, None])
        parent_hist = s.leaf_hist[best_leaf]
        large_hist = parent_hist - small_hist
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        leaf_hist = (s.leaf_hist.at[best_leaf].set(left_hist)
                     .at[new_leaf].set(right_hist))

        # ---- new best splits for both children ---------------------------
        bl = best_fn(left_hist, lsums)
        br = best_fn(right_hist, rsums)

        def upd2(arr, v_left, v_right):
            return arr.at[best_leaf].set(v_left).at[new_leaf].set(v_right)

        psums = s.leaf_sums[best_leaf]
        depth = s.leaf_depth[best_leaf] + 1

        # ---- tree wiring (Tree::Split) -----------------------------------
        p = s.leaf_parent[best_leaf]
        p_safe = jnp.maximum(p, 0)
        was_left = s.leaf_is_left[best_leaf]
        lc = jnp.where(
            (p >= 0) & was_left, s.left_child.at[p_safe].set(node),
            s.left_child)
        rc = jnp.where(
            (p >= 0) & ~was_left, s.right_child.at[p_safe].set(node),
            s.right_child)
        lc = lc.at[node].set(-best_leaf - 1)     # ~leaf encoding
        rc = rc.at[node].set(-new_leaf - 1)

        lval = calc_leaf_output(lsums[0], lsums[1], cfg.lambda_l1,
                                cfg.lambda_l2, cfg.max_delta_step)
        rval = calc_leaf_output(rsums[0], rsums[1], cfg.lambda_l1,
                                cfg.lambda_l2, cfg.max_delta_step)

        new = GrowState(
            split_idx=node + 1,
            num_leaves=new_leaf + 1,
            has_split=jnp.array(True),  # recomputed below
            leaf_id=leaf_id,
            leaf_hist=leaf_hist,
            leaf_sums=upd2(s.leaf_sums, lsums, rsums),
            leaf_depth=upd2(s.leaf_depth, depth, depth),
            best_gain=upd2(s.best_gain, bl["gain"], br["gain"]),
            best_feature=upd2(s.best_feature, bl["feature"], br["feature"]),
            best_threshold=upd2(s.best_threshold, bl["threshold_bin"],
                                br["threshold_bin"]),
            best_default_left=upd2(s.best_default_left, bl["default_left"],
                                   br["default_left"]),
            best_left_sums=upd2(s.best_left_sums, bl["left_sums"],
                                br["left_sums"]),
            best_right_sums=upd2(s.best_right_sums, bl["right_sums"],
                                 br["right_sums"]),
            split_feature=s.split_feature.at[node].set(feature),
            threshold_bin=s.threshold_bin.at[node].set(tbin),
            default_left=s.default_left.at[node].set(dleft),
            left_child=lc,
            right_child=rc,
            split_gain=s.split_gain.at[node].set(gain),
            internal_value=s.internal_value.at[node].set(
                calc_leaf_output(psums[0], psums[1], cfg.lambda_l1,
                                 cfg.lambda_l2, cfg.max_delta_step)),
            internal_count=s.internal_count.at[node].set(psums[2]),
            leaf_value=upd2(s.leaf_value, lval, rval),
            leaf_count=upd2(s.leaf_count, lsums[2], rsums[2]),
            leaf_weight=upd2(s.leaf_weight, lsums[1], rsums[1]),
            leaf_parent=upd2(s.leaf_parent, node, node),
            leaf_is_left=upd2(s.leaf_is_left, jnp.array(True),
                              jnp.array(False)),
        )
        next_gains = _masked_gains(new.best_gain, new.leaf_depth,
                                   new.num_leaves, cfg.max_depth)
        return new._replace(has_split=jnp.isfinite(jnp.max(next_gains)))

    final = jax.lax.while_loop(cond, body, state)

    tree = {
        "num_leaves": final.num_leaves,
        "split_feature": final.split_feature,
        "threshold_bin": final.threshold_bin,
        "default_left": final.default_left,
        "left_child": final.left_child,
        "right_child": final.right_child,
        "split_gain": final.split_gain,
        "internal_value": final.internal_value,
        "internal_count": final.internal_count,
        "leaf_value": final.leaf_value,
        "leaf_count": final.leaf_count,
        "leaf_weight": final.leaf_weight,
    }
    return tree, final.leaf_id
