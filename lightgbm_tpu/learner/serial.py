"""Leaf-wise tree learner: batched best-first growth under jit.

Reference: ``SerialTreeLearner::Train`` (src/treelearner/serial_tree_learner
.cpp, UNVERIFIED — empty mount, see SURVEY.md banner): best-first growth —
repeatedly construct the smaller new leaf's histogram, derive the sibling
by SUBTRACTION from the parent, find per-leaf best splits, expand the best
leaf, partition its rows.

TPU-first design (SURVEY.md §7.1):
- The reference's ``DataPartition`` per-leaf index buckets become a per-row
  ``leaf_id`` vector; splitting is a masked ``where`` update — no dynamic
  shapes.
- The growth loop is ONE ``lax.while_loop``; tree structure lives in
  fixed-size flat arrays exactly like the reference's ``Tree`` (~leaf child
  encoding). Each array has one trailing TRASH slot so vectorized scatters
  for inactive batch lanes are harmless.
- BATCHED best-first: each round expands the top-``leaf_batch`` leaves at
  once, and the Pallas kernel (ops/pallas_histogram.py) computes ALL their
  smaller-child histograms in one fused data scan — the masks pack into
  the matmul N dimension, amortizing both the scan and the MXU's N-padding.
  ``leaf_batch=1`` reproduces the reference's exact leaf-wise order; larger
  batches are a bounded relaxation (each round's choices are still the
  current best leaves) trading exact split ORDER for ~10-20x fewer scans.
- The histogram pool (``HistogramPool`` LRU) becomes a dense
  ``[L+1, F, B, 3]`` array so sibling subtraction is a slice.
- Data-parallel: with ``cfg.axis_name`` set, rows are sharded over that
  mesh axis and every histogram/leaf-sum is psum'd — the TPU-native
  replacement for the reference's socket ReduceScatter (SURVEY.md §3.4).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.pallas_histogram import (multi_leaf_histogram,
                                    multi_leaf_histogram_xla)
from ..ops.split import (NEG_INF, SplitConfig, calc_leaf_output,
                         elect_best, find_best_split, per_feature_gains,
                         smooth_output)


@dataclasses.dataclass(frozen=True)
class GrowConfig:
    """Static tree-growth hyperparameters."""

    num_leaves: int = 31
    max_depth: int = -1
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    num_bins: int = 256
    rows_per_block: int = 1024
    precise_histogram: bool = False
    # number of leaves expanded per round (1 = exact reference order)
    leaf_batch: int = 1
    # use the fused Pallas kernel (TPU) vs the XLA einsum fallback
    use_pallas: bool = False
    # quantized-gradient int8 x int8 -> int32 kernel variant (exact
    # integer accumulation at 2x MXU rate); only valid when vals carry
    # small integer levels (use_quantized_grad, engine-enforced)
    int_hist: bool = False
    # GOSS histogram-only compaction: histograms scan the compacted
    # sampled-row buffer (grow_tree's `compact` argument) while the
    # full-row partition/score path stays masked
    hist_compact: bool = False
    # forced splits (forcedsplits_filename): number of entries in the
    # PREORDER-flattened forced-split table (grow_tree's `forced`
    # argument; parents must precede children — the target-slot
    # resolution depends on it); the first n_forced growth rounds
    # apply them one per round, engine-gated to the serial pool-mode
    # learner
    n_forced: int = 0
    # mesh axis for data-parallel histogram reduction ("" = single device)
    axis_name: str = ""
    # -- distributed modes (SURVEY.md §3.4) ---------------------------
    # packed quantized collective wire (tpu_hist_packed_wire): with
    # use_quantized_grad, each (g,h) level-sum pair rides ONE int32
    # (g in the high 16 bits, non-negative h in the low 16) and count
    # rides a second int32 — 2/3 of the f32 psum payload, bit-exact.
    # A 3-scalar guard psum checks sum-of-local-extreme bounds per
    # round; any risk of int16 overflow (or a negative hessian) falls
    # back to the f32 reduction inside the same jitted step.
    packed_wire: bool = False
    # data-parallel + hist_scatter: ReduceScatter feature ownership —
    # each device reduces/owns F/num_shards features, finds its local
    # best, and the winner is elected by all_gather
    # (data_parallel_tree_learner.cpp)
    hist_scatter: bool = False
    num_shards: int = 1
    # data-parallel + voting: PV-Tree — local top_k feature votes,
    # global top-2k elected, only elected columns psum'd
    # (voting_parallel_tree_learner.cpp)
    voting: bool = False
    top_k: int = 20
    # feature-parallel: rows replicated, feature columns sharded over
    # this axis; split search local, winner elected, partition via
    # ownership-psum (feature_parallel_tree_learner.cpp)
    feature_axis: str = ""
    # constraints (monotone_constraints.hpp; ColSampler interaction
    # constraints): zero-cost when False. monotone_intermediate uses
    # the realized child outputs as the children's bounds
    # (IntermediateLeafConstraints) instead of basic's midpoint —
    # WITHOUT the reference's retroactive ancestor updates (documented
    # divergence); monotone_penalty discounts constrained-feature
    # splits near the root
    has_monotone: bool = False
    monotone_intermediate: bool = False
    # advanced mode (AdvancedLeafConstraints, monotone_constraints.hpp):
    # intermediate's per-round bound recompute, but each node's bound
    # aggregates only the opposing subtree's BOUNDARY-ADJACENT strip —
    # leaves whose split-feature bin range touches the node's threshold
    # — instead of the whole subtree (shielded leaves are ordered
    # transitively through the strip chain). Tracked via per-leaf
    # per-feature bin-range carries.
    monotone_advanced: bool = False
    monotone_penalty: float = 0.0
    has_interaction: bool = False
    # EFB (dataset_loader.cpp FastFeatureBundling): bins is the bundled
    # PHYSICAL matrix; histograms are expanded to logical features via
    # the bundle maps before split finding. Mutually exclusive with
    # hist_scatter / feature_axis (engine enforces).
    has_bundles: bool = False
    # True: no [L+1, F, B, 3] histogram pool — both children are
    # histogrammed directly each round (one scan, masks packed into the
    # matmul N dim), bounding memory to O(leaf_batch * F * B)
    hist_rebuild: bool = False
    # leaf-ordered device row partition (ops/partition.py;
    # tpu_hist_partition): rows ride the carry physically grouped by
    # leaf (per-leaf offset/count tables + a stable cumsum front/back
    # move per round), and each round's histogram scans only the
    # elected children's padded spans — a lax.switch over a static pow2
    # budget ladder, falling back to the masked full scan whenever the
    # spans would not shrink it. Siblings still come from pool
    # subtraction (or ride the rebuild scan's N-packing).
    partition: bool = False
    # block size of the TPU compact_rows-based repartition move
    # (<= 1024, divides the padded row count; the engine computes it)
    part_rpb: int = 1024
    # per-NODE column sampling (ColSampler feature_fraction_bynode)
    feature_fraction_bynode: float = 1.0
    # CEGB gain discounts (cost_effective_gradient_boosting.hpp)
    has_cegb: bool = False
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    # lazy per-row feature-acquisition penalty: grow_tree's `lazy`
    # argument carries (U [n, F] acquired-matrix, penalty [F]); each
    # candidate child's penalty is penalty[f] x #unacquired rows,
    # counted with a membership-mask matmul per round
    has_cegb_lazy: bool = False
    # path smoothing (feature_histogram.hpp USE_SMOOTHING): children
    # shrink toward the parent leaf's stored output by n/(n+alpha)
    path_smooth: float = 0.0
    # extra_trees (extremely randomized trees): one random numerical
    # threshold per feature per node, drawn from node_key + extra_seed
    extra_trees: bool = False
    extra_seed: int = 6
    # feature_contri per-feature gain multipliers (the `contri` array
    # argument of grow_tree)
    has_contri: bool = False
    # categorical split search (zero-cost when has_categorical=False);
    # cat_positions: static categorical indices for the sliced fast
    # path (empty under scatter/feature-parallel whose search space is
    # a dynamic shard)
    has_categorical: bool = False
    cat_positions: Tuple = ()
    max_cat_threshold: int = 32
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100

    @property
    def cat_words(self) -> int:
        """uint32 words per categorical bitset (over bins)."""
        return (self.num_bins + 31) // 32

    @property
    def split_config(self) -> SplitConfig:
        return SplitConfig(
            lambda_l1=self.lambda_l1, lambda_l2=self.lambda_l2,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            min_gain_to_split=self.min_gain_to_split,
            max_delta_step=self.max_delta_step,
            has_categorical=self.has_categorical,
            cat_positions=self.cat_positions,
            max_cat_threshold=self.max_cat_threshold,
            cat_smooth=self.cat_smooth, cat_l2=self.cat_l2,
            max_cat_to_onehot=self.max_cat_to_onehot,
            min_data_per_group=self.min_data_per_group,
            has_monotone=self.has_monotone,
            monotone_penalty=self.monotone_penalty,
            has_cegb=self.has_cegb,
            cegb_tradeoff=self.cegb_tradeoff,
            cegb_penalty_split=self.cegb_penalty_split,
            path_smooth=self.path_smooth,
            extra_trees=self.extra_trees,
            has_contri=self.has_contri)


class GrowState(NamedTuple):
    """while_loop carry. Leaf arrays sized L+1 (slot L = trash); node
    arrays sized L (slot L-1 = trash; real nodes use 0..L-2).

    Carry-width note (round-6 %copy trim): per-leaf/per-node float
    stats that update together are PACKED into one array each
    (``best_lr_sums``, ``node_vcg``, ``leaf_vcw``, ``leaf_bounds``) —
    the round-5 trace attributed ~9% of device busy to while-loop
    ``%copy`` traffic whose cost is per-ARRAY overhead, so fewer carry
    tuple elements means fewer copies per round at identical numerics.

    Donation note (round 7, ``tpu_donate`` — docs/perf.md "Iteration
    floor"): this carry — including the leaf-ordered partition arrays
    (``part_bins``/``part_vals``, the largest elements) — lives
    entirely INSIDE grow_tree's jit, and ``lax.while_loop`` exposes no
    donation control; XLA's buffer assignment already aliases the
    carry slots where liveness permits. The jit-boundary carries the
    donation pass CAN reach (the step/chunk score, valid scores, the
    streamed score slots, cegb_U) donate in boosting/gbdt.py and
    boosting/streaming.py; the residual in-loop ``%copy`` is attacked
    structurally (fewer arrays, above), not by donation.
    """

    split_idx: jnp.ndarray
    num_leaves: jnp.ndarray
    has_split: jnp.ndarray
    leaf_id: jnp.ndarray            # [n]
    leaf_hist: jnp.ndarray          # [L+1, F, B, 3]
    leaf_sums: jnp.ndarray          # [L+1, 3]
    leaf_depth: jnp.ndarray         # [L+1]
    best_gain: jnp.ndarray          # [L+1]
    best_feature: jnp.ndarray
    best_threshold: jnp.ndarray
    best_default_left: jnp.ndarray
    best_lr_sums: jnp.ndarray       # [L+1, 2, 3] (left, right)
    best_is_cat: jnp.ndarray        # [L+1]
    best_cat_bitset: jnp.ndarray    # [L+1, W]
    split_feature: jnp.ndarray      # [L]
    threshold_bin: jnp.ndarray
    default_left: jnp.ndarray
    node_is_cat: jnp.ndarray        # [L]
    node_cat_bitset: jnp.ndarray    # [L, W]
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    node_vcg: jnp.ndarray           # [L, 3] (internal value/count/gain)
    leaf_vcw: jnp.ndarray           # [L+1, 3] (value, count, weight)
    leaf_parent: jnp.ndarray
    leaf_is_left: jnp.ndarray
    # monotone "basic" bounds ([L+1, 2] = lower/upper; ±inf when
    # unconstrained) and interaction-constraint path features
    # ([L+1, F or 1-dummy]; the per-leaf allowed set is derived from
    # this at split time)
    leaf_bounds: jnp.ndarray
    leaf_used: jnp.ndarray
    # intermediate monotone mode: [L, L+1] membership of each leaf in
    # each node's left/right subtree ([1, 1] placeholder otherwise) —
    # bounds are recomputed per round from CURRENT leaf outputs via
    # masked min/max over these, the TPU-native replacement for
    # IntermediateLeafConstraints' recursive constraint walks
    mono_left: jnp.ndarray
    mono_right: jnp.ndarray
    # advanced monotone mode: per-leaf per-feature bin ranges
    # ([L+1, F_meta] when active, [1, 1] placeholders otherwise) — the
    # adjacency test for strip-bounded constraints
    leaf_flo: jnp.ndarray
    leaf_fhi: jnp.ndarray
    # compact-row leaf ids for GOSS histogram-only compaction ([1]
    # placeholder otherwise): partitioned by the same splits as leaf_id
    leaf_id_c: jnp.ndarray
    # forced-split machinery (placeholder when cfg.n_forced == 0):
    # each entry's state: -1 waiting on parent, >=0 realized target
    # leaf slot, -2 cancelled (skipped parent), -3 applied
    forced_target: jnp.ndarray
    # leaf-ordered row partition (cfg.partition; [1]/[1,1] placeholders
    # otherwise): the histogram source arrays physically grouped by
    # leaf, the per-POSITION leaf ids, and the (offset, count) tables
    part_bins: jnp.ndarray          # [F, n] fm (Pallas) / [n, F] rm
    part_vals: jnp.ndarray          # [C, n] fm / [n, C] rm
    part_leaf: jnp.ndarray          # [n]
    part_off: jnp.ndarray           # [L+1]
    part_cnt: jnp.ndarray           # [L+1]
    # rows the histogram scans touched so far this tree (always
    # maintained — the masked path counts n per round) — the
    # hist.rows_scanned observability metric
    rows_scanned: jnp.ndarray


def _masked_gains(gain, leaf_depth, num_leaves, max_depth):
    Lp1 = gain.shape[0]
    active = jnp.arange(Lp1, dtype=jnp.int32) < num_leaves
    gains = jnp.where(active, gain, NEG_INF)
    if max_depth > 0:
        gains = jnp.where(leaf_depth < max_depth, gains, NEG_INF)
    return gains


@functools.partial(jax.jit, static_argnames=("cfg",))
def grow_tree(bins: jax.Array, vals: jax.Array,
              feat_num_bin: jax.Array, feat_has_nan: jax.Array,
              allowed_feature: jax.Array, cfg: GrowConfig,
              bins_t: jax.Array = None,
              is_cat: jax.Array = None,
              mono: jax.Array = None,
              groups: jax.Array = None,
              bundle: Tuple = None,
              chan_scale: jax.Array = None,
              node_key: jax.Array = None,
              cegb_pen: jax.Array = None,
              contri: jax.Array = None,
              compact: Tuple = None,
              forced: Tuple = None,
              lazy: Tuple = None,
              ) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """Grow one tree.

    Args:
      bins: ``[n, F]`` row-major binned matrix (partition gathers).
      vals: ``[n, 3]`` float32 (grad*mask, hess*mask, count-mask).
      feat_num_bin / feat_has_nan: ``[F]`` per-feature bin metadata.
      allowed_feature: ``[F]`` bool feature-sampling mask for this tree.
      cfg: static growth config.
      bins_t: ``[F, n]`` int8 feature-major copy; required (and only read)
        when ``cfg.use_pallas`` — the Pallas kernel input.
      is_cat: ``[F]`` bool categorical-feature mask; only read when
        ``cfg.has_categorical``.

    Returns:
      (tree dict of fixed-size arrays + ``num_leaves``, per-row leaf_id).
    """
    n_rows, F = bins.shape          # F = LOCAL width under feature_axis
    L = cfg.num_leaves
    B = cfg.num_bins
    Kb = max(1, min(cfg.leaf_batch, L))
    i32 = jnp.int32
    scfg = cfg.split_config

    # GOSS histogram-only compaction (cfg.hist_compact): histograms scan
    # a COMPACTED buffer of just the sampled rows while the full-row
    # leaf_id partition/score path stays masked (the split perf.md
    # proved cheap) — the reference's bag_data_indices_ subset scan,
    # without its gather. Both partitions run the same split logic; the
    # compact leaf ids ride the carry alongside the full ones.
    if not cfg.hist_compact:
        compact = None
    if compact is not None:
        bins_c, bins_t_c, vals_c = compact
        n_rows_c = bins_c.shape[0]
        h_bins, h_bins_t, h_vals = bins_c, bins_t_c, vals_c
    else:
        bins_c = bins_t_c = vals_c = None
        n_rows_c = 1
        h_bins, h_bins_t, h_vals = bins, bins_t, vals

    # ---- distributed search modes (SURVEY.md §3.4) -------------------
    mode_feature = bool(cfg.feature_axis)
    mode_voting = bool(cfg.axis_name) and cfg.voting
    mode_scatter = (bool(cfg.axis_name) and cfg.hist_scatter
                    and not cfg.voting and cfg.num_shards > 1
                    and F % cfg.num_shards == 0 and not mode_feature)
    if mode_scatter:
        F_s = F // cfg.num_shards       # owned feature slice per device
    else:
        F_s = F

    # packed wire is a quantized-only, cross-device-reduce-only
    # optimization; voting reduces elected columns later and feature-
    # parallel/serial histograms are already complete
    use_packed = (cfg.packed_wire and chan_scale is not None
                  and bool(cfg.axis_name)
                  and not (mode_voting or mode_feature))

    def hist_reduce(h):
        """Mode-specific cross-device histogram reduction — ONE
        collective through the shared packed-int32 wire
        (learner/collective.py; the streaming engine reduces through
        the same helper). With quantized gradients
        (use_quantized_grad), ``vals`` hold small integer levels —
        EXACT in the bf16 matmul and reduced as ints (the reference's
        int-histogram allreduce, cuda_gradient_discretizer.cu) — and
        are rescaled to real units here, right after the reduction."""
        from .collective import hist_allreduce
        if use_packed:
            h = hist_allreduce(h, cfg.axis_name, scatter=mode_scatter,
                               packed=True)
        elif cfg.axis_name and not (mode_voting or mode_feature):
            h = hist_allreduce(h, cfg.axis_name, scatter=mode_scatter)
        if chan_scale is not None:
            h = h * chan_scale
        return h

    if cfg.use_pallas:
        if h_bins_t is None:
            raise ValueError("cfg.use_pallas=True requires bins_t ([F, n] "
                             "feature-major int8 binned matrix)")
        if B > 256:
            raise ValueError(
                f"Pallas histogram path supports at most 256 bins (int8 "
                f"storage round-trips 0..255); got num_bins={B}. Use the "
                f"XLA path for wider histograms.")
        h_vals_t = h_vals.T
        # block size must divide the padded row count; rows_per_block does
        # (padding guarantees it), so cap via gcd to keep the streamed
        # one-hot within scoped VMEM without breaking divisibility.
        # R=4096 measured fastest on v5e at Higgs width, but the
        # feature-blocked grid (F*B > 8192, e.g. MSLR/Criteo widths)
        # overflows the 16MB scoped-vmem budget at 4096 — those shapes
        # cap at 2048.
        import math
        r_cap = 4096 if h_bins_t.shape[0] * B <= 8192 else 2048
        if h_bins_t.shape[0] <= 5 and B > 128:
            # measured on v5e (round 3): at F<=4, B=256 Mosaic's stack
            # allocation for the streamed one-hot blows scoped VMEM
            # (28.7M > 16M) at R=4096; F=6 is fine. Narrow-F shapes are
            # cheap anyway — halve the row block for safety margin.
            r_cap = min(r_cap, 2048)
        pr = math.gcd(cfg.rows_per_block, r_cap)
        base_rpb = pr

        def hist_kernel(b_src, v_src, l_src, ids, rpb):
            """Raw local multi-leaf histogram over an arbitrary source
            (the whole data, the GOSS buffer, or partition spans) —
            cross-device reduction stays with the caller so the span
            lax.switch never encloses a collective."""
            return multi_leaf_histogram(
                b_src, v_src, l_src, ids, num_bins=B,
                rows_per_block=rpb, int_mode=cfg.int_hist)

        def hist_multi(leaf_id, small_ids):
            return hist_reduce(hist_kernel(
                h_bins_t, h_vals_t, leaf_id, small_ids, pr))
    else:
        import math
        base_rpb = cfg.rows_per_block

        def hist_kernel(b_src, v_src, l_src, ids, rpb):
            return multi_leaf_histogram_xla(
                b_src, v_src, l_src, ids, num_bins=B,
                rows_per_block=rpb, precise=cfg.precise_histogram)

        def hist_multi(leaf_id, small_ids):
            return hist_reduce(hist_kernel(
                h_bins, h_vals, leaf_id, small_ids,
                cfg.rows_per_block))

    # ---- leaf-ordered row partition (cfg.partition) -------------------
    # ops/partition.py: rows (of the histogram SOURCE — the GOSS buffer
    # under hist_compact, else all rows) ride the carry grouped by leaf;
    # each round's histogram scans only the elected children's padded
    # spans via a static pow2 budget ladder, falling back to the masked
    # full scan when the spans would not shrink it.
    use_part = cfg.partition
    part_fm = cfg.use_pallas            # feature-major partition layout
    n_h = h_bins.shape[0]               # histogram-source row count
    if use_part:
        from ..ops import partition as part_ops
        M_span = 2 * Kb if cfg.hist_rebuild else Kb
        part_budgets = part_ops.span_budgets(n_h, M_span)
        # float32: the counter reaches n x rounds (x shards after the
        # psum) — int32 wraps at the very scales the metric watches
        _span_rows = jnp.asarray(
            tuple(M_span * s for s in part_budgets) + (n_h,),
            jnp.float32)

        def span_hist(pb, pv, pl, ids, offs, cnts):
            """[M, F_h, B, 3] local histograms of the elected children
            + the rows this round's scan touched."""
            branches = []
            for S in part_budgets:
                def mk(S):
                    rpb_b = math.gcd(S, base_rpb)

                    def br(pb, pv, pl, ids, offs, cnts):
                        bcat, vcat, lcat = part_ops.slice_spans(
                            pb, pv, pl, offs, cnts, S, part_fm)
                        return hist_kernel(bcat, vcat, lcat, ids, rpb_b)
                    return br
                branches.append(mk(S))

            def full_br(pb, pv, pl, ids, offs, cnts):
                # masked full scan over the partition (pl is a valid
                # per-position leaf vector) — the degenerate-budget
                # fallback, never worse than the masked path
                return hist_kernel(pb, pv, pl, ids, base_rpb)
            branches.append(full_br)
            need = jnp.max(jnp.where(ids >= 0, cnts, 0))
            if not part_budgets:
                return full_br(pb, pv, pl, ids, offs, cnts), \
                    jnp.asarray(n_h, jnp.float32)
            idx = jnp.sum((jnp.asarray(part_budgets, i32) < need)
                          .astype(i32))
            hist = jax.lax.switch(idx, branches, pb, pv, pl, ids,
                                  offs, cnts)
            return hist, _span_rows[idx]

    W = cfg.cat_words
    if not cfg.has_categorical:
        is_cat = None
    if not cfg.has_monotone:
        mono = None
    if not cfg.has_interaction:
        groups = None
    if not cfg.has_bundles:
        bundle = None
    if not cfg.has_contri:
        contri = None
    F_meta = feat_num_bin.shape[0]      # GLOBAL (logical) feature count
    if bundle is not None:
        assert not (mode_scatter or mode_feature), \
            "EFB composes with serial/psum/voting learners only"
        (bmap_pf, bmap_pb, bmap_valid, bat_def, bbundled, bphys_col,
         bstart, bdef) = bundle

        def expand_hist(hists, totals):
            """Physical [C, F_b, Bb, 3] -> logical [C, F_meta, B, 3];
            each bundled feature's DEFAULT-bin mass is recovered as the
            leaf-total residual (injected at its default slot)."""
            g = hists[:, bmap_pf, bmap_pb, :]
            g = jnp.where(bmap_valid[None, :, :, None], g, 0.0)
            resid = totals[:, None, :] - jnp.sum(g, axis=2)  # [C, F, 3]
            return g + (bat_def[None, :, :, None]
                        * resid[:, :, None, :])

    # search-slice metadata: under scatter/feature-parallel each device
    # searches only the F_s features it owns, offset into the GLOBAL
    # feature index space
    if mode_scatter or mode_feature:
        _ax = cfg.axis_name if mode_scatter else cfg.feature_axis
        off = (jax.lax.axis_index(_ax) * F_s).astype(i32)
        nb_s = jax.lax.dynamic_slice_in_dim(feat_num_bin, off, F_s)
        hn_s = jax.lax.dynamic_slice_in_dim(feat_has_nan, off, F_s)
        al_s = jax.lax.dynamic_slice_in_dim(allowed_feature, off, F_s)
        ic_s = (jax.lax.dynamic_slice_in_dim(is_cat, off, F_s)
                if is_cat is not None else None)
        mn_s = (jax.lax.dynamic_slice_in_dim(mono, off, F_s)
                if mono is not None else None)
        cp_s = (jax.lax.dynamic_slice_in_dim(cegb_pen, off, F_s)
                if cegb_pen is not None else None)
        ct_s = (jax.lax.dynamic_slice_in_dim(contri, off, F_s)
                if contri is not None else None)
    else:
        off = jnp.zeros((), i32)
        nb_s, hn_s, al_s, ic_s, mn_s, cp_s, ct_s = (
            feat_num_bin, feat_has_nan, allowed_feature, is_cat,
            mono, cegb_pen, contri)

    def bynode_mask(allow2, round_tag):
        """Exact-k per-child column sampling
        (ColSampler feature_fraction_bynode): k is the fraction of each
        child's CURRENTLY-ALLOWED features (after per-tree sampling,
        interaction constraints, and shard padding), like the
        reference's per-node resample of the valid set."""
        if cfg.feature_fraction_bynode >= 1.0 or node_key is None:
            return allow2
        C2 = allow2.shape[0]
        kk = jax.random.fold_in(node_key, round_tag)
        u = jnp.where(allow2, jax.random.uniform(kk, (C2, F_meta)),
                      jnp.inf)
        n_allow = jnp.sum(allow2, axis=1)
        k_idx = jnp.clip(
            jnp.ceil(cfg.feature_fraction_bynode
                     * n_allow.astype(jnp.float32)).astype(i32) - 1,
            0, F_meta - 1)
        kth = jnp.take_along_axis(jnp.sort(u, axis=1), k_idx[:, None],
                                  axis=1)
        return allow2 & (u <= kth)

    def extra_uniforms(C, round_tag):
        """Per-(child, feature) uniforms for extra_trees' one random
        threshold per node — GLOBAL feature width, drawn from a common
        key so every device slices a consistent random field."""
        if not cfg.extra_trees or node_key is None:
            return None
        kk = jax.random.fold_in(
            jax.random.fold_in(node_key, 0xE77A + cfg.extra_seed),
            round_tag)
        return jax.random.uniform(kk, (C, F_meta))

    if not cfg.has_cegb_lazy:
        lazy = None
    if lazy is not None:
        lazy_U, lazy_pen = lazy
        notU = (1.0 - lazy_U.astype(jnp.float32)).astype(jnp.bfloat16)

        def lazy_pen2(child_ids, lid_vec, pathf=None):
            """[C] candidate leaf ids -> [C, F] lazy penalties:
            penalty[f] x #rows of the child that never acquired f
            (0/1 bf16 operands, exact f32 accumulation). ``pathf``
            ([C, F] bool) marks features already split on the child's
            path THIS tree: every row of the child acquired those on
            split application (cost_effective_gradient_boosting.hpp),
            so re-splitting them deeper is penalty-free."""
            mk = (lid_vec[:, None]
                  == child_ids[None, :]).astype(jnp.bfloat16)  # [n, C]
            cnt = jax.lax.dot_general(
                mk, notU, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)            # [C, F]
            if pathf is not None:
                cnt = cnt * (1.0 - pathf.astype(jnp.float32))
            return cnt * lazy_pen[None, :]
    else:
        lazy_pen2 = None

    def search_best(hists, sums, lowers=None, uppers=None, allows=None,
                    parent_outs=None, round_tag=0, depths=None,
                    pen2=None):
        """Best split per child: ``hists [C, F_h, B, 3]`` (mode-reduced),
        ``sums [C, 3]`` global leaf totals, optional per-child monotone
        output bounds (``[C]``), interaction-constrained allowed
        masks (``[C, F_meta]``, GLOBAL width), and per-child parent
        outputs (``[C]``; path smoothing). Returns per-child best
        dict with GLOBAL feature indices, identical on every device."""
        C = hists.shape[0]
        if lowers is None:
            lowers = jnp.full(C, -jnp.inf, jnp.float32)
            uppers = jnp.full(C, jnp.inf, jnp.float32)
        allows_g = (jnp.broadcast_to(allowed_feature, (C, F_meta))
                    if allows is None else allows)
        eu = extra_uniforms(C, round_tag)                   # [C, F_meta]
        if mode_voting:
            # PV-Tree (voting_parallel_tree_learner.cpp): vote with
            # LOCAL histograms + local totals, elect global top-2k by
            # vote count, reduce only those columns
            local_sums = jnp.sum(hists[:, 0], axis=1)        # [C, 3]
            if bundle is not None:
                hists = expand_hist(hists, local_sums)
            pf = jax.vmap(lambda h, s, al, lo, hi, po, eu_, dp:
                          per_feature_gains(
                              h, s, feat_num_bin, feat_has_nan, al, scfg,
                              is_cat, mono=mono, out_lower=lo,
                              out_upper=hi, cegb_pen=cegb_pen,
                              parent_out=po, extra_u=eu_,
                              contri=contri, depth=dp))(
                hists, local_sums, allows_g, lowers, uppers,
                parent_outs, eu, depths)                     # [C, F]
            k_ = min(cfg.top_k, F_meta)
            vk = min(2 * cfg.top_k, F_meta)
            _, top_local = jax.lax.top_k(pf, k_)             # [C, k]
            votes = jnp.zeros((C, F_meta), jnp.float32).at[
                jnp.arange(C)[:, None], top_local].add(1.0)
            votes = jax.lax.psum(votes, cfg.axis_name)
            _, elected = jax.lax.top_k(votes, vk)            # [C, vk]
            hist_e = jnp.take_along_axis(
                hists, elected[:, :, None, None], axis=1)
            hist_e = jax.lax.psum(hist_e, cfg.axis_name)
            nb_e, hn_e = feat_num_bin[elected], feat_has_nan[elected]
            al_e = jnp.take_along_axis(allows_g, elected, axis=1)
            ic_e = is_cat[elected] if is_cat is not None else None
            mn_e = mono[elected] if mono is not None else None
            cp_e = cegb_pen[elected] if cegb_pen is not None else None
            ct_e = contri[elected] if contri is not None else None
            eu_e = (jnp.take_along_axis(eu, elected, axis=1)
                    if eu is not None else None)
            scfg_e = dataclasses.replace(scfg, cat_positions=())
            best = jax.vmap(
                lambda h, s, nb, hn, al, ic, mn, cp, lo, hi, po, eu_,
                ct, dp:
                find_best_split(
                    h, s, nb, hn, al, scfg_e, is_cat=ic, mono=mn,
                    out_lower=lo, out_upper=hi, cegb_pen=cp,
                    parent_out=po, extra_u=eu_, contri=ct, depth=dp))(
                hist_e, sums, nb_e, hn_e, al_e, ic_e, mn_e, cp_e,
                lowers, uppers, parent_outs, eu_e, ct_e, depths)
            best["feature"] = jnp.take_along_axis(
                elected, best["feature"][:, None], axis=1)[:, 0]
            return best
        if bundle is not None:
            hists = expand_hist(hists, sums)
        allows_s = (jax.lax.dynamic_slice_in_dim(allows_g, off, F_s,
                                                 axis=1)
                    if (mode_scatter or mode_feature) else allows_g)
        eu_s = (jax.lax.dynamic_slice_in_dim(eu, off, F_s, axis=1)
                if eu is not None and (mode_scatter or mode_feature)
                else eu)
        # one penalty shape for both CEGB flavors: per-child lazy (+
        # coupled), broadcast coupled, or None — a single vmap call
        # (None vmaps as an empty pytree)
        if pen2 is not None:
            pen_c = pen2 + (cp_s[None, :] if cp_s is not None else 0.0)
        elif cp_s is not None:
            pen_c = jnp.broadcast_to(cp_s[None, :],
                                     (hists.shape[0], cp_s.shape[0]))
        else:
            pen_c = None
        best = jax.vmap(lambda h, s, al, lo, hi, po, eu_, dp, p2:
                        find_best_split(
                            h, s, nb_s, hn_s, al, scfg, is_cat=ic_s,
                            mono=mn_s, out_lower=lo, out_upper=hi,
                            cegb_pen=p2, parent_out=po, extra_u=eu_,
                            contri=ct_s, depth=dp))(
            hists, sums, allows_s, lowers, uppers, parent_outs,
            eu_s, depths, pen_c)
        best["feature"] = best["feature"] + off
        if mode_scatter:
            # SyncUpGlobalBestSplit across feature owners
            return elect_best(best, cfg.axis_name)
        if mode_feature:
            return elect_best(best, cfg.feature_axis)
        return best

    def leaf_out(sums):
        return calc_leaf_output(sums[..., 0], sums[..., 1], cfg.lambda_l1,
                                cfg.lambda_l2, cfg.max_delta_step)

    use_mono_inter = cfg.has_monotone and cfg.monotone_intermediate
    use_mono_adv = use_mono_inter and cfg.monotone_advanced

    # forced splits (forcedsplits_filename; Tree::AddSplit forced paths
    # in serial_tree_learner.cpp ForceSplits — UNVERIFIED): a PREORDER
    # table (parents before children). Every READY entry (parent
    # realized) is applied in the SAME leaf-batch round — sibling
    # entries land together, so a k-entry table consumes ~depth(table)
    # rounds, not k (round 4; was one-entry-per-round). Numerical AND
    # categorical entries (one-vs-rest bin bitsets) are supported.
    # forced_target codes: -1 waiting on parent, >=0 target leaf slot,
    # -2 cancelled (skipped parent), -3 applied. Requires the pool
    # (leaf_hist) for the forced threshold's child sums; the engine
    # gates eligibility.
    if cfg.n_forced <= 0:
        forced = None
    if forced is not None:
        f_parent, f_is_left, f_feat, f_tbin, f_is_cat, f_bitset = forced
        M_f = cfg.n_forced
        assert not cfg.hist_rebuild, \
            "forced splits need the histogram pool"

    # ---- root ----------------------------------------------------------
    leaf_id0 = jnp.zeros(n_rows, dtype=i32)
    leaf_id0_c = jnp.zeros(n_rows_c, dtype=i32)
    if use_part:
        # initial layout: every histogram-source row belongs to the
        # root, one contiguous span covering the whole buffer
        part_bins0 = h_bins_t if part_fm else h_bins
        part_vals0 = h_vals_t if part_fm else h_vals
        part_leaf0 = jnp.zeros(n_h, dtype=i32)
        part_off0 = jnp.zeros(L + 1, dtype=i32)
        part_cnt0 = jnp.zeros(L + 1, dtype=i32).at[0].set(n_h)
    else:
        part_bins0 = jnp.zeros((1, 1), jnp.int8)
        part_vals0 = jnp.zeros((1, 1), jnp.float32)
        part_leaf0 = jnp.zeros(1, dtype=i32)
        part_off0 = jnp.zeros(1, dtype=i32)
        part_cnt0 = jnp.zeros(1, dtype=i32)
    root_small = jnp.concatenate(
        [jnp.zeros(1, i32), jnp.full(Kb - 1, -1, i32)]) if Kb > 1 \
        else jnp.zeros(1, i32)
    root_hist = hist_multi(leaf_id0_c if compact is not None
                           else leaf_id0, root_small)[0]
    root_sums = jnp.sum(h_vals, axis=0)
    if cfg.axis_name:
        root_sums = jax.lax.psum(root_sums, cfg.axis_name)
    if chan_scale is not None:
        root_sums = root_sums * chan_scale
    if cfg.has_interaction:
        # features in no constraint group can never be used
        root_allow = jnp.any(groups, axis=0) & allowed_feature  # [F_meta]
    else:
        root_allow = None
    root_allows = (root_allow[None] if root_allow is not None else None)
    if cfg.feature_fraction_bynode < 1.0 and node_key is not None:
        base = (root_allows if root_allows is not None
                else jnp.broadcast_to(allowed_feature, (1, F_meta)))
        root_allows = bynode_mask(base, L + 7)
    root_parent_out = (leaf_out(root_sums)[None]
                       if cfg.path_smooth > 0.0 else None)
    root_best = jax.tree.map(
        lambda a: a[0], search_best(
            root_hist[None], root_sums[None], allows=root_allows,
            parent_outs=root_parent_out, round_tag=L + 7,
            depths=(jnp.zeros(1, i32)
                    if cfg.monotone_penalty > 0.0 else None),
            pen2=(lazy_pen2(jnp.zeros(1, i32), leaf_id0)
                  if lazy is not None else None)))

    def set0(arr, value):
        return arr.at[0].set(value)

    state = GrowState(
        split_idx=jnp.array(0, i32),
        num_leaves=jnp.array(1, i32),
        # pending forced entries must enter the loop even when the free
        # root search found nothing (forced splits bypass gain checks)
        has_split=(jnp.array(True) if forced is not None
                   else jnp.isfinite(root_best["gain"])),
        leaf_id=leaf_id0,
        # rebuild mode carries no pool — a 1-element placeholder keeps
        # the NamedTuple structure static
        leaf_hist=(jnp.zeros((1, 1, 1, 1), jnp.float32)
                   if cfg.hist_rebuild else
                   set0(jnp.zeros((L + 1,) + root_hist.shape,
                                  jnp.float32), root_hist)),
        leaf_sums=set0(jnp.zeros((L + 1, 3), jnp.float32), root_sums),
        leaf_depth=jnp.zeros(L + 1, i32),
        best_gain=set0(jnp.full(L + 1, NEG_INF), root_best["gain"]),
        best_feature=set0(jnp.zeros(L + 1, i32), root_best["feature"]),
        best_threshold=set0(jnp.zeros(L + 1, i32),
                            root_best["threshold_bin"]),
        best_default_left=set0(jnp.zeros(L + 1, jnp.bool_),
                               root_best["default_left"]),
        best_lr_sums=set0(jnp.zeros((L + 1, 2, 3), jnp.float32),
                          jnp.stack([root_best["left_sums"],
                                     root_best["right_sums"]])),
        best_is_cat=set0(jnp.zeros(L + 1, jnp.bool_),
                         root_best["is_cat"]),
        best_cat_bitset=set0(jnp.zeros((L + 1, W), jnp.uint32),
                             root_best["cat_bitset"]),
        split_feature=jnp.zeros(L, i32),
        threshold_bin=jnp.zeros(L, i32),
        default_left=jnp.zeros(L, jnp.bool_),
        node_is_cat=jnp.zeros(L, jnp.bool_),
        node_cat_bitset=jnp.zeros((L, W), jnp.uint32),
        left_child=jnp.zeros(L, i32),
        right_child=jnp.zeros(L, i32),
        node_vcg=jnp.zeros((L, 3), jnp.float32),
        leaf_vcw=set0(jnp.zeros((L + 1, 3), jnp.float32),
                      jnp.stack([leaf_out(root_sums), root_sums[2],
                                 root_sums[1]])),
        leaf_parent=jnp.full(L + 1, -1, i32),
        leaf_is_left=jnp.zeros(L + 1, jnp.bool_),
        leaf_bounds=jnp.stack(
            [jnp.full(L + 1, -jnp.inf, jnp.float32),
             jnp.full(L + 1, jnp.inf, jnp.float32)], axis=1),
        leaf_used=jnp.zeros(
            (L + 1, F_meta if (cfg.has_interaction or cfg.has_cegb_lazy)
             else 1), jnp.bool_),
        mono_left=jnp.zeros(
            (L, L + 1) if use_mono_inter else (1, 1), jnp.bool_),
        mono_right=jnp.zeros(
            (L, L + 1) if use_mono_inter else (1, 1), jnp.bool_),
        leaf_flo=(jnp.zeros((L + 1, F_meta), i32) if use_mono_adv
                  else jnp.zeros((1, 1), i32)),
        leaf_fhi=(jnp.broadcast_to(feat_num_bin[None, :],
                                   (L + 1, F_meta)).astype(i32)
                  if use_mono_adv else jnp.zeros((1, 1), i32)),
        leaf_id_c=(leaf_id0_c if compact is not None
                   else jnp.zeros(1, i32)),
        forced_target=(jnp.where(f_parent < 0, 0, -1).astype(i32)
                       if forced is not None else jnp.zeros(1, i32)),
        part_bins=part_bins0,
        part_vals=part_vals0,
        part_leaf=part_leaf0,
        part_off=part_off0,
        part_cnt=part_cnt0,
        # the root histogram above scanned the whole source once
        # (float32: n x rounds x shards overflows int32 at prod scale)
        rows_scanned=jnp.asarray(n_h, jnp.float32),
    )

    node_trash = L - 1  # real nodes occupy 0..L-2
    leaf_trash = L

    def cond(s: GrowState):
        return (s.split_idx < L - 1) & s.has_split

    def body(s: GrowState) -> GrowState:
        gains = _masked_gains(s.best_gain, s.leaf_depth, s.num_leaves,
                              cfg.max_depth)
        if forced is not None:
            # ---- forced rounds: every READY entry this round ---------
            tgt = s.forced_target                          # [M]
            ready = tgt >= 0
            in_forced = jnp.any(ready | (tgt == -1))
            tgt_cl = jnp.clip(tgt, 0, L)
            is_forced_leaf = jnp.zeros(L + 1, jnp.bool_).at[
                jnp.where(ready, tgt_cl, L)].set(True).at[L].set(False)
            # while entries remain, ONLY forced targets may split
            # (reference applies all forced splits before free growth)
            gains = jnp.where(
                in_forced,
                jnp.where(is_forced_leaf, jnp.float32(3e38), NEG_INF),
                gains)
        top_gain, top_leaf = jax.lax.top_k(gains, Kb)
        remaining = (L - 1) - s.split_idx
        valid = jnp.isfinite(top_gain) \
            & (jnp.arange(Kb, dtype=i32) < remaining)
        if forced is not None:
            from ..ops.split import leaf_gain as _lg
            # match each batch lane to its forced entry (targets are
            # unique per leaf slot, so at most one entry per lane)
            lane_match = ((top_leaf[:, None] == tgt[None, :])
                          & ready[None, :])                  # [Kb, M]
            flane = jnp.any(lane_match, axis=1) & in_forced  # [Kb]

            def esel(arr):
                return jnp.sum(
                    jnp.where(lane_match, arr[None, :].astype(i32), 0),
                    axis=1)

            ff_k = esel(f_feat)                              # [Kb]
            ftb_k = esel(f_tbin)
            fcat_k = jnp.any(lane_match & f_is_cat[None, :], axis=1)
            fbs_k = jnp.sum(
                jnp.where(lane_match[:, :, None],
                          f_bitset[None, :, :],
                          jnp.uint32(0)), axis=1)            # [Kb, W]
            # per-lane child sums from the pool histogram: gather the
            # target leaves' histograms with the one-hot matmul trick
            oh_tf = (top_leaf[:, None]
                     == jnp.arange(L + 1, dtype=i32)[None, :]
                     ).astype(jnp.float32)
            fhist = jax.lax.dot_general(
                oh_tf, s.leaf_hist.reshape(L + 1, -1),
                dimension_numbers=(((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST).reshape(
                    Kb, s.leaf_hist.shape[1], B, 3)
            oh_ff = (ff_k[:, None]
                     == jnp.arange(F_meta, dtype=i32)[None, :])
            col_f = jnp.sum(
                jnp.where(oh_ff[:, :, None, None], fhist, 0.0),
                axis=1)                                       # [Kb,B,3]
            bidx_f = jnp.arange(B, dtype=i32)[None, :]
            nanb_f = (feat_has_nan[ff_k][:, None]
                      & (bidx_f == feat_num_bin[ff_k][:, None] - 1))
            num_lm = (bidx_f <= ftb_k[:, None]) & ~nanb_f
            word_k = jnp.take_along_axis(
                fbs_k, (bidx_f >> 5).astype(i32), axis=1)
            cat_lm = ((word_k >> (bidx_f & 31).astype(jnp.uint32))
                      & jnp.uint32(1)) > 0
            lm_f = jnp.where(fcat_k[:, None], cat_lm, num_lm) \
                & (bidx_f < feat_num_bin[ff_k][:, None])
            f_lsums = jnp.sum(col_f * lm_f[:, :, None], axis=1)
            f_psums2 = s.leaf_sums[jnp.clip(top_leaf, 0, L)]
            f_rsums = f_psums2 - f_lsums
            # forced splits bypass gain/min_data checks, but both
            # children must receive rows (and respect max_depth);
            # otherwise the entry and its subtree are skipped
            applied_k = (flane & (f_lsums[:, 2] > 0)
                         & (f_rsums[:, 2] > 0))
            if cfg.max_depth > 0:
                applied_k = applied_k \
                    & (s.leaf_depth[jnp.clip(top_leaf, 0, L)]
                       < cfg.max_depth)
            valid = valid & (~flane | applied_k)
        nv = jnp.sum(valid).astype(i32)
        rank = jnp.cumsum(valid.astype(i32)) - 1
        node_ids = jnp.where(valid, s.split_idx + rank, node_trash)
        new_ids = jnp.where(valid, s.num_leaves + rank, leaf_trash)
        tl_safe = jnp.where(valid, top_leaf, leaf_trash)

        # ---- partition: apply all selected splits in one row pass ------
        # TPU note: per-row gathers into tiny tables (feat[lf], thr[lf],
        # ...) run on the scalar unit at ~100M elem/s — 5 of them cost
        # ~45ms/round at 1M rows. Instead build the [n, Kb] membership
        # mask of the selected leaves once and contract it against the
        # per-leaf attributes packed as a [Kb, 6] matrix: one small MXU
        # matmul replaces every per-row lookup.
        lf = s.leaf_id
        # per-lane split attributes; a forced round substitutes the
        # forced entry's feature/threshold for lane 0
        feat_sel = s.best_feature[tl_safe]
        thr_sel = s.best_threshold[tl_safe]
        dl_sel = s.best_default_left[tl_safe]
        gain_rec = top_gain
        lr_sel = s.best_lr_sums[tl_safe]           # [Kb, 2, 3]
        lsums_sel = lr_sel[:, 0]                   # [Kb, 3]
        rsums_sel = lr_sel[:, 1]
        cat_sel = (s.best_is_cat[tl_safe] if cfg.has_categorical
                   else None)
        bs_sel = (s.best_cat_bitset[tl_safe] if cfg.has_categorical
                  else None)
        if forced is not None:
            # substitute the forced entries' attributes on their lanes
            # (analysis arrays computed above, before `valid`)
            feat_sel = jnp.where(flane, ff_k, feat_sel)
            thr_sel = jnp.where(flane, ftb_k, thr_sel)
            dl_sel = jnp.where(flane, False, dl_sel)
            lsums_sel = jnp.where(flane[:, None], f_lsums, lsums_sel)
            rsums_sel = jnp.where(flane[:, None], f_rsums, rsums_sel)
            g_forced = (_lg(f_lsums[:, 0], f_lsums[:, 1], cfg.lambda_l1,
                            cfg.lambda_l2)
                        + _lg(f_rsums[:, 0], f_rsums[:, 1],
                              cfg.lambda_l1, cfg.lambda_l2)
                        - _lg(f_psums2[:, 0], f_psums2[:, 1],
                              cfg.lambda_l1, cfg.lambda_l2))
            gain_rec = jnp.where(flane, g_forced, gain_rec)
            if cfg.has_categorical:
                cat_sel = jnp.where(flane, fcat_k, cat_sel)
                bs_sel = jnp.where(flane[:, None], fbs_k, bs_sel)
        attr_cols = [feat_sel.astype(jnp.float32),
                     thr_sel.astype(jnp.float32),
                     dl_sel.astype(jnp.float32),
                     new_ids.astype(jnp.float32),
                     feat_num_bin[feat_sel].astype(jnp.float32),
                     feat_has_nan[feat_sel].astype(jnp.float32)]
        if cfg.has_categorical:
            # bitset words split into 16-bit halves: exact in float32,
            # so the same masked matmul carries them per row
            attr_cols.append(cat_sel.astype(jnp.float32))
            attr_cols.extend(jnp.moveaxis(
                (bs_sel & jnp.uint32(0xFFFF)).astype(jnp.float32), 1, 0))
            attr_cols.extend(jnp.moveaxis(
                (bs_sel >> jnp.uint32(16)).astype(jnp.float32), 1, 0))
        if cfg.has_bundles:
            # EFB: the row pass reads the PHYSICAL bundle column and
            # recovers the logical bin via the member's offset/default
            attr_cols.extend([
                bphys_col[feat_sel].astype(jnp.float32),
                bstart[feat_sel].astype(jnp.float32),
                bbundled[feat_sel].astype(jnp.float32),
                bdef[feat_sel].astype(jnp.float32)])
        packed = jnp.stack(attr_cols, axis=1)

        def apply_splits(lf_vec, bins_mat, fm=False):
            """Route one row set through this round's selected splits
            (shared by the full partition, the compacted buffer's
            partition under hist_compact, and the leaf-ordered row
            partition's per-position ids under cfg.partition). With
            ``fm`` the source is the FEATURE-MAJOR ``[F, n]`` int8
            matrix (wraparound storage) — the one-hot column read
            reduces over the leading axis, so no transpose is ever
            materialized."""
            mk = (lf_vec[:, None] == tl_safe[None, :]) & valid[None, :]
            sel_rows = jnp.any(mk, axis=1)
            row_attr = jax.lax.dot_general(
                mk.astype(jnp.float32), packed,
                dimension_numbers=(((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST)  # [n, 6(+1+2W)]
            feat_r = row_attr[:, 0].astype(i32)
            thr_r = row_attr[:, 1].astype(i32)
            dl_r = row_attr[:, 2] > 0.5
            new_leaf_r = row_attr[:, 3].astype(i32)
            nb_r = row_attr[:, 4].astype(i32)
            hn_r = row_attr[:, 5] > 0.5
            # bins[row, feat_r] without a per-row gather: one-hot over
            # F, fused compare-select-reduce on the VPU (exact in
            # int32). Under feature-parallel, only the winning
            # feature's OWNER has the column — its contribution is
            # broadcast by the psum (every other device contributes
            # zeros), the TPU-native replacement for the reference's
            # full-data local split.
            if cfg.has_bundles:
                bidx = 6 + ((1 + 2 * W) if cfg.has_categorical else 0)
                pcol_r = row_attr[:, bidx].astype(i32)
                start_r = row_attr[:, bidx + 1].astype(i32)
                bundled_r = row_attr[:, bidx + 2] > 0.5
                def_r = row_attr[:, bidx + 3].astype(i32)
            else:
                pcol_r = feat_r
            col_ids = jnp.arange(F, dtype=i32)
            if mode_feature:
                col_ids = col_ids + off
            if fm:
                # int8 wraparound storage -> restore uint8 bin values
                oh_f = pcol_r[None, :] == col_ids[:, None]     # [F, n]
                col = jnp.sum(
                    jnp.where(oh_f, bins_mat.astype(i32) & 0xFF, 0),
                    axis=0)
            else:
                oh_f = pcol_r[:, None] == col_ids[None, :]
                col = jnp.sum(jnp.where(oh_f, bins_mat.astype(i32), 0),
                              axis=1)
            if mode_feature:
                col = jax.lax.psum(col, cfg.feature_axis)
            if cfg.has_bundles:
                # invert the bundle relabeling: phys v -> logical bin
                # (the member's default bin was skipped in the
                # enumeration)
                idx = col - start_r
                in_r = (idx >= 0) & (idx <= nb_r - 2)
                b_log = idx + (idx >= def_r).astype(i32)
                col = jnp.where(bundled_r,
                                jnp.where(in_r, b_log, def_r), col)
            is_missing = hn_r & (col == nb_r - 1)
            goes_left = jnp.where(is_missing, dl_r, col <= thr_r)
            if cfg.has_categorical:
                is_cat_r = row_attr[:, 6] > 0.5
                oh_w = ((col >> 5)[:, None]
                        == jnp.arange(W, dtype=i32)[None, :])  # [n, W]
                lo16 = jnp.sum(jnp.where(oh_w, row_attr[:, 7:7 + W],
                                         0.0), axis=1).astype(jnp.uint32)
                hi16 = jnp.sum(
                    jnp.where(oh_w, row_attr[:, 7 + W:7 + 2 * W], 0.0),
                    axis=1).astype(jnp.uint32)
                word = lo16 | (hi16 << jnp.uint32(16))
                cat_left = ((word >> (col & 31).astype(jnp.uint32))
                            & jnp.uint32(1)) > 0
                goes_left = jnp.where(is_cat_r, cat_left, goes_left)
            return jnp.where(sel_rows & ~goes_left, new_leaf_r, lf_vec)

        leaf_id = apply_splits(lf, bins)
        # under the leaf-ordered partition the compact-buffer masked ids
        # are dead (histograms read part_leaf instead) — skip the pass
        leaf_id_c = (apply_splits(s.leaf_id_c, bins_c)
                     if compact is not None and not use_part
                     else s.leaf_id_c)
        hist_lid = leaf_id_c if compact is not None else leaf_id

        # ---- leaf-ordered repartition (cfg.partition) ------------------
        # one stable front/back move per round: rows that routed to a
        # RIGHT child pack (stably) to the back of the buffer, everything
        # else packs to the front — per-leaf contiguity and within-leaf
        # source order both survive, and the (offset, count) tables
        # update from the same prefix sums (ops/partition.py).
        if use_part:
            part_leaf_mv = apply_splits(s.part_leaf, s.part_bins,
                                        fm=part_fm)
            moved = part_leaf_mv != s.part_leaf
            dest, n_front, cum = part_ops.plan_split_move(moved)
            p_off, p_cnt = part_ops.update_tables(
                s.part_off, s.part_cnt, cum, n_front, tl_safe, new_ids,
                valid)
            if part_fm:
                # TPU: two compact_rows passes (front keys, back keys);
                # the int32 leaf ids ride as one extra float32 value
                # channel (exact via the kernel's bf16x3 split)
                pv_aug = jnp.concatenate(
                    [s.part_vals,
                     part_leaf_mv[None].astype(jnp.float32)])
                p_bins, pv2 = part_ops.move_cols_tpu(
                    s.part_bins, pv_aug, moved, n_front, cfg.part_rpb)
                p_vals = pv2[:-1]
                p_leaf = pv2[-1].astype(i32)
            else:
                p_bins, p_vals, p_leaf = part_ops.move_rows_xla(
                    [s.part_bins, s.part_vals, part_leaf_mv], dest)
        else:
            p_bins, p_vals, p_leaf = (s.part_bins, s.part_vals,
                                      s.part_leaf)
            p_off, p_cnt = s.part_off, s.part_cnt

        def span_tables(ids):
            """Per-elected-child (offset, count) rows for slice_spans
            (-1 lanes get count 0, so they match nothing)."""
            safe = jnp.clip(ids, 0, L)
            return p_off[safe], jnp.where(ids >= 0, p_cnt[safe], 0)

        lsums = lsums_sel                      # [Kb, 3]
        rsums = rsums_sel
        psums = s.leaf_sums[tl_safe]
        if cfg.hist_rebuild:
            # ---- both children direct, one fused scan ------------------
            # 2*Kb membership masks pack into the matmul N dimension;
            # the sibling's histogram rides the MXU padding that the
            # subtraction trick exists to avoid on CPUs
            both_ids = jnp.concatenate([
                jnp.where(valid, top_leaf, -1),
                jnp.where(valid, new_ids, -1)]).astype(i32)
            if use_part:
                # partitioned: scan only the 2Kb children's padded spans
                offs_k, cnts_k = span_tables(both_ids)
                raw2, span_rows = span_hist(p_bins, p_vals, p_leaf,
                                            both_ids, offs_k, cnts_k)
                hist2 = hist_reduce(raw2)            # [2Kb, F, B, 3]
            else:
                hist2 = hist_multi(hist_lid, both_ids)
                span_rows = jnp.asarray(n_h, jnp.float32)
            left_hist, right_hist = hist2[:Kb], hist2[Kb:]
            leaf_hist = s.leaf_hist
        else:
            # ---- smaller-child histogram + sibling subtraction ---------
            left_smaller = lsums[:, 2] <= rsums[:, 2]
            small_ids = jnp.where(
                valid, jnp.where(left_smaller, top_leaf, new_ids),
                -1).astype(i32)
            if use_part:
                # partitioned: scan only the Kb smaller children's spans
                offs_k, cnts_k = span_tables(small_ids)
                raw_s, span_rows = span_hist(p_bins, p_vals, p_leaf,
                                             small_ids, offs_k, cnts_k)
                hist_small = hist_reduce(raw_s)      # [Kb, F, B, 3]
            else:
                hist_small = hist_multi(hist_lid, small_ids)
                span_rows = jnp.asarray(n_h, jnp.float32)
            # TPU note: the [L+1, F, B, 3] pool gather/scatter by leaf id
            # lowers to serialized dynamic slices (~13 ms/round at
            # nl=127); both become one-hot matmuls on the MXU instead.
            # 0/1 weights with disjoint rows keep values exact; the
            # trash lane L may accumulate a SUM of invalid lanes rather
            # than the last write, but slot L is never an active leaf.
            F_h = s.leaf_hist.shape[1]
            pool_flat = s.leaf_hist.reshape(L + 1, -1)
            leaf_ids_ax = jnp.arange(L + 1, dtype=i32)
            oh_parent = (tl_safe[:, None]
                         == leaf_ids_ax[None, :]).astype(jnp.float32)
            parent_hist = jax.lax.dot_general(
                oh_parent, pool_flat,
                dimension_numbers=(((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST).reshape(
                    Kb, F_h, B, 3)
            hist_large = parent_hist - hist_small
            ls4 = left_smaller[:, None, None, None]
            left_hist = jnp.where(ls4, hist_small, hist_large)
            right_hist = jnp.where(ls4, hist_large, hist_small)
            oh_new = (new_ids[:, None]
                      == leaf_ids_ax[None, :]).astype(jnp.float32)
            upd = jax.lax.dot_general(
                jnp.concatenate([oh_parent, oh_new]).T,
                jnp.concatenate([left_hist, right_hist]).reshape(
                    2 * Kb, -1),
                dimension_numbers=(((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST)
            written = (jnp.sum(oh_parent, axis=0)
                       + jnp.sum(oh_new, axis=0)) > 0       # [L+1]
            leaf_hist = jnp.where(written[:, None], upd,
                                  pool_flat).reshape(s.leaf_hist.shape)

        depth2 = s.leaf_depth[tl_safe] + 1
        lvals = leaf_out(lsums)
        rvals = leaf_out(rsums)
        if cfg.has_categorical:
            # children of a categorical split are regularized with
            # lambda_l2 + cat_l2, matching the gain computed in
            # ops/split.py (reference: feature_histogram.hpp categorical
            # CalculateSplittedLeafOutput uses the cat-augmented l2)
            def leaf_out_cat(sums):
                return calc_leaf_output(
                    sums[..., 0], sums[..., 1], cfg.lambda_l1,
                    cfg.lambda_l2 + cfg.cat_l2, cfg.max_delta_step)
            cat_split = cat_sel
            lvals = jnp.where(cat_split, leaf_out_cat(lsums), lvals)
            rvals = jnp.where(cat_split, leaf_out_cat(rsums), rvals)

        if cfg.path_smooth > 0.0:
            # children shrink toward the SPLIT leaf's stored output
            # (feature_histogram.hpp passes tree->LeafOutput(leaf) as
            # parent_output); smoothing applies before constraint clips
            pvals = s.leaf_vcw[tl_safe, 0]
            lvals = smooth_output(lvals, lsums[:, 2], pvals,
                                  cfg.path_smooth)
            rvals = smooth_output(rvals, rsums[:, 2], pvals,
                                  cfg.path_smooth)

        # ---- constraint propagation (monotone_constraints.hpp) ---------
        if cfg.has_monotone:
            m_k = mono[feat_sel].astype(jnp.float32)
            if use_mono_inter:
                # intermediate mode: bounds recomputed each round from
                # the CURRENT leaf outputs of every constrained node's
                # opposing subtree (IntermediateLeafConstraints'
                # semantics) — masked min/max over the [L, L+1]
                # membership matrices instead of recursive tree walks.
                # Cached best splits from earlier rounds may predate a
                # bound tightening; the clip below re-applies the
                # CURRENT bound at split time, keeping every realized
                # output sound by induction.
                leaf_ax = jnp.arange(L + 1, dtype=i32)
                node_ok = jnp.arange(L, dtype=i32) < s.split_idx
                node_m = jnp.where(node_ok,
                                   mono[s.split_feature], 0)     # [L]
                act = leaf_ax < s.num_leaves                     # [L+1]
                vals_c = s.leaf_vcw[:, 0]
                big = jnp.float32(jnp.inf)
                if use_mono_adv:
                    # ADVANCED (AdvancedLeafConstraints): each node
                    # binds only the leaves of either subtree that are
                    # ADJACENT to its boundary in its split feature
                    # (leaf bin range touching the threshold); shielded
                    # leaves are ordered transitively through the
                    # adjacent strip chain, so their bounds — and the
                    # strip aggregates below — are strictly looser than
                    # intermediate's whole-subtree min/max.
                    oh_nf = (s.split_feature[:, None]
                             == jnp.arange(F_meta, dtype=i32)[None, :]
                             ).astype(jnp.float32)       # [L, F_meta]
                    lo_f = jax.lax.dot_general(
                        oh_nf, s.leaf_flo.astype(jnp.float32),
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        precision=jax.lax.Precision.HIGHEST)  # [L, L+1]
                    hi_f = jax.lax.dot_general(
                        oh_nf, s.leaf_fhi.astype(jnp.float32),
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        precision=jax.lax.Precision.HIGHEST)
                    tjf = s.threshold_bin.astype(jnp.float32)[:, None]
                    ncat = s.node_is_cat[:, None]        # [L, 1]
                    ml_eff = s.mono_left & (ncat | (hi_f == tjf))
                    mr_eff = s.mono_right & (ncat | (lo_f == tjf + 1.0))
                else:
                    ml_eff, mr_eff = s.mono_left, s.mono_right
                inf_r = jnp.where(mr_eff & act[None, :],
                                  vals_c[None, :], big)
                inf_l = jnp.where(ml_eff & act[None, :],
                                  vals_c[None, :], big)
                rmin = jnp.min(inf_r, axis=1)                    # [L]
                lmin = jnp.min(inf_l, axis=1)
                rmax = jnp.max(jnp.where(mr_eff & act[None, :],
                                         vals_c[None, :], -big), axis=1)
                lmax = jnp.max(jnp.where(ml_eff & act[None, :],
                                         vals_c[None, :], -big), axis=1)
                in_l = ml_eff[:, tl_safe]                        # [L, Kb]
                in_r = mr_eff[:, tl_safe]
                # batch race guard: when THIS round splits leaves on
                # BOTH sides of a constrained node, each side would use
                # the other's pre-round value and their children could
                # cross; those nodes fall back to a shared midpoint cut
                # (sound for concurrent updates), everything else keeps
                # the looser one-sided bound
                both = (jnp.any(in_l & valid[None, :], axis=1)
                        & jnp.any(in_r & valid[None, :], axis=1))  # [L]
                c_inc = jnp.where(both, 0.5 * (lmax + rmin), 0.0)
                c_dec = jnp.where(both, 0.5 * (lmin + rmax), 0.0)
                nup_l = jnp.where(both, c_inc, rmin)  # inc, leaf on left
                nlo_r = jnp.where(both, c_inc, lmax)  # inc, leaf on right
                nup_r = jnp.where(both, c_dec, lmin)  # dec, leaf on right
                nlo_l = jnp.where(both, c_dec, rmax)  # dec, leaf on left
                pos = (node_m > 0)[:, None]
                neg = (node_m < 0)[:, None]
                phi = jnp.min(jnp.where(
                    pos & in_l, nup_l[:, None],
                    jnp.where(neg & in_r, nup_r[:, None], big)), axis=0)
                plo = jnp.max(jnp.where(
                    pos & in_r, nlo_r[:, None],
                    jnp.where(neg & in_l, nlo_l[:, None], -big)), axis=0)
            else:
                plo = s.leaf_bounds[tl_safe, 0]
                phi = s.leaf_bounds[tl_safe, 1]
            lvals = jnp.clip(lvals, plo, phi)
            rvals = jnp.clip(rvals, plo, phi)
            if use_mono_inter:
                # children are bounded by the SIBLING's realized output
                # (looser than basic's midpoint; later tightenings are
                # picked up by the per-round recompute above)
                bound_l, bound_r = rvals, lvals
            else:
                # basic mode: the mid-point of the realized outputs
                # becomes the shared bound of the two children, so any
                # LATER split below either child cannot cross it
                bound_l = bound_r = 0.5 * (lvals + rvals)
            lo_l = jnp.where(m_k < 0, jnp.maximum(plo, bound_l), plo)
            hi_l = jnp.where(m_k > 0, jnp.minimum(phi, bound_l), phi)
            lo_r = jnp.where(m_k > 0, jnp.maximum(plo, bound_r), plo)
            hi_r = jnp.where(m_k < 0, jnp.minimum(phi, bound_r), phi)
            child_lower = jnp.concatenate([lo_l, lo_r])
            child_upper = jnp.concatenate([hi_l, hi_r])
        else:
            child_lower = child_upper = None
        if cfg.has_interaction or cfg.has_cegb_lazy:
            fk = feat_sel
            # only lanes that actually split extend their path set
            used_k = s.leaf_used[tl_safe] \
                | ((fk[:, None] == jnp.arange(F_meta, dtype=i32)[None, :])
                   & valid[:, None])
            child_used = jnp.concatenate([used_k, used_k])
        else:
            child_used = None
        if cfg.has_interaction:
            # a group is usable iff it contains EVERY feature on the path
            viol = jnp.any(used_k[:, None, :] & ~groups[None],
                           axis=2)                            # [Kb, G]
            allow_k = jnp.any(groups[None] & ~viol[:, :, None],
                              axis=1) & allowed_feature[None]  # [Kb, F]
            child_allow = jnp.concatenate([allow_k, allow_k])
        else:
            child_allow = None
        if cfg.feature_fraction_bynode < 1.0 and node_key is not None:
            base = (child_allow if child_allow is not None
                    else jnp.broadcast_to(allowed_feature,
                                          (2 * Kb, F_meta)))
            child_allow = bynode_mask(base, s.split_idx)

        # ---- intermediate-mode membership updates ----------------------
        if use_mono_inter:
            # children inherit the split leaf's subtree memberships
            # (column copy), then register under the new node
            ml = s.mono_left.at[:, new_ids].set(s.mono_left[:, tl_safe])
            mr = s.mono_right.at[:, new_ids].set(
                s.mono_right[:, tl_safe])
            ml = ml.at[node_ids, tl_safe].set(True)
            mr = mr.at[node_ids, new_ids].set(True)
        else:
            ml, mr = s.mono_left, s.mono_right
        ids2 = jnp.concatenate([tl_safe, new_ids])
        if use_mono_adv:
            # per-leaf feature bin ranges: children inherit the split
            # leaf's ranges; a NUMERICAL split narrows the split
            # feature's range at the threshold (categorical splits
            # leave ranges whole — their nodes bind whole subtrees)
            flo_p = s.leaf_flo[tl_safe]                  # [Kb, F_meta]
            fhi_p = s.leaf_fhi[tl_safe]
            oh_sf = (feat_sel[:, None]
                     == jnp.arange(F_meta, dtype=i32)[None, :])
            upd = oh_sf & valid[:, None]
            if cfg.has_categorical:
                upd = upd & ~cat_sel[:, None]
            fhi_left = jnp.where(upd, thr_sel[:, None], fhi_p)
            flo_right = jnp.where(upd, thr_sel[:, None] + 1, flo_p)
            leaf_flo2 = s.leaf_flo.at[ids2].set(
                jnp.concatenate([flo_p, flo_right]))
            leaf_fhi2 = s.leaf_fhi.at[ids2].set(
                jnp.concatenate([fhi_left, fhi_p]))
        else:
            leaf_flo2, leaf_fhi2 = s.leaf_flo, s.leaf_fhi

        # ---- best splits for all 2*Kb children -------------------------
        child_hists = jnp.concatenate([left_hist, right_hist])
        child_sums = jnp.concatenate([lsums, rsums])
        bests = search_best(child_hists, child_sums,
                            child_lower, child_upper, child_allow,
                            parent_outs=(jnp.concatenate([lvals, rvals])
                                         if cfg.path_smooth > 0.0
                                         else None),
                            round_tag=s.split_idx,
                            depths=(jnp.concatenate([depth2, depth2])
                                    if cfg.monotone_penalty > 0.0
                                    else None),
                            pen2=(lazy_pen2(ids2, leaf_id, child_used)
                                  if lazy is not None else None))

        # ---- tree wiring -----------------------------------------------
        lc = s.left_child.at[node_ids].set(-top_leaf - 1)
        rc = s.right_child.at[node_ids].set(-new_ids - 1)
        p = s.leaf_parent[tl_safe]
        was_left = s.leaf_is_left[tl_safe]
        fix_l = jnp.where(valid & (p >= 0) & was_left, p, node_trash)
        fix_r = jnp.where(valid & (p >= 0) & ~was_left, p, node_trash)
        # trash-lane writes land in the unused node slot L-1
        lc = lc.at[fix_l].set(jnp.where(fix_l == node_trash, lc[fix_l],
                                        node_ids))
        rc = rc.at[fix_r].set(jnp.where(fix_r == node_trash, rc[fix_r],
                                        node_ids))

        # ---- forced-entry state resolution -----------------------------
        if forced is not None:
            sel_applied = lane_match & applied_k[:, None]    # [Kb, M]
            applied_entry = jnp.any(sel_applied, axis=0)     # [M]
            attempted = jnp.any(lane_match & flane[:, None], axis=0)
            skipped = attempted & ~applied_entry
            fp_c = jnp.clip(f_parent, 0, M_f - 1)
            # children resolve against the lane where their parent
            # applied: left child keeps the parent's leaf slot, right
            # child takes the new leaf id minted in that lane
            pm = sel_applied[:, fp_c]                        # [Kb, M]
            child_tgt = jnp.where(
                f_is_left,
                jnp.sum(jnp.where(pm, tl_safe[:, None], 0), axis=0),
                jnp.sum(jnp.where(pm, new_ids[:, None], 0), axis=0))
            resolved_now = jnp.any(pm, axis=0) & (f_parent >= 0)
            parent_dead = (f_parent >= 0) & (
                skipped[fp_c] | (tgt[fp_c] == -2))
            forced_tgt_next = jnp.where(
                applied_entry, -3,
                jnp.where(skipped, -2,
                          jnp.where(tgt == -1,
                                    jnp.where(resolved_now, child_tgt,
                                              jnp.where(parent_dead,
                                                        -2, -1)),
                                    tgt))).astype(i32)

        new = GrowState(
            split_idx=s.split_idx + nv,
            num_leaves=s.num_leaves + nv,
            has_split=jnp.array(True),
            leaf_id=leaf_id,
            leaf_hist=leaf_hist,
            leaf_sums=s.leaf_sums.at[ids2].set(child_sums),
            leaf_depth=s.leaf_depth.at[ids2].set(
                jnp.concatenate([depth2, depth2])),
            best_gain=s.best_gain.at[ids2].set(bests["gain"]),
            best_feature=s.best_feature.at[ids2].set(bests["feature"]),
            best_threshold=s.best_threshold.at[ids2].set(
                bests["threshold_bin"]),
            best_default_left=s.best_default_left.at[ids2].set(
                bests["default_left"]),
            best_lr_sums=s.best_lr_sums.at[ids2].set(
                jnp.stack([bests["left_sums"], bests["right_sums"]],
                          axis=1)),
            best_is_cat=s.best_is_cat.at[ids2].set(bests["is_cat"]),
            best_cat_bitset=s.best_cat_bitset.at[ids2].set(
                bests["cat_bitset"]),
            split_feature=s.split_feature.at[node_ids].set(feat_sel),
            threshold_bin=s.threshold_bin.at[node_ids].set(thr_sel),
            default_left=s.default_left.at[node_ids].set(dl_sel),
            node_is_cat=s.node_is_cat.at[node_ids].set(
                cat_sel if cfg.has_categorical
                else s.best_is_cat[tl_safe]),
            node_cat_bitset=s.node_cat_bitset.at[node_ids].set(
                bs_sel if cfg.has_categorical
                else s.best_cat_bitset[tl_safe]),
            left_child=lc,
            right_child=rc,
            node_vcg=s.node_vcg.at[node_ids].set(jnp.stack(
                [s.leaf_vcw[tl_safe, 0] if cfg.path_smooth > 0.0
                 else leaf_out(psums),
                 psums[:, 2], gain_rec], axis=1)),
            leaf_vcw=s.leaf_vcw.at[ids2].set(jnp.stack(
                [jnp.concatenate([lvals, rvals]),
                 child_sums[:, 2], child_sums[:, 1]], axis=1)),
            leaf_parent=s.leaf_parent.at[ids2].set(
                jnp.concatenate([node_ids, node_ids])),
            leaf_is_left=s.leaf_is_left.at[ids2].set(
                jnp.concatenate([jnp.ones(Kb, jnp.bool_),
                                 jnp.zeros(Kb, jnp.bool_)])),
            leaf_bounds=(s.leaf_bounds.at[ids2].set(
                jnp.stack([child_lower, child_upper], axis=1))
                if cfg.has_monotone else s.leaf_bounds),
            leaf_used=(s.leaf_used.at[ids2].set(child_used)
                       if (cfg.has_interaction or cfg.has_cegb_lazy)
                       else s.leaf_used),
            mono_left=ml,
            mono_right=mr,
            leaf_flo=leaf_flo2,
            leaf_fhi=leaf_fhi2,
            leaf_id_c=leaf_id_c,
            forced_target=(forced_tgt_next if forced is not None
                           else s.forced_target),
            part_bins=p_bins,
            part_vals=p_vals,
            part_leaf=p_leaf,
            part_off=p_off,
            part_cnt=p_cnt,
            rows_scanned=s.rows_scanned + span_rows,
        )
        next_gains = _masked_gains(new.best_gain, new.leaf_depth,
                                   new.num_leaves, cfg.max_depth)
        keep_going = jnp.isfinite(jnp.max(next_gains)) & (nv > 0)
        if forced is not None:
            # forced rounds may split nothing (entries skipped at
            # runtime: empty child, depth cap). Growth must neither
            # terminate while entries remain NOR when the LAST entries
            # cancel in a zero-split round — free growth resumes next
            # round as long as any leaf still has finite gain.
            keep_going = (keep_going
                          | jnp.any((forced_tgt_next == -1)
                                    | (forced_tgt_next >= 0))
                          | (in_forced
                             & jnp.isfinite(jnp.max(next_gains))))
        return new._replace(has_split=keep_going)

    final = jax.lax.while_loop(cond, body, state)

    nn = max(L - 1, 1)
    # total rows the histogram scans touched this tree: the structural
    # "fewer rows" win of the partition path (masked = n per round);
    # summed over shards so every device reports the global figure
    rows_scanned = final.rows_scanned
    if cfg.axis_name:
        rows_scanned = jax.lax.psum(rows_scanned, cfg.axis_name)
    tree = {
        "num_leaves": final.num_leaves,
        "split_feature": final.split_feature[:nn],
        "threshold_bin": final.threshold_bin[:nn],
        "default_left": final.default_left[:nn],
        "left_child": final.left_child[:nn],
        "right_child": final.right_child[:nn],
        "split_gain": final.node_vcg[:nn, 2],
        "internal_value": final.node_vcg[:nn, 0],
        "internal_count": final.node_vcg[:nn, 1],
        "leaf_value": final.leaf_vcw[:L, 0],
        "leaf_count": final.leaf_vcw[:L, 1],
        "leaf_weight": final.leaf_vcw[:L, 2],
        "hist_rows": rows_scanned,
    }
    if cfg.has_categorical:
        # only emitted when categorical features exist, so downstream
        # traversal (tree_predict_binned) skips the bitset branch — and
        # its per-row gathers — on pure-numerical datasets
        tree["is_cat"] = final.node_is_cat[:nn]
        tree["cat_bitset"] = final.node_cat_bitset[:nn]
    if cfg.has_cegb_lazy:
        # per-leaf path-feature sets ([L, F]): the boosting engine
        # folds them into the per-row acquisition matrix device-side
        # (rows acquire a feature when a split on it is applied above
        # them — cost_effective_gradient_boosting.hpp)
        tree["leaf_used"] = final.leaf_used[:L]
    return tree, final.leaf_id
