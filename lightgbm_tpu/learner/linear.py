"""Linear-tree leaf refinement.

Reference: ``LinearTreeLearner`` (src/treelearner/linear_tree_learner.cpp,
UNVERIFIED — empty mount, see SURVEY.md banner): after the tree STRUCTURE
is grown by the standard learner, each leaf's constant output is replaced
by a ridge-regularized linear model over the numerical features on the
leaf's root-to-leaf path, fitted by hessian-weighted least squares on the
leaf's rows (the reference solves with Eigen; coefficient count per leaf
= path depth, so the systems are tiny).

TPU-first split of labor: the tree growth stays the jitted device
program; the per-leaf solves are host numpy (a handful of <=depth-sized
normal equations — scalar work the MXU has no business doing). Rows with
NaN in any leaf feature fall back to the constant, like the reference.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


def _parents_map(tree) -> dict:
    parents = {}
    for nd in range(tree.num_nodes):
        for child in (int(tree.left_child[nd]), int(tree.right_child[nd])):
            parents[child] = nd
    return parents


def path_features(tree, leaf: int, max_feats: int,
                  parents: Optional[dict] = None) -> List[int]:
    """Numerical feature indices on the root->leaf path (deduped,
    root-first)."""
    if parents is None:
        parents = _parents_map(tree)
    out: List[int] = []
    node = -leaf - 1
    while node in parents:
        nd = parents[node]
        f = int(tree.split_feature[nd])
        is_cat = (tree.is_categorical is not None
                  and bool(tree.is_categorical[nd]))
        if not is_cat and f not in out:
            out.append(f)
        node = nd
    out.reverse()
    return out[:max_feats]


def fit_linear_leaves(tree, leaf_id: np.ndarray, X_used: np.ndarray,
                      g: np.ndarray, h: np.ndarray, lambda_l2: float,
                      linear_lambda: float, shrinkage: float,
                      min_rows: int = 10) -> np.ndarray:
    """Fit per-leaf linear models in place; returns the per-row delta
    (new_prediction - old_constant) * shrinkage for the score update.

    The target of leaf L's weighted ridge is the Newton step: minimize
    ``sum_i h_i (beta . [x_i, 1] + g_i / h_i)^2 + reg`` — whose constant
    -only solution is exactly the leaf's standard output.
    """
    n = len(leaf_id)
    delta = np.zeros(n, dtype=np.float64)
    nl = tree.num_leaves
    coeffs: List[Optional[np.ndarray]] = [None] * nl
    feats: List[List[int]] = [[] for _ in range(nl)]
    consts = np.array(tree.leaf_value, dtype=np.float64)
    parents = _parents_map(tree)
    for lf in range(nl):
        rows = np.flatnonzero(leaf_id == lf)
        pf = path_features(tree, lf, max_feats=10, parents=parents)
        if len(rows) < max(min_rows, len(pf) + 2) or not pf:
            continue
        A = X_used[np.ix_(rows, pf)]
        ok = np.isfinite(A).all(axis=1)
        rows, A = rows[ok], A[ok]
        if len(rows) < max(min_rows, len(pf) + 2):
            continue
        hw = np.maximum(h[rows], 1e-12)
        target = -g[rows] / hw
        Ab = np.concatenate([A, np.ones((len(rows), 1))], axis=1)
        W = hw[:, None]
        lhs = Ab.T @ (W * Ab)
        reg = np.full(len(pf) + 1, lambda_l2 + linear_lambda)
        reg[-1] = lambda_l2            # intercept: plain l2 only
        lhs[np.diag_indices_from(lhs)] += reg
        rhs = Ab.T @ (hw * target)
        try:
            beta = np.linalg.solve(lhs, rhs)
        except np.linalg.LinAlgError:
            continue
        if not np.isfinite(beta).all():
            continue
        pred = Ab @ beta
        coeffs[lf] = beta
        feats[lf] = pf
        # tree.leaf_value stays the CONSTANT — the non-finite-feature
        # fallback at predict time, like the reference
        delta[rows] = pred * shrinkage - consts[lf]
    tree.leaf_features = feats
    tree.leaf_coeff = [None if c is None else c * shrinkage
                       for c in coeffs]
    tree.is_linear = any(c is not None for c in coeffs)
    return delta


def predict_linear(tree, X_used: np.ndarray,
                   leaf: np.ndarray) -> np.ndarray:
    """Leaf outputs with linear models applied. A row whose linear-leaf
    features contain a non-finite value falls back to the CONSTANT
    leaf_value (tree.h Tree::Predict sets nan_found and returns
    LeafOutput); leaves whose model has no features always output
    leaf_const (the coefficient loop is empty, so nan_found never
    trips) — both pinned by tests/test_model_fixture.py. Leaves without
    a model at all (coeff None, degenerate fit) use leaf_value."""
    out = np.asarray(tree.leaf_value, dtype=np.float64)[leaf]
    if not getattr(tree, "is_linear", False):
        return out
    for lf, beta in enumerate(tree.leaf_coeff):
        if beta is None:
            continue
        rows = np.flatnonzero(leaf == lf)
        if not len(rows):
            continue
        A = X_used[np.ix_(rows, tree.leaf_features[lf])] \
            if len(tree.leaf_features[lf]) else \
            np.zeros((len(rows), 0))
        ok = np.isfinite(A).all(axis=1)
        out[rows[ok]] = A[ok] @ beta[:-1] + beta[-1]
    return out
