"""Shared cross-device histogram reduction — the packed int32 wire.

Reference: the socket ``Network::Allreduce`` the reference learners call
on their per-machine histograms (data_parallel_tree_learner.cpp,
SURVEY.md §3.4, UNVERIFIED — empty mount). TPU-native replacement: ONE
``psum`` (or ``psum_scatter`` for ReduceScatter feature ownership) over
a mesh axis, optionally on the packed quantized wire
(``tpu_hist_packed_wire``, docs/perf.md "packed-wire design").

Factored out of ``learner/serial.py``'s ``grow_tree`` closures so the
out-of-core streaming engine (boosting/streaming.py) reduces its
accumulated per-level histograms through the SAME wire instead of
growing a second reduction path: both callers get the identical
packing, guard, and fallback semantics from one definition.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["hist_allreduce"]


def hist_allreduce(h: jax.Array, axis_name: str, *,
                   scatter: bool = False, scatter_dim: int = 1,
                   packed: bool = False) -> jax.Array:
    """Reduce a ``[..., 3]`` (grad, hess, count) histogram over a mesh
    axis: one collective per call.

    Args:
      h: local partial histogram, last dim = (g, h, count) channels.
      axis_name: mesh axis to reduce over.
      scatter: use ``psum_scatter`` (ReduceScatter feature ownership —
        each device receives the summed slice of ``scatter_dim`` it
        owns) instead of a full ``psum``.
      packed: engage the packed quantized wire — each (g, h) level-sum
        pair rides ONE int32 (g in the high 16 bits, non-negative h in
        the low 16) and count rides a second int32: 2/3 of the f32
        payload, bit-exact. Per-lane modular addition is carry-free
        because the low (hessian) lane is non-negative and its GLOBAL
        sum stays under 2^15 — guaranteed by a 3-scalar guard psum of
        sum-of-local-extreme bounds (|Σ_d x_d| <= Σ_d max|x_d|); any
        risk of int16 overflow (or a negative hessian from a custom
        objective) falls back to the f32 reduction inside the same
        jitted step. Only valid when ``h`` carries small integer
        values (quantized gradient levels).

    Returns the reduced histogram in the INPUT units — callers owning
    a quantization scale rescale to real units themselves, after (and
    outside) the reduction, so integer sums stay exact on the wire.
    """
    def _reduce(x):
        if scatter:
            return jax.lax.psum_scatter(x, axis_name,
                                        scatter_dimension=scatter_dim,
                                        tiled=True)
        return jax.lax.psum(x, axis_name)

    if not packed:
        h = _reduce(h)
    else:
        def _packed_reduce(hh):
            gi = hh[..., 0].astype(jnp.int32)
            hi = hh[..., 1].astype(jnp.int32)
            ci = hh[..., 2].astype(jnp.int32)
            p = jnp.stack([(gi << 16) | (hi & 0xFFFF), ci], axis=-1)
            p = _reduce(p)
            g_out = (p[..., 0] >> 16).astype(jnp.float32)
            h_out = (p[..., 0] & 0xFFFF).astype(jnp.float32)
            return jnp.stack([g_out, h_out,
                              p[..., 1].astype(jnp.float32)], axis=-1)

        loc = jnp.stack([jnp.max(jnp.abs(h[..., 0])),
                         jnp.max(h[..., 1]),
                         jnp.maximum(-jnp.min(h[..., 1]), 0.0)])
        glob = jax.lax.psum(loc, axis_name)
        safe = ((glob[0] < 32767.0) & (glob[1] < 32767.0)
                & (glob[2] <= 0.0))
        h = jax.lax.cond(safe, _packed_reduce, _reduce, h)
    return h
