// Fast delimited-text parser for the dataset loader.
//
// Reference: the reference framework's C++ text readers
// (include/LightGBM/utils/text_reader.h + src/io/parser.cpp, UNVERIFIED —
// empty mount, see SURVEY.md banner) stream CSV/TSV/LibSVM with custom
// atof loops because libc strtod + Python-level splitting dominate load
// time at multi-GB scale. This is the TPU framework's equivalent native
// runtime piece: a ctypes-loaded shared object (no pybind11 in the
// image), compiled on demand by native/__init__.py.
//
// Exposed C ABI:
//   count_lines(path)                      -> data lines (non-empty)
//   count_fields(path, delim)              -> fields in first data line
//   parse_dense(path, delim, skip, out, max_rows, n_cols) -> rows parsed
//   parse_libsvm(path, skip, rows_out, cols_out, vals_out, labels_out,
//                max_nnz, max_rows)        -> nnz parsed (labels per row)
//
// Missing fields ("", "NA", "na", "nan", "?") parse as NaN. Lines whose
// first non-space char is '#' are skipped.
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

bool read_file(const char* path, std::vector<char>& buf) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long size = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    buf.resize(static_cast<size_t>(size) + 1);
    size_t got = std::fread(buf.data(), 1, static_cast<size_t>(size), f);
    std::fclose(f);
    buf[got] = '\0';
    buf.resize(got + 1);
    return true;
}

inline bool is_missing_token(const char* s, const char* end) {
    size_t len = static_cast<size_t>(end - s);
    if (len == 0) return true;
    if (len == 1 && *s == '?') return true;
    if ((len == 2) && (s[0] == 'N' || s[0] == 'n')
        && (s[1] == 'A' || s[1] == 'a')) return true;
    return false;
}

inline double parse_field(const char* s, const char* end) {
    while (s < end && (*s == ' ' || *s == '\r')) ++s;
    const char* e = end;
    while (e > s && (e[-1] == ' ' || e[-1] == '\r')) --e;
    if (is_missing_token(s, e)) return NAN;
    char* parse_end = nullptr;
    double v = std::strtod(s, &parse_end);
    if (parse_end == s) return NAN;
    return v;
}

inline bool skip_line(const char* p, const char* nl) {
    while (p < nl && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    return p >= nl || *p == '#';
}

}  // namespace

extern "C" {

long count_lines(const char* path) {
    std::vector<char> buf;
    if (!read_file(path, buf)) return -1;
    long n = 0;
    const char* p = buf.data();
    const char* end = p + buf.size() - 1;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!nl) nl = end;
        if (!skip_line(p, nl)) ++n;
        p = nl + 1;
    }
    return n;
}

int count_fields(const char* path, char delim) {
    std::vector<char> buf;
    if (!read_file(path, buf)) return -1;
    const char* p = buf.data();
    const char* end = p + buf.size() - 1;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!nl) nl = end;
        if (!skip_line(p, nl)) {
            int n = 1;
            for (const char* q = p; q < nl; ++q)
                if (*q == delim) ++n;
            return n;
        }
        p = nl + 1;
    }
    return 0;
}

long parse_dense(const char* path, char delim, int skip_rows,
                 double* out, long max_rows, int n_cols) {
    std::vector<char> buf;
    if (!read_file(path, buf)) return -1;
    const char* p = buf.data();
    const char* end = p + buf.size() - 1;
    long row = 0;
    int to_skip = skip_rows;
    while (p < end && row < max_rows) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!nl) nl = end;
        if (!skip_line(p, nl)) {
            if (to_skip > 0) {
                --to_skip;
            } else {
                double* dst = out + row * n_cols;
                const char* fs = p;
                int c = 0;
                for (const char* q = p; q <= nl && c < n_cols; ++q) {
                    if (q == nl || *q == delim) {
                        dst[c++] = parse_field(fs, q);
                        fs = q + 1;
                    }
                }
                for (; c < n_cols; ++c) dst[c] = NAN;
                ++row;
            }
        }
        p = nl + 1;
    }
    return row;
}

long parse_libsvm(const char* path, int skip_rows, int* rows_out,
                  int* cols_out, double* vals_out, double* labels_out,
                  long max_nnz, long max_rows) {
    std::vector<char> buf;
    if (!read_file(path, buf)) return -1;
    const char* p = buf.data();
    const char* end = p + buf.size() - 1;
    long nnz = 0;
    long row = 0;
    int to_skip = skip_rows;
    while (p < end && row < max_rows) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!nl) nl = end;
        if (!skip_line(p, nl)) {
            if (to_skip > 0) {
                --to_skip;
            } else {
                char* q = nullptr;
                labels_out[row] = std::strtod(p, &q);
                while (q < nl) {
                    while (q < nl && *q == ' ') ++q;
                    if (q >= nl) break;
                    char* colon = nullptr;
                    long idx = std::strtol(q, &colon, 10);
                    if (colon == q || *colon != ':') break;
                    char* vend = nullptr;
                    double v = std::strtod(colon + 1, &vend);
                    if (vend == colon + 1) break;
                    if (nnz >= max_nnz) return -2;
                    rows_out[nnz] = static_cast<int>(row);
                    cols_out[nnz] = static_cast<int>(idx);
                    vals_out[nnz] = v;
                    ++nnz;
                    q = vend;
                }
                ++row;
            }
        }
        p = nl + 1;
    }
    return nnz;
}

}  // extern "C"
