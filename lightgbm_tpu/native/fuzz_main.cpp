// Standalone fuzz driver for the C ABI model parser (VERDICT r4 item 5).
//
// Compiled by scripts/fuzz_c_api.sh with -fsanitize=address,undefined
// and fed the truncation/bit-flip corpus that
// tests/test_c_api.py::test_fuzz_truncated_and_bitflipped_models
// generates: every model file must either parse cleanly (then predict
// a few rows) or return an error code — never read out of bounds,
// leak, or abort. ASAN+UBSAN turn any OOB/UB into a nonzero exit.
//
// Usage: fuzz_main MODEL_FILE...   (exit 0 = all handled cleanly)
#include <cmath>
#include <cstdio>
#include <cstdint>
#include <string>
#include <vector>

extern "C" {
int LGBMTPU_BoosterCreateFromModelfile(const char*, int*, void**);
int LGBMTPU_BoosterFree(void*);
int LGBMTPU_BoosterGetNumFeature(void*, int*);
int LGBMTPU_BoosterGetNumTreePerIteration(void*, int*);
int LGBMTPU_BoosterPredictForMat(void*, const double*, int32_t, int32_t,
                                 int, int, int, int, double*, int64_t*);
const char* LGBMTPU_GetLastError();
}

int main(int argc, char** argv) {
  int failures = 0;
  for (int a = 1; a < argc; ++a) {
    int num_iters = 0;
    void* h = nullptr;
    const int rc = LGBMTPU_BoosterCreateFromModelfile(argv[a], &num_iters,
                                                      &h);
    if (rc != 0) continue;  // clean rejection is a pass
    int nf = 0, k = 0;
    if (LGBMTPU_BoosterGetNumFeature(h, &nf) != 0 || nf <= 0 ||
        nf > 1 << 20 ||
        LGBMTPU_BoosterGetNumTreePerIteration(h, &k) != 0 || k <= 0 ||
        k > 64) {
      LGBMTPU_BoosterFree(h);
      continue;
    }
    // parse survived: predict must survive too (8 rows, mixed values
    // incl. NaN to drive the missing paths)
    const int32_t n = 8;
    std::vector<double> X(static_cast<size_t>(n) * nf);
    for (size_t i = 0; i < X.size(); ++i) {
      X[i] = (i % 7 == 0) ? std::nan("") : (double)(i % 13) - 6.0;
    }
    std::vector<double> out(static_cast<size_t>(n) * k, 0.0);
    int64_t out_len = 0;
    const int prc = LGBMTPU_BoosterPredictForMat(
        h, X.data(), n, nf, /*is_row_major=*/1, /*predict_type=*/0,
        /*start_iteration=*/0, /*num_iteration=*/-1, out.data(),
        &out_len);
    if (prc != 0) {
      std::fprintf(stderr, "%s: predict failed after clean parse: %s\n",
                   argv[a], LGBMTPU_GetLastError());
      ++failures;
    }
    LGBMTPU_BoosterFree(h);
  }
  return failures == 0 ? 0 : 1;
}
