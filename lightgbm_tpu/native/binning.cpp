// Native binning hot paths (exact ports of io/binning.py).
//
// Reference analog: BinMapper::FindBin / GreedyFindBin and
// DenseBin::Push (src/io/bin.cpp, UNVERIFIED — empty mount, see
// SURVEY.md banner). Two costs dominate host-side dataset
// construction at flagship scale (measured, docs/perf.md):
//   1. the greedy equal-mass bound search — a Python loop over ~100k
//      distinct sample values, twice per feature (neg/pos sides);
//   2. the value->bin apply — seven numpy passes over each 10M-row
//      column (asarray, isnan, where, searchsorted, clip, where,
//      astype).
// Both are bit-exact ports: the Python implementations remain as the
// no-toolchain fallback, and tests/test_native_binning.py pins
// native == Python on randomized inputs.

#include <cmath>
#include <cstdint>

extern "C" {

// Exact port of _greedy_find_distinct_bounds (io/binning.py).
// Returns the number of bounds written to `out` (capacity max_bin+1);
// the last bound is +inf.
int64_t greedy_find_bounds(const double* dv, const int64_t* counts,
                           int64_t n_distinct, int64_t max_bin,
                           int64_t total_cnt, int64_t min_data_in_bin,
                           double* out) {
  const double kInf = INFINITY;
  int64_t n_out = 0;
  if (n_distinct == 0) {
    out[n_out++] = kInf;
    return n_out;
  }
  if (n_distinct <= max_bin) {
    int64_t cur_cnt = 0;
    for (int64_t i = 0; i + 1 < n_distinct; ++i) {
      cur_cnt += counts[i];
      if (cur_cnt >= min_data_in_bin) {
        out[n_out++] = (dv[i] + dv[i + 1]) / 2.0;
        cur_cnt = 0;
      }
    }
    out[n_out++] = kInf;
    return n_out;
  }
  if (min_data_in_bin > 0) {
    const int64_t cap = total_cnt / min_data_in_bin;
    const int64_t cap1 = cap > 1 ? cap : 1;
    if (cap1 < max_bin) max_bin = cap1;
  }
  double mean_size = static_cast<double>(total_cnt)
                     / static_cast<double>(max_bin);
  // is_big per value + aggregates (the Python computes these
  // vectorized; identical results)
  int64_t big_cnt_sum = 0, big_n = 0;
  for (int64_t i = 0; i < n_distinct; ++i) {
    if (static_cast<double>(counts[i]) >= mean_size) {
      big_cnt_sum += counts[i];
      ++big_n;
    }
  }
  double rest_cnt = static_cast<double>(total_cnt - big_cnt_sum);
  int64_t rest_bins = max_bin - big_n;
  mean_size = rest_bins > 0 ? rest_cnt / static_cast<double>(rest_bins)
                            : INFINITY;
  const double big_thresh = static_cast<double>(total_cnt)
                            / static_cast<double>(max_bin);
  auto is_big = [&](int64_t i) {
    return static_cast<double>(counts[i]) >= big_thresh;
  };
  int64_t cur_cnt = 0;
  int64_t n_upper = 0;
  for (int64_t i = 0; i + 1 < n_distinct; ++i) {
    const bool big_i = is_big(i);
    if (!big_i) rest_cnt -= static_cast<double>(counts[i]);
    cur_cnt += counts[i];
    const double cc = static_cast<double>(cur_cnt);
    const double half = mean_size * 0.5 > 1.0 ? mean_size * 0.5 : 1.0;
    if (big_i || cc >= mean_size || (is_big(i + 1) && cc >= half)) {
      out[n_out++] = (dv[i] + dv[i + 1]) / 2.0;
      ++n_upper;
      cur_cnt = 0;
      if (n_upper >= max_bin - 1) break;
      if (!big_i) {
        --rest_bins;
        if (rest_bins > 0) {
          mean_size = rest_cnt / static_cast<double>(rest_bins);
        }
      }
    }
  }
  out[n_out++] = kInf;
  return n_out;
}

// Exact port of BinMapper.values_to_bins's numerical branch: one pass,
// NaN-aware, strided in and out.
//   missing_type: 0 none / 1 zero / 2 nan (binning.py _MISSING codes)
//   out_kind: 0 uint8 / 1 uint16 / 2 int32
void bin_numeric_column(const void* values, int is_f32, int64_t n,
                        int64_t v_stride, const double* ub, int64_t nb,
                        int missing_type, int64_t default_bin,
                        int64_t num_bin, void* out, int out_kind,
                        int64_t out_stride) {
  const float* vf = static_cast<const float*>(values);
  const double* vd = static_cast<const double*>(values);
  uint8_t* o8 = static_cast<uint8_t*>(out);
  uint16_t* o16 = static_cast<uint16_t*>(out);
  int32_t* o32 = static_cast<int32_t*>(out);
  for (int64_t i = 0; i < n; ++i) {
    const double v = is_f32 ? static_cast<double>(vf[i * v_stride])
                            : vd[i * v_stride];
    int64_t b;
    if (std::isnan(v)) {
      // none/zero route NaN to the zero bin (== default_bin); the nan
      // type owns the last bin
      b = missing_type == 2 ? num_bin - 1 : default_bin;
    } else {
      // np.searchsorted(ub, v, side="left"): first idx with ub[i] >= v
      int64_t lo = 0, hi = nb;
      while (lo < hi) {
        const int64_t mid = (lo + hi) >> 1;
        if (ub[mid] < v) lo = mid + 1; else hi = mid;
      }
      b = lo < nb - 1 ? lo : nb - 1;  // np.clip(vb, 0, nb-1)
    }
    const int64_t j = i * out_stride;
    if (out_kind == 0) o8[j] = static_cast<uint8_t>(b);
    else if (out_kind == 1) o16[j] = static_cast<uint16_t>(b);
    else o32[j] = static_cast<int32_t>(b);
  }
}

}  // extern "C"


// Bin every (numeric) column of a dense row-major matrix in ONE
// row-major pass — column-at-a-time binning of a [n, F] matrix strides
// F*itemsize bytes per element and cache-misses every read (measured
// 74 ns/elem at Higgs-10M). When every column has <= 256 bounds (the
// max_bin=255 norm), the search runs BRANCHLESS over bound tables
// padded to a fixed 256 doubles, interleaved across the row's columns
// so the L2 probe latencies overlap (8 fixed steps, conditional-move
// adds, ~6x over the scalar binary-search loop; measured in
// docs/perf.md). Non-numeric output columns (is_num[c] == 0) are
// skipped and filled by the caller. Output is row-major [n_rows,
// n_cols].
//   ub_concat/ub_off: concatenated per-column upper bounds,
//     column c's bounds live in [ub_off[c], ub_off[c+1]).
#include <cstdlib>
#include <cstring>

namespace {

// generic per-element fallback (any bound count)
inline int64_t SearchClip(const double* ub, int64_t nb, double v) {
  int64_t lo = 0, hi = nb;
  while (lo < hi) {
    const int64_t mid = (lo + hi) >> 1;
    if (ub[mid] < v) lo = mid + 1; else hi = mid;
  }
  return lo < nb - 1 ? lo : nb - 1;
}

}  // namespace

extern "C" {

void bin_matrix(const void* X, int is_f32, int64_t n_rows,
                int64_t row_stride, const int64_t* col_idx,
                int64_t n_cols, const double* ub_concat,
                const int64_t* ub_off, const int* missing_type,
                const int64_t* default_bin, const int64_t* num_bin,
                const int* is_num, void* out, int out_kind) {
  const float* xf = static_cast<const float*>(X);
  const double* xd = static_cast<const double*>(X);
  uint8_t* o8 = static_cast<uint8_t*>(out);
  uint16_t* o16 = static_cast<uint16_t*>(out);
  int32_t* o32 = static_cast<int32_t*>(out);

  bool fast = n_cols <= 512;
  for (int64_t c = 0; c < n_cols && fast; ++c) {
    if (is_num[c] && ub_off[c + 1] - ub_off[c] > 256) fast = false;
  }
  if (fast) {
    // padded fixed-depth tables: tab[c] has 256 entries, real bounds
    // first, +inf padding after (padding never changes the clipped
    // searchsorted-left result because the real last bound IS +inf)
    double* tab = static_cast<double*>(
        std::malloc(static_cast<size_t>(n_cols) * 256 * sizeof(double)));
    int64_t nb_m1[512];
    for (int64_t c = 0; c < n_cols; ++c) {
      double* t = tab + c * 256;
      const int64_t nb = is_num[c] ? ub_off[c + 1] - ub_off[c] : 1;
      for (int64_t i = 0; i < 256; ++i) {
        t[i] = i < nb ? ub_concat[ub_off[c] + i] : INFINITY;
      }
      nb_m1[c] = nb - 1;
    }
    double v[512];
    int64_t pos[512];
    for (int64_t r = 0; r < n_rows; ++r) {
      const int64_t rbase = r * row_stride;
      const int64_t obase = r * n_cols;
      for (int64_t c = 0; c < n_cols; ++c) {
        const int64_t src = rbase + col_idx[c];
        v[c] = is_f32 ? static_cast<double>(xf[src]) : xd[src];
        pos[c] = 0;
      }
      // branchless searchsorted-left: pos = #bounds < v. NaN compares
      // false everywhere so pos stays 0 and is overwritten below.
      for (int64_t s = 128; s; s >>= 1) {
        for (int64_t c = 0; c < n_cols; ++c) {
          const double* t = tab + c * 256;
          // mask arithmetic, NOT a ternary: gcc branches the ternary
          // and the 50% mispredicts serialize the probe chain
          // (measured 63 vs 12 ns/elem)
          pos[c] += s & -static_cast<int64_t>(
              t[pos[c] + s - 1] < v[c]);
        }
      }
      for (int64_t c = 0; c < n_cols; ++c) {
        if (!is_num[c]) continue;
        int64_t b = pos[c] < nb_m1[c] ? pos[c] : nb_m1[c];
        if (std::isnan(v[c])) {
          b = missing_type[c] == 2 ? num_bin[c] - 1 : default_bin[c];
        }
        if (out_kind == 0) o8[obase + c] = static_cast<uint8_t>(b);
        else if (out_kind == 1)
          o16[obase + c] = static_cast<uint16_t>(b);
        else o32[obase + c] = static_cast<int32_t>(b);
      }
    }
    std::free(tab);
    return;
  }
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t rbase = r * row_stride;
    const int64_t obase = r * n_cols;
    for (int64_t c = 0; c < n_cols; ++c) {
      if (!is_num[c]) continue;
      const int64_t src = rbase + col_idx[c];
      const double v = is_f32 ? static_cast<double>(xf[src]) : xd[src];
      const double* ub = ub_concat + ub_off[c];
      const int64_t nb = ub_off[c + 1] - ub_off[c];
      int64_t b;
      if (std::isnan(v)) {
        b = missing_type[c] == 2 ? num_bin[c] - 1 : default_bin[c];
      } else {
        b = SearchClip(ub, nb, v);
      }
      if (out_kind == 0) o8[obase + c] = static_cast<uint8_t>(b);
      else if (out_kind == 1) o16[obase + c] = static_cast<uint16_t>(b);
      else o32[obase + c] = static_cast<int32_t>(b);
    }
  }
}

}  // extern "C"
