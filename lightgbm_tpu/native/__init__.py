"""Native runtime pieces: on-demand-compiled C++ loaded via ctypes.

The image has g++ but no pybind11, so native components use the C ABI +
ctypes (the reference's analog is its C API boundary, c_api.cpp). Shared
objects are compiled once per source hash into a cache dir; every native
entry point has a pure-Python fallback so a missing toolchain degrades
gracefully.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_CACHED: dict = {}


def _source_path(name: str) -> str:
    return os.path.join(os.path.dirname(__file__), name)


def load_native(name: str = "text_parser.cpp") -> Optional[ctypes.CDLL]:
    """Compile (cached) + dlopen a native source; None if unavailable."""
    if name in _CACHED:
        return _CACHED[name]
    lib = None
    try:
        src = _source_path(name)
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        cache_dir = os.path.join(tempfile.gettempdir(),
                                 "lightgbm_tpu_native")
        os.makedirs(cache_dir, exist_ok=True)
        so = os.path.join(cache_dir,
                          f"{os.path.splitext(name)[0]}_{digest}.so")
        if not os.path.exists(so):
            tmp = so + f".build{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, src],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        lib = ctypes.CDLL(so)
    except Exception:       # no g++ / sandboxed tmp / bad toolchain
        lib = None
    _CACHED[name] = lib
    return lib


def text_parser() -> Optional[ctypes.CDLL]:
    lib = load_native("text_parser.cpp")
    if lib is None:
        return None
    if not getattr(lib, "_sigs_set", False):
        c = ctypes
        lib.count_lines.restype = c.c_long
        lib.count_lines.argtypes = [c.c_char_p]
        lib.count_fields.restype = c.c_int
        lib.count_fields.argtypes = [c.c_char_p, c.c_char]
        lib.parse_dense.restype = c.c_long
        lib.parse_dense.argtypes = [
            c.c_char_p, c.c_char, c.c_int,
            c.POINTER(c.c_double), c.c_long, c.c_int]
        lib.parse_libsvm.restype = c.c_long
        lib.parse_libsvm.argtypes = [
            c.c_char_p, c.c_int, c.POINTER(c.c_int), c.POINTER(c.c_int),
            c.POINTER(c.c_double), c.POINTER(c.c_double), c.c_long,
            c.c_long]
        lib._sigs_set = True
    return lib
