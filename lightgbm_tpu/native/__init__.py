"""Native runtime pieces: on-demand-compiled C++ loaded via ctypes.

The image has g++ but no pybind11, so native components use the C ABI +
ctypes (the reference's analog is its C API boundary, c_api.cpp). Shared
objects are compiled once per source hash into a cache dir; every native
entry point has a pure-Python fallback so a missing toolchain degrades
gracefully.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_CACHED: dict = {}


def _source_path(name: str) -> str:
    return os.path.join(os.path.dirname(__file__), name)


def load_native(name: str = "text_parser.cpp",
                extra_flags: tuple = ()) -> Optional[ctypes.CDLL]:
    """Compile (cached) + dlopen a native source; None if unavailable."""
    key = (name, extra_flags)
    if key in _CACHED:
        return _CACHED[key]
    lib = None
    try:
        src = _source_path(name)
        with open(src, "rb") as f:
            payload = f.read() + repr(extra_flags).encode()
        digest = hashlib.sha256(payload).hexdigest()[:16]
        cache_dir = os.path.join(tempfile.gettempdir(),
                                 "lightgbm_tpu_native")
        os.makedirs(cache_dir, exist_ok=True)
        so = os.path.join(cache_dir,
                          f"{os.path.splitext(name)[0]}_{digest}.so")
        if not os.path.exists(so):
            tmp = so + f".build{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 *extra_flags, "-o", tmp, src],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        lib = ctypes.CDLL(so)
    except Exception:       # no g++ / sandboxed tmp / bad toolchain
        lib = None
    _CACHED[key] = lib
    return lib


def c_api() -> Optional[ctypes.CDLL]:
    """The minimal LGBMTPU_* C ABI (model load + predict surface).

    Reference analog: src/c_api.cpp's LGBM_* boundary (SURVEY.md L7,
    UNVERIFIED). Only the predict/model functions exist — training is a
    jitted XLA program and gains nothing from a C entry point. See
    native/c_api.cpp's header comment and docs/design.md for the scope
    decision.
    """
    lib = load_native("c_api.cpp", extra_flags=("-fopenmp",))
    if lib is None:
        # -fopenmp may be missing from a stripped toolchain; the ABI is
        # still correct single-threaded
        lib = load_native("c_api.cpp")
    if lib is None:
        return None
    if not getattr(lib, "_sigs_set", False):
        c = ctypes
        H = c.c_void_p
        lib.LGBMTPU_GetLastError.restype = c.c_char_p
        lib.LGBMTPU_GetLastError.argtypes = []
        lib.LGBMTPU_BoosterLoadModelFromString.restype = c.c_int
        lib.LGBMTPU_BoosterLoadModelFromString.argtypes = [
            c.c_char_p, c.POINTER(c.c_int), c.POINTER(H)]
        lib.LGBMTPU_BoosterCreateFromModelfile.restype = c.c_int
        lib.LGBMTPU_BoosterCreateFromModelfile.argtypes = [
            c.c_char_p, c.POINTER(c.c_int), c.POINTER(H)]
        lib.LGBMTPU_BoosterFree.restype = c.c_int
        lib.LGBMTPU_BoosterFree.argtypes = [H]
        for fn in ("GetNumClasses", "GetNumFeature",
                   "GetCurrentIteration", "GetNumTreePerIteration"):
            f = getattr(lib, f"LGBMTPU_Booster{fn}")
            f.restype = c.c_int
            f.argtypes = [H, c.POINTER(c.c_int)]
        lib.LGBMTPU_BoosterSaveModel.restype = c.c_int
        lib.LGBMTPU_BoosterSaveModel.argtypes = [H, c.c_char_p]
        lib.LGBMTPU_BoosterGetModelSize.restype = c.c_int
        lib.LGBMTPU_BoosterGetModelSize.argtypes = [
            H, c.POINTER(c.c_int64)]
        lib.LGBMTPU_BoosterGetModelString.restype = c.c_int
        lib.LGBMTPU_BoosterGetModelString.argtypes = [
            H, c.c_int64, c.c_char_p]
        lib.LGBMTPU_BoosterPredictForMat.restype = c.c_int
        lib.LGBMTPU_BoosterPredictForMat.argtypes = [
            H, c.POINTER(c.c_double), c.c_int32, c.c_int32, c.c_int,
            c.c_int, c.c_int, c.c_int, c.POINTER(c.c_double),
            c.POINTER(c.c_int64)]
        lib._sigs_set = True
    return lib


class CBooster:
    """Thin Python wrapper over the LGBMTPU_* ABI — exists so tests can
    drive the C boundary exactly the way an external C caller would,
    and as living documentation of the calling convention."""

    PREDICT_NORMAL, PREDICT_RAW, PREDICT_LEAF = 0, 1, 2

    def __init__(self, model_str: str = None, model_file: str = None):
        import numpy as np
        self._np = np
        self._lib = c_api()
        if self._lib is None:
            raise RuntimeError("native c_api unavailable (no g++?)")
        h = ctypes.c_void_p()
        it = ctypes.c_int()
        if model_file is not None:
            rc = self._lib.LGBMTPU_BoosterCreateFromModelfile(
                model_file.encode(), ctypes.byref(it), ctypes.byref(h))
        else:
            rc = self._lib.LGBMTPU_BoosterLoadModelFromString(
                model_str.encode(), ctypes.byref(it), ctypes.byref(h))
        if rc != 0:
            raise ValueError(self.last_error())
        self._h = h
        self.num_iterations = it.value

    def last_error(self) -> str:
        return self._lib.LGBMTPU_GetLastError().decode()

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.LGBMTPU_BoosterFree(self._h)
            self._h = None

    def _get_int(self, fn: str) -> int:
        out = ctypes.c_int()
        rc = getattr(self._lib, f"LGBMTPU_Booster{fn}")(
            self._h, ctypes.byref(out))
        if rc != 0:
            raise ValueError(self.last_error())
        return out.value

    @property
    def num_classes(self) -> int:
        return self._get_int("GetNumClasses")

    @property
    def num_feature(self) -> int:
        return self._get_int("GetNumFeature")

    def save_model(self, path: str) -> None:
        if self._lib.LGBMTPU_BoosterSaveModel(self._h,
                                              path.encode()) != 0:
            raise ValueError(self.last_error())

    def model_to_string(self) -> str:
        size = ctypes.c_int64()
        if self._lib.LGBMTPU_BoosterGetModelSize(
                self._h, ctypes.byref(size)) != 0:
            raise ValueError(self.last_error())
        buf = ctypes.create_string_buffer(size.value + 1)
        if self._lib.LGBMTPU_BoosterGetModelString(
                self._h, size.value + 1, buf) != 0:
            raise ValueError(self.last_error())
        return buf.value.decode()

    def predict(self, X, predict_type: int = 0, start_iteration: int = 0,
                num_iteration: int = -1):
        np = self._np
        X = np.ascontiguousarray(np.asarray(X, dtype=np.float64))
        n, ncol = X.shape
        k = self.num_classes
        nt = (self.num_iterations - start_iteration
              if num_iteration <= 0 else
              min(num_iteration, self.num_iterations - start_iteration))
        nt = max(nt, 0)
        if predict_type == self.PREDICT_LEAF:
            width = nt * max(1, self._trees_per_iter)
            if width == 0:
                return np.zeros((n, 0), dtype=np.float64)
        else:
            width = k
        out = np.zeros((n, width), dtype=np.float64)
        out_len = ctypes.c_int64()
        rc = self._lib.LGBMTPU_BoosterPredictForMat(
            self._h, X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n, ncol, 1, predict_type, start_iteration, num_iteration,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.byref(out_len))
        if rc != 0:
            raise ValueError(self.last_error())
        assert out_len.value == n * width
        if width == 1:
            return out[:, 0]
        return out

    @property
    def _trees_per_iter(self) -> int:
        # num_tree_per_iteration == num_class for multiclass
        return self._get_int("GetNumTreePerIteration")


def binning() -> Optional[ctypes.CDLL]:
    """Native binning hot paths (greedy bound search + bin apply);
    bit-exact ports of io/binning.py's Python implementations, which
    remain the fallback."""
    lib = load_native("binning.cpp")
    if lib is None:
        return None
    if not getattr(lib, "_sigs_set", False):
        c = ctypes
        lib.greedy_find_bounds.restype = c.c_int64
        lib.greedy_find_bounds.argtypes = [
            c.POINTER(c.c_double), c.POINTER(c.c_int64), c.c_int64,
            c.c_int64, c.c_int64, c.c_int64, c.POINTER(c.c_double)]
        lib.bin_numeric_column.restype = None
        lib.bin_numeric_column.argtypes = [
            c.c_void_p, c.c_int, c.c_int64, c.c_int64,
            c.POINTER(c.c_double), c.c_int64, c.c_int, c.c_int64,
            c.c_int64, c.c_void_p, c.c_int, c.c_int64]
        lib.bin_matrix.restype = None
        lib.bin_matrix.argtypes = [
            c.c_void_p, c.c_int, c.c_int64, c.c_int64,
            c.POINTER(c.c_int64), c.c_int64, c.POINTER(c.c_double),
            c.POINTER(c.c_int64), c.POINTER(c.c_int),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64),
            c.POINTER(c.c_int), c.c_void_p, c.c_int]
        lib._sigs_set = True
    return lib


def text_parser() -> Optional[ctypes.CDLL]:
    lib = load_native("text_parser.cpp")
    if lib is None:
        return None
    if not getattr(lib, "_sigs_set", False):
        c = ctypes
        lib.count_lines.restype = c.c_long
        lib.count_lines.argtypes = [c.c_char_p]
        lib.count_fields.restype = c.c_int
        lib.count_fields.argtypes = [c.c_char_p, c.c_char]
        lib.parse_dense.restype = c.c_long
        lib.parse_dense.argtypes = [
            c.c_char_p, c.c_char, c.c_int,
            c.POINTER(c.c_double), c.c_long, c.c_int]
        lib.parse_libsvm.restype = c.c_long
        lib.parse_libsvm.argtypes = [
            c.c_char_p, c.c_int, c.POINTER(c.c_int), c.POINTER(c.c_int),
            c.POINTER(c.c_double), c.POINTER(c.c_double), c.c_long,
            c.c_long]
        lib._sigs_set = True
    return lib
