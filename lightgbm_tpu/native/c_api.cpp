// Minimal native C ABI for the model/predict surface (the L7 seam).
//
// Reference analog: src/c_api.cpp's ~90 LGBM_* functions (UNVERIFIED —
// empty mount, see SURVEY.md banner). A TPU/JAX training framework has no
// use for a C training ABI (training is a jitted XLA program driven from
// Python), but the PREDICT/model surface is exactly where a stable ABI
// earns its keep: deployment inference from C/C++/Go/Rust services with
// zero Python/JAX runtime. This file is that surface: a standalone
// C++17 parser for the LightGBM v4 model text format plus an
// OpenMP-parallel predictor, exported as ~10 extern "C" functions
// mirroring the reference's naming (BoosterCreateFromModelfile,
// BoosterPredictForMat, GetLastError, ...).
//
// Semantics mirror lightgbm_tpu.tree.Tree._leaf_index_raw /
// io/model_text.py HostModel.predict bit-for-bit:
//   - decision_type bit0 = categorical, bit1 = default_left,
//     bits2-3 = missing type (0 none / 1 zero / 2 nan)
//   - missing "none": NaN behaves as 0.0; "zero": |x|<=1e-35 and NaN
//     take the default direction; "nan": NaN takes the default
//   - categorical: value-level uint32 bitset membership; NaN, negative
//     and out-of-range values miss the set and go right
//   - linear leaves: leaf_const + sum(coef*x) with constant-leaf
//     fallback when any referenced feature is non-finite
//   - average_output divides raw by the iteration count (RF)
//   - objective transforms: binary sigmoid, softmax, ova-normalize,
//     exp (poisson/gamma/tweedie), xentropy sigmoid, regression sqrt

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

thread_local std::string g_last_error;

void SetError(const std::string& msg) { g_last_error = msg; }

struct NativeTree {
  int num_leaves = 1;
  std::vector<int32_t> split_feature, left_child, right_child;
  std::vector<double> threshold;
  std::vector<uint8_t> decision_type;
  std::vector<double> leaf_value;
  // categorical payload (LightGBM layout: threshold[i] indexes
  // cat_boundaries; that range delimits uint32 words in cat_threshold)
  std::vector<int64_t> cat_boundaries;
  std::vector<uint32_t> cat_threshold;
  // linear-leaf payload
  bool is_linear = false;
  std::vector<double> leaf_const;
  std::vector<std::vector<int32_t>> leaf_features;
  std::vector<std::vector<double>> leaf_coeff;

  int LeafIndex(const double* row) const {
    if (num_leaves <= 1) return 0;
    int nd = 0;
    for (;;) {
      const double v = row[split_feature[nd]];
      const uint8_t dt = decision_type[nd];
      bool go_left;
      if (dt & 1) {  // categorical bitset membership
        go_left = false;
        if (std::isfinite(v) && v >= 0) {
          const int64_t iv = static_cast<int64_t>(v);
          const int ci = static_cast<int>(threshold[nd]);
          const int64_t start = cat_boundaries[ci];
          const int64_t nw = cat_boundaries[ci + 1] - start;
          const int64_t w = iv >> 5;
          if (w < nw) {
            go_left = (cat_threshold[start + w] >> (iv & 31)) & 1u;
          }
        }
      } else {
        const bool dl = dt & 2;
        const int mt = (dt >> 2) & 3;
        const bool miss = std::isnan(v);
        if (mt == 2) {            // nan
          go_left = miss ? dl : (v <= threshold[nd]);
        } else if (mt == 1) {     // zero
          const double v0 = miss ? 0.0 : v;
          go_left = (miss || std::fabs(v0) <= 1e-35)
                        ? dl : (v0 <= threshold[nd]);
        } else {                  // none: NaN behaves as 0.0
          go_left = (miss ? 0.0 : v) <= threshold[nd];
        }
      }
      const int nxt = go_left ? left_child[nd] : right_child[nd];
      if (nxt < 0) return -nxt - 1;
      nd = nxt;
    }
  }

  double LeafOutput(int leaf, const double* row) const {
    if (!is_linear) return leaf_value[leaf];
    // text-format linear leaves always carry leaf_const; rows whose
    // referenced features contain a non-finite value fall back to the
    // constant leaf_value (tree.h Tree::Predict nan_found semantics)
    double s = leaf_const[leaf];
    const auto& feats = leaf_features[leaf];
    const auto& coefs = leaf_coeff[leaf];
    for (size_t i = 0; i < feats.size(); ++i) {
      const double v = row[feats[i]];
      if (!std::isfinite(v)) return leaf_value[leaf];
      s += coefs[i] * v;
    }
    return s;
  }
};

struct NativeBooster {
  std::vector<NativeTree> trees;
  int num_class = 1;
  int num_tree_per_iteration = 1;
  int max_feature_idx = 0;
  bool average_output = false;
  std::string objective = "regression";
  std::string model_str;  // retained verbatim for SaveModel

  int NumIterations() const {
    const int k = num_tree_per_iteration > 0 ? num_tree_per_iteration : 1;
    return static_cast<int>(trees.size()) / k;
  }
};

// ---------------------------------------------------------------------
// model text parsing
// ---------------------------------------------------------------------
bool ParseIntArray(const std::string& s, std::vector<int32_t>* out) {
  out->clear();
  const char* p = s.c_str();
  char* end;
  for (;;) {
    while (*p == ' ') ++p;
    if (!*p) break;
    // thresholds for cat splits are written as floats by some writers;
    // accept any numeric token
    const double v = std::strtod(p, &end);
    if (end == p) return false;
    out->push_back(static_cast<int32_t>(v));
    p = end;
  }
  return true;
}

bool ParseDoubleArray(const std::string& s, std::vector<double>* out) {
  out->clear();
  const char* p = s.c_str();
  char* end;
  for (;;) {
    while (*p == ' ') ++p;
    if (!*p) break;
    const double v = std::strtod(p, &end);
    if (end == p) return false;
    out->push_back(v);
    p = end;
  }
  return true;
}

// key=value lines of one tree block into a small map (vector of pairs;
// blocks have ~20 keys so linear scan is fine)
struct KVBlock {
  std::vector<std::pair<std::string, std::string>> kv;
  const std::string* Get(const char* key) const {
    for (const auto& p : kv) {
      if (p.first == key) return &p.second;
    }
    return nullptr;
  }
};

KVBlock SplitKVLines(const std::string& text) {
  KVBlock out;
  std::istringstream ss(text);
  std::string line;
  while (std::getline(ss, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    const size_t eq = line.find('=');
    if (eq == std::string::npos) continue;
    out.kv.emplace_back(line.substr(0, eq), line.substr(eq + 1));
  }
  return out;
}

bool ParseTree(const std::string& block, NativeTree* t,
               int max_feature_idx) {
  const KVBlock kv = SplitKVLines(block);
  const std::string* s = kv.Get("num_leaves");
  if (!s) return false;
  t->num_leaves = std::atoi(s->c_str());
  // range-check BEFORE any sizing: a corrupt count (negative, or
  // larger than the block could possibly serialize — every array
  // entry is >=1 char) must not reach vector::resize, where it would
  // throw length_error/bad_alloc across the extern-C boundary
  if (t->num_leaves < 1 ||
      static_cast<size_t>(t->num_leaves) > block.size()) {
    return false;
  }
  const int nn = t->num_leaves > 1 ? t->num_leaves - 1 : 0;

  // strict parsing: a present array must tokenize cleanly and carry
  // exactly n entries — zero-filling a corrupted field would load a
  // booster that silently predicts garbage
  bool parse_ok = true;
  auto geti = [&](const char* k, int n, std::vector<int32_t>* out) {
    const std::string* v = kv.Get(k);
    if (!v || v->find_first_not_of(' ') == std::string::npos) {
      out->assign(n, 0);
      return;
    }
    if (!ParseIntArray(*v, out) ||
        out->size() != static_cast<size_t>(n)) {
      parse_ok = false;
      out->resize(n, 0);
    }
  };
  auto getf = [&](const char* k, int n, std::vector<double>* out) {
    const std::string* v = kv.Get(k);
    if (!v || v->find_first_not_of(' ') == std::string::npos) {
      out->assign(n, 0.0);
      return;
    }
    if (!ParseDoubleArray(*v, out) ||
        out->size() != static_cast<size_t>(n)) {
      parse_ok = false;
      out->resize(n, 0.0);
    }
  };

  geti("split_feature", nn, &t->split_feature);
  geti("left_child", nn, &t->left_child);
  geti("right_child", nn, &t->right_child);
  getf("threshold", nn, &t->threshold);
  getf("leaf_value", t->num_leaves, &t->leaf_value);
  std::vector<int32_t> dt;
  geti("decision_type", nn, &dt);
  t->decision_type.assign(dt.begin(), dt.end());

  const std::string* nc = kv.Get("num_cat");
  if (nc && std::atoi(nc->c_str()) > 0) {
    const int ncat = std::atoi(nc->c_str());
    if (static_cast<size_t>(ncat) > block.size()) return false;
    std::vector<int32_t> cb;
    geti("cat_boundaries", ncat + 1, &cb);
    t->cat_boundaries.assign(cb.begin(), cb.end());
    const std::string* ct = kv.Get("cat_threshold");
    std::vector<double> ctd;
    if (ct && !ParseDoubleArray(*ct, &ctd)) parse_ok = false;
    t->cat_threshold.clear();
    for (double v : ctd) {
      t->cat_threshold.push_back(static_cast<uint32_t>(v));
    }
  }

  const std::string* lin = kv.Get("is_linear");
  if (lin && std::atoi(lin->c_str()) == 1 && kv.Get("leaf_const")) {
    t->is_linear = true;
    getf("leaf_const", t->num_leaves, &t->leaf_const);
    std::vector<int32_t> counts, feats_flat;
    std::vector<double> coefs_flat;
    geti("num_features", t->num_leaves, &counts);
    const std::string* ff = kv.Get("leaf_features");
    if (ff && !ParseIntArray(*ff, &feats_flat)) parse_ok = false;
    const std::string* cf = kv.Get("leaf_coeff");
    if (cf && !ParseDoubleArray(*cf, &coefs_flat)) parse_ok = false;
    t->leaf_features.resize(t->num_leaves);
    t->leaf_coeff.resize(t->num_leaves);
    size_t off = 0;
    for (int lf = 0; lf < t->num_leaves; ++lf) {
      const size_t c = counts[lf] > 0 ? counts[lf] : 0;
      if (off + c <= feats_flat.size() && off + c <= coefs_flat.size()) {
        t->leaf_features[lf].assign(feats_flat.begin() + off,
                                    feats_flat.begin() + off + c);
        t->leaf_coeff[lf].assign(coefs_flat.begin() + off,
                                 coefs_flat.begin() + off + c);
      }
      off += c;
    }
  }

  // structural bounds check so a malformed file errors instead of UB:
  // children in range, split features within the header's feature
  // count, categorical indices inside cat_boundaries and every
  // boundary range inside cat_threshold
  for (size_t i = 0; i + 1 < t->cat_boundaries.size(); ++i) {
    const int64_t lo = t->cat_boundaries[i];
    const int64_t hi = t->cat_boundaries[i + 1];
    if (lo < 0 || hi < lo ||
        hi > static_cast<int64_t>(t->cat_threshold.size())) {
      return false;
    }
  }
  if (!parse_ok) return false;
  for (int i = 0; i < nn; ++i) {
    const int lc = t->left_child[i], rc = t->right_child[i];
    // internal children must point FORWARD (creation order) — this is
    // what makes traversal provably acyclic/terminating
    if (lc >= nn || rc >= nn || (lc >= 0 && lc <= i) ||
        (rc >= 0 && rc <= i) || -lc - 1 >= t->num_leaves ||
        -rc - 1 >= t->num_leaves || t->split_feature[i] < 0 ||
        t->split_feature[i] > max_feature_idx) {
      return false;
    }
    if (t->decision_type[i] & 1) {
      // compare in floating point BEFORE casting: double->size_t on a
      // value outside size_t's range is undefined behavior, so a
      // corrupt threshold like 1e300 must be rejected pre-cast
      const double ci = t->threshold[i];
      if (!(ci >= 0) || t->cat_boundaries.empty() ||
          ci + 1 >= static_cast<double>(t->cat_boundaries.size())) {
        return false;
      }
    }
  }
  return true;
}

NativeBooster* ParseModel(const std::string& text) {
  if (text.compare(0, 4, "tree") != 0) {
    SetError("Model string doesn't start with the 'tree' magic");
    return nullptr;
  }
  auto booster = new NativeBooster();
  booster->model_str = text;

  const size_t first_tree = text.find("\nTree=");
  const std::string head =
      text.substr(0, first_tree == std::string::npos ? text.size()
                                                     : first_tree);
  const KVBlock hkv = SplitKVLines(head);
  if (const std::string* v = hkv.Get("num_class"))
    booster->num_class = std::atoi(v->c_str());
  if (const std::string* v = hkv.Get("num_tree_per_iteration"))
    booster->num_tree_per_iteration = std::atoi(v->c_str());
  if (const std::string* v = hkv.Get("max_feature_idx"))
    booster->max_feature_idx = std::atoi(v->c_str());
  if (const std::string* v = hkv.Get("objective"))
    booster->objective = *v;
  // header sanity: corrupt counts must error here, not size buffers or
  // index arrays later (reference hardens with CHECK macros; SURVEY
  // §2.1 utils row, UNVERIFIED)
  if (booster->num_class < 1 || booster->num_class > (1 << 20) ||
      booster->num_tree_per_iteration < 1 ||
      booster->num_tree_per_iteration > (1 << 20) ||
      booster->max_feature_idx < 0 ||
      booster->max_feature_idx >= (1 << 28)) {
    SetError("Malformed model header counts");
    delete booster;
    return nullptr;
  }
  booster->average_output =
      head.find("\naverage_output") != std::string::npos;

  size_t pos = first_tree;
  while (pos != std::string::npos) {
    pos += 1;  // skip '\n'
    size_t end = text.find("\nTree=", pos);
    size_t stop = text.find("\nend of trees", pos);
    size_t block_end = std::min(
        end == std::string::npos ? text.size() : end,
        stop == std::string::npos ? text.size() : stop);
    NativeTree t;
    if (!ParseTree(text.substr(pos, block_end - pos), &t,
                   booster->max_feature_idx)) {
      SetError("Malformed tree block in model string");
      delete booster;
      return nullptr;
    }
    booster->trees.push_back(std::move(t));
    pos = (end != std::string::npos && (stop == std::string::npos ||
                                        end < stop))
              ? end : std::string::npos;
  }
  return booster;
}

// ---------------------------------------------------------------------
// prediction
// ---------------------------------------------------------------------
enum PredictType { kNormal = 0, kRaw = 1, kLeafIndex = 2 };

// first token of the objective string + a named numeric suffix
std::string ObjHead(const std::string& obj) {
  const size_t sp = obj.find(' ');
  return sp == std::string::npos ? obj : obj.substr(0, sp);
}

double ObjParam(const std::string& obj, const char* name, double dflt) {
  const std::string key = std::string(name) + ":";
  const size_t p = obj.find(key);
  if (p == std::string::npos) return dflt;
  return std::atof(obj.c_str() + p + key.size());
}

void Transform(const NativeBooster& b, double* raw, int k) {
  const std::string head = ObjHead(b.objective);
  if (head == "binary") {
    const double s = ObjParam(b.objective, "sigmoid", 1.0);
    raw[0] = 1.0 / (1.0 + std::exp(-s * raw[0]));
  } else if (head == "multiclass" || head == "softmax") {
    double mx = raw[0];
    for (int i = 1; i < k; ++i) mx = std::max(mx, raw[i]);
    double sum = 0.0;
    for (int i = 0; i < k; ++i) { raw[i] = std::exp(raw[i] - mx);
                                  sum += raw[i]; }
    for (int i = 0; i < k; ++i) raw[i] /= sum;
  } else if (head == "multiclassova") {
    const double s = ObjParam(b.objective, "sigmoid", 1.0);
    double sum = 0.0;
    for (int i = 0; i < k; ++i) {
      raw[i] = 1.0 / (1.0 + std::exp(-s * raw[i]));
      sum += raw[i];
    }
    for (int i = 0; i < k; ++i) raw[i] /= sum;
  } else if (head == "poisson" || head == "gamma" || head == "tweedie") {
    raw[0] = std::exp(raw[0]);
  } else if (head == "cross_entropy" || head == "xentropy") {
    raw[0] = 1.0 / (1.0 + std::exp(-raw[0]));
  } else if (head == "regression" &&
             b.objective.find(" sqrt") != std::string::npos) {
    raw[0] = (raw[0] >= 0 ? 1.0 : -1.0) * raw[0] * raw[0];
  }
}

}  // namespace

extern "C" {

const char* LGBMTPU_GetLastError() { return g_last_error.c_str(); }

int LGBMTPU_BoosterLoadModelFromString(const char* model_str,
                                       int* out_num_iterations,
                                       void** out_handle) {
  if (!model_str || !out_handle) {
    SetError("null argument");
    return -1;
  }
  // last-resort exception fence: no C++ exception (bad_alloc,
  // length_error from a corrupt count that slipped past validation)
  // may cross the C ABI — that is std::terminate in the caller
  NativeBooster* b = nullptr;
  try {
    b = ParseModel(model_str);
  } catch (const std::exception& e) {
    SetError(std::string("Malformed model string (") + e.what() + ")");
    return -1;
  }
  if (!b) return -1;
  if (out_num_iterations) *out_num_iterations = b->NumIterations();
  *out_handle = b;
  return 0;
}

int LGBMTPU_BoosterCreateFromModelfile(const char* filename,
                                       int* out_num_iterations,
                                       void** out_handle) {
  if (!filename || !out_handle) {
    SetError("null argument");
    return -1;
  }
  std::ifstream f(filename, std::ios::binary);
  if (!f) {
    SetError(std::string("Could not open model file: ") + filename);
    return -1;
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  const std::string text = ss.str();
  return LGBMTPU_BoosterLoadModelFromString(text.c_str(),
                                            out_num_iterations,
                                            out_handle);
}

int LGBMTPU_BoosterFree(void* handle) {
  delete static_cast<NativeBooster*>(handle);
  return 0;
}

int LGBMTPU_BoosterGetNumClasses(void* handle, int* out) {
  if (!handle || !out) { SetError("null argument"); return -1; }
  *out = static_cast<NativeBooster*>(handle)->num_class;
  return 0;
}

int LGBMTPU_BoosterGetNumFeature(void* handle, int* out) {
  if (!handle || !out) { SetError("null argument"); return -1; }
  *out = static_cast<NativeBooster*>(handle)->max_feature_idx + 1;
  return 0;
}

int LGBMTPU_BoosterGetCurrentIteration(void* handle, int* out) {
  if (!handle || !out) { SetError("null argument"); return -1; }
  *out = static_cast<NativeBooster*>(handle)->NumIterations();
  return 0;
}

int LGBMTPU_BoosterGetNumTreePerIteration(void* handle, int* out) {
  if (!handle || !out) { SetError("null argument"); return -1; }
  *out = static_cast<NativeBooster*>(handle)->num_tree_per_iteration;
  return 0;
}

int LGBMTPU_BoosterSaveModel(void* handle, const char* filename) {
  if (!handle || !filename) { SetError("null argument"); return -1; }
  const NativeBooster* b = static_cast<NativeBooster*>(handle);
  std::ofstream f(filename, std::ios::binary);
  if (!f) {
    SetError(std::string("Could not open for write: ") + filename);
    return -1;
  }
  f << b->model_str;
  return f.good() ? 0 : -1;
}

int LGBMTPU_BoosterGetModelSize(void* handle, int64_t* out) {
  if (!handle || !out) { SetError("null argument"); return -1; }
  *out = static_cast<int64_t>(
      static_cast<NativeBooster*>(handle)->model_str.size());
  return 0;
}

int LGBMTPU_BoosterGetModelString(void* handle, int64_t buffer_len,
                                  char* out) {
  if (!handle || !out) { SetError("null argument"); return -1; }
  const NativeBooster* b = static_cast<NativeBooster*>(handle);
  if (buffer_len < static_cast<int64_t>(b->model_str.size()) + 1) {
    SetError("buffer too small");
    return -1;
  }
  std::memcpy(out, b->model_str.c_str(), b->model_str.size() + 1);
  return 0;
}

// data: [nrow, ncol] double, row-major (is_row_major=1) or col-major.
// predict_type: 0 normal, 1 raw score, 2 leaf index.
// out_result sizes: normal/raw -> nrow * num_class (binary/regression:
// nrow); leaf -> nrow * num_used_trees. out_len receives the count.
int LGBMTPU_BoosterPredictForMat(void* handle, const double* data,
                                 int32_t nrow, int32_t ncol,
                                 int is_row_major, int predict_type,
                                 int start_iteration, int num_iteration,
                                 double* out_result, int64_t* out_len) {
  if (!handle || !data || !out_result) {
    SetError("null argument");
    return -1;
  }
  const NativeBooster& b = *static_cast<NativeBooster*>(handle);
  if (ncol < b.max_feature_idx + 1) {
    SetError("Input matrix has " + std::to_string(ncol) +
             " columns but the model needs " +
             std::to_string(b.max_feature_idx + 1));
    return -1;
  }
  const int k = b.num_tree_per_iteration > 0 ? b.num_tree_per_iteration
                                             : 1;
  const int total_iters = b.NumIterations();
  if (start_iteration < 0) start_iteration = 0;
  int iters = num_iteration <= 0 ? total_iters - start_iteration
                                 : num_iteration;
  if (iters > total_iters - start_iteration)
    iters = total_iters - start_iteration;
  if (iters < 0) iters = 0;
  const int t0 = start_iteration * k;
  const int nt = iters * k;
  const int out_per_row = predict_type == kLeafIndex ? nt : k;

#ifdef _OPENMP
#pragma omp parallel
#endif
  {
  // col-major inputs are strided-gathered into one per-thread buffer
  std::vector<double> rowbuf(is_row_major ? 0 : ncol);
#ifdef _OPENMP
#pragma omp for schedule(static)
#endif
  for (int32_t r = 0; r < nrow; ++r) {
    const double* row;
    if (is_row_major) {
      row = data + static_cast<int64_t>(r) * ncol;
    } else {
      for (int32_t c = 0; c < ncol; ++c) {
        rowbuf[c] = data[static_cast<int64_t>(c) * nrow + r];
      }
      row = rowbuf.data();
    }
    double* out = out_result + static_cast<int64_t>(r) * out_per_row;
    if (predict_type == kLeafIndex) {
      for (int i = 0; i < nt; ++i) {
        out[i] = b.trees[t0 + i].LeafIndex(row);
      }
      continue;
    }
    for (int i = 0; i < k; ++i) out[i] = 0.0;
    for (int i = 0; i < nt; ++i) {
      const NativeTree& t = b.trees[t0 + i];
      out[(t0 + i) % k] += t.LeafOutput(t.LeafIndex(row), row);
    }
    if (b.average_output && nt > 0) {
      for (int i = 0; i < k; ++i) out[i] /= (nt / k);
    }
    if (predict_type == kNormal) {
      Transform(b, out, k);
    }
  }
  }  // omp parallel
  if (out_len) *out_len = static_cast<int64_t>(nrow) * out_per_row;
  return 0;
}

}  // extern "C"
