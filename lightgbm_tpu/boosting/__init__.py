"""Boosting engines: GBDT (base), DART, RF.

Reference: the Boosting factory (src/boosting/boosting.cpp
Boosting::CreateBoosting, UNVERIFIED — empty mount, see SURVEY.md banner)
dispatches on the ``boosting`` param; ``goss`` resolves to GBDT +
data_sample_strategy=goss at config-fixup time (config.py).
"""
from .gbdt import GBDT

__all__ = ["GBDT", "create_boosting"]


def create_boosting(config, train_set, fobj=None, mesh=None,
                    init_forest=None) -> GBDT:
    if config.boosting == "dart":
        from .dart import DART
        return DART(config, train_set, fobj=fobj, mesh=mesh,
                    init_forest=init_forest)
    if config.boosting == "rf":
        from .rf import RandomForest
        return RandomForest(config, train_set, fobj=fobj, mesh=mesh,
                            init_forest=init_forest)
    return GBDT(config, train_set, fobj=fobj, mesh=mesh,
                init_forest=init_forest)
