"""Boosting engines: GBDT (base), DART, RF, streaming (out-of-core).

Reference: the Boosting factory (src/boosting/boosting.cpp
Boosting::CreateBoosting, UNVERIFIED — empty mount, see SURVEY.md banner)
dispatches on the ``boosting`` param; ``goss`` resolves to GBDT +
data_sample_strategy=goss at config-fixup time (config.py). The
streaming dispatch has no reference analog — upstream's CPU engine is
always "out of core" relative to an accelerator; here it is the path
that keeps >HBM datasets trainable (VERDICT r4 item 3).
"""
from .gbdt import GBDT

__all__ = ["GBDT", "create_boosting"]


def _streaming_compatible(config) -> bool:
    """Configs StreamingGBDT.__init__ would accept — BOTH sides now
    read lightgbm_tpu/capabilities.py, so the iff the drift-guard
    sweep in tests/test_streaming_sharded.py pins holds by
    construction (auto mode must NEVER route a config into a
    log.fatal that the resident engine would have trained).

    Bagging, GOSS, quantized gradients and ``tree_learner=data`` (the
    sharded streamed path) are streaming-supported; voting/feature
    learners and the structured-constraint features are not — see the
    "streaming" column of ``capabilities.CAPABILITIES``."""
    from .. import capabilities
    return capabilities.supports("streaming", config)


def _should_stream(config, train_set, fobj) -> bool:
    mode = str(getattr(config, "tpu_streaming", "auto"))
    if mode == "false":
        return False
    if mode == "true":
        return True
    # auto: stream when the binned matrix (plus the Pallas path's
    # feature-major int8 copy) would exceed ~60% of device HBM — the
    # resident engine's own guard fatals at 92%, so auto-streaming
    # kicks in with margin to spare for histograms/score/partition.
    # Only for configs streaming supports (anything else keeps the
    # resident engine and its own guard/sharding, e.g. a mesh run
    # whose per-device shard fits); dataset-level gates (categorical
    # bins) are re-checked by StreamingGBDT itself.
    if fobj is not None or not _streaming_compatible(config):
        return False
    from ..utils.hbm import (STREAM_HBM_FRACTION, binned_device_bytes,
                             hbm_bytes_limit)
    try:
        import jax
        n_dev = jax.device_count()
        local_dev = jax.local_device_count()
    except Exception:
        return False
    shards = 1
    if n_dev > 1:
        # a mesh config: only the data-parallel learner has a streamed
        # sharded path (each rank streams its own row shard's blocks;
        # one packed psum per level). Other learners keep the resident
        # engine and its own per-device sharding/guard.
        if config.tree_learner != "data":
            return False
        tms = str(getattr(config, "tpu_mesh_shape", "")).strip()
        shards = max(1, min(local_dev, int(tms) if tms else local_dev))
    limit = hbm_bytes_limit()
    if not limit:
        return False
    ds = train_set
    n = getattr(ds, "num_data", None)
    f = None
    if getattr(ds, "_constructed", False):
        f = len(ds.used_features)
    elif hasattr(ds.data, "shape") and len(getattr(ds.data, "shape", ())) == 2:
        f = int(ds.data.shape[1])
        n = int(ds.data.shape[0])
    if not n or not f:
        return False
    itemsize = 2 if int(config.max_bin) > 255 else 1
    est = binned_device_bytes(n, f, itemsize)   # bins + bins_t (Pallas)
    # this process's data spreads over its local mesh devices: stream
    # only when the PER-RANK shard would still blow the HBM budget —
    # the beyond-HBM x beyond-host composition (ROADMAP item 1)
    if est / shards <= STREAM_HBM_FRACTION * limit:
        return False
    # dataset-level gate: pandas-category / auto-detected categorical
    # bins would make StreamingGBDT fatal — keep those resident
    ds.construct()
    return not any(ds.bin_mappers[fi].bin_type == "categorical"
                   for fi in ds.used_features)


def create_boosting(config, train_set, fobj=None, mesh=None,
                    init_forest=None) -> GBDT:
    # forced streaming x a non-gbdt boosting mode would dispatch AWAY
    # from the streaming engine below — fatal early with clear wording
    # (boosting is normalized to {gbdt, dart, rf} by Config; the
    # table's dart/rf rows mark the same configs streaming-fatal)
    if (str(getattr(config, "tpu_streaming", "auto")) == "true"
            and config.boosting != "gbdt"):
        from ..utils import log
        log.fatal(f"tpu_streaming=true supports boosting=gbdt only "
                  f"(got {config.boosting}); DART/RF need the resident "
                  f"engine")
    if config.boosting == "dart":
        from .dart import DART
        return DART(config, train_set, fobj=fobj, mesh=mesh,
                    init_forest=init_forest)
    if config.boosting == "rf":
        from .rf import RandomForest
        return RandomForest(config, train_set, fobj=fobj, mesh=mesh,
                            init_forest=init_forest)
    if _should_stream(config, train_set, fobj):
        from .streaming import StreamingGBDT
        return StreamingGBDT(config, train_set, fobj=fobj, mesh=mesh,
                             init_forest=init_forest)
    return GBDT(config, train_set, fobj=fobj, mesh=mesh,
                init_forest=init_forest)
