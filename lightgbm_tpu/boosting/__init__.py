"""Subpackage: boosting."""
