"""Random-forest mode (``boosting=rf``).

Reference semantics: ``RF`` (src/boosting/rf.hpp, UNVERIFIED — empty
mount, see SURVEY.md banner): trees are trained *independently* — the
gradients are always evaluated at the constant init score, never at the
boosted ensemble score — each on its own bagging subset (bagging is
mandatory), stored UNSHRUNK with the per-class init score folded into
every tree's leaves, and the ensemble output is the AVERAGE of tree
outputs (``average_output`` in the model text).

TPU-first: reuses the jitted GBDT step verbatim — only the score fed to
the gradient computation (the constant init tile) and the host-side
averaging bookkeeping differ. The displayed train/valid scores are
maintained incrementally as ``base + pred_sum / n_iter`` so metrics see
the averaged forest at every iteration.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..ops.predict import forest_predict_binned
from ..utils import log
from .gbdt import GBDT


class RandomForest(GBDT):
    """RF engine (reference: src/boosting/rf.hpp RF : public GBDT)."""

    # no carry donation (tpu_donate): every iteration re-feeds the
    # persistent _score0 base tile into the step and reads it back to
    # isolate the new tree's raw output — donation would delete the
    # shared base buffer on the first dispatch (docs/perf.md
    # "Iteration floor")
    _donate_carries = False

    def __init__(self, config, train_set, fobj=None, mesh=None,
                 init_forest=None):
        # eligibility from the capability table's "rf" column (the
        # same rows the drift-guard sweep in tests/test_analysis.py
        # constructs against); messages keep the reference wording
        from .. import capabilities
        for name, cap, v in capabilities.engine_verdicts("rf", config):
            if v == capabilities.FATAL:
                log.fatal(cap.messages.get("rf",
                                           f"rf does not support "
                                           f"{cap.describe}"))
            else:
                # a DEMOTE row added to the table without a demotion
                # action here would be a silent no-op (same guard as
                # StreamingGBDT's walk)
                log.fatal(f"capability table DEMOTEs {name!r} for the "
                          f"rf engine but RandomForest has no demotion "
                          f"action for it — add one here")
        super().__init__(config, train_set, fobj=fobj, mesh=mesh,
                         init_forest=init_forest)
        self.average_output = True
        # constant gradient point: init score tile (+ dataset init_score).
        # Under continuation init_scores are zero (the bias lives in the
        # loaded trees), and self.score currently holds score0 + forest
        # sum — recover both pieces.
        self._score0 = self._init_score_tile(self.data)
        self._s0 = jnp.asarray(self.init_scores.astype(np.float32))[None, :]
        self._base = self._score0 - self._s0   # dataset init_score offset
        self._pred_sum = self.score - self._score0  # sum of biased preds
        if self.iter_:
            self.score = self._base + self._pred_sum / self.iter_
        else:
            self.score = self._score0
        self._valid_base: List[jnp.ndarray] = []
        self._valid_pred_sum: List[jnp.ndarray] = []

    def _learning_rate(self) -> float:
        return 1.0  # rf.hpp: no shrinkage, trees stored raw

    def can_fuse_iters(self) -> bool:
        return False  # bagging re-draw + averaging are host-orchestrated

    # ------------------------------------------------------------------
    def add_valid(self, ds, name: str) -> None:
        super().add_valid(ds, name)
        vi = len(self.valid_data) - 1
        dd = self.valid_data[vi]
        full = self.valid_scores[vi]   # v0 + sum of (biased) stored trees
        v0 = self._init_score_tile(dd)
        base = v0 - self._s0
        pred_sum = full - v0
        self._valid_base.append(base)
        self._valid_pred_sum.append(pred_sum)
        n = max(self.iter_, 1)
        self.valid_scores[vi] = (base + pred_sum / n if self.iter_
                                 else v0)

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> None:
        K = self.num_class
        saved_valid = self.valid_scores
        self.valid_scores = []          # skip the base valid-score update
        self.score = self._score0       # gradients at the constant init
        super().train_one_iter(grad, hess)
        pred = self.score - self._score0   # this iteration's raw outputs
        self.valid_scores = saved_valid
        n = self.iter_

        # fold the init score into the stored trees (rf.hpp AddBias) so
        # the averaged model output carries the bias
        for c in range(K):
            t = self.models[-K + c]
            bias = float(self.init_scores[c])
            t.leaf_value = t.leaf_value + bias
            t.internal_value = t.internal_value + bias
        # the bias fold mutated the just-appended trees: drop any stack
        # cached between the append and here
        self._invalidate_forest_cache()

        self._pred_sum = self._pred_sum + pred + self._s0
        self.score = self._base + self._pred_sum / n

        if self.valid_data:
            stacked, class_idx = self._stack_model_list(
                list(range(len(self.models) - K, len(self.models))))
            for vi, dd in enumerate(self.valid_data):
                raw, _ = forest_predict_binned(
                    stacked, dd.bins, self.feat_num_bin,
                    self.feat_has_nan, class_idx, K)
                self._valid_pred_sum[vi] = self._valid_pred_sum[vi] + raw
                self.valid_scores[vi] = (self._valid_base[vi]
                                         + self._valid_pred_sum[vi] / n)

    # ------------------------------------------------------------------
    def export_train_state(self):
        st = super().export_train_state()
        st["rf"] = {
            "pred_sum": self._rows_to_host(self._pred_sum),
            "valid_pred_sum": [self._rows_to_host(s)
                               for s in self._valid_pred_sum],
        }
        return st

    def import_train_state(self, state) -> bool:
        restored = super().import_train_state(state)
        rf = state.get("rf")
        if restored and rf is not None and rf["pred_sum"] is not None:
            # the averaged display score was restored by the base; the
            # running biased-prediction sums are RF's true accumulators
            self._pred_sum = self.data._place(rf["pred_sum"],
                                              extra_dims=2)
            for i, vs in enumerate(rf.get("valid_pred_sum") or []):
                if i < len(self.valid_data) and vs is not None:
                    self._valid_pred_sum[i] = self.valid_data[i]._place(
                        vs, extra_dims=2)
        return restored

    # ------------------------------------------------------------------
    def _recompute_scores(self) -> None:
        super()._recompute_scores()
        n = self.iter_
        if n == 0:
            self._pred_sum = jnp.zeros_like(self.score)
            self.score = self._score0
            for vi in range(len(self.valid_scores)):
                self._valid_pred_sum[vi] = jnp.zeros_like(
                    self.valid_scores[vi])
                self.valid_scores[vi] = self._valid_base[vi] + self._s0
            return
        self._pred_sum = self.score - self._score0
        self.score = self._base + self._pred_sum / n
        for vi in range(len(self.valid_scores)):
            v0 = self._valid_base[vi] + self._s0
            self._valid_pred_sum[vi] = self.valid_scores[vi] - v0
            self.valid_scores[vi] = (self._valid_base[vi]
                                     + self._valid_pred_sum[vi] / n)
