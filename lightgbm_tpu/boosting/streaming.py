"""Out-of-core (larger-than-HBM) boosting: host-resident bins, streamed
level sweeps.

Closes the last scale-axis gap vs the reference (VERDICT r4 item 3):
upstream LightGBM trains any dataset that fits host RAM/disk — its
two-round loader + row-wise bin storage never require the binned matrix
on the accelerator (``src/io/dataset_loader.cpp``, SURVEY.md §2.1,
UNVERIFIED — empty mount). The resident engine here (`gbdt.GBDT`)
uploads the full binned matrix to HBM, capping trainable size at
~HBM/(F bytes-per-row). This module removes that cap for the configs
that need it.

Design (SURVEY.md §7.4 hard-part 4, "sharded binning on host, streamed
epochs"):

- The BINNED matrix (uint8/16, the big object) stays in host RAM; the
  native binner builds it at ~GB/s. Device-resident state is one row
  BLOCK at a time plus the accumulated `[K, F, B, 3]` histograms
  (~11 MB at K=128/F=28/B=256) — HBM use is O(block), not O(n).
- Trees grow LEVEL-WISE: one streamed pass over all blocks per level
  computes the histograms of every frontier leaf at once (the same
  multi-leaf one-hot-matmul histogram the resident engine uses), so a
  depth-d tree costs d+1 sweeps of PCIe traffic instead of the
  resident engine's zero. Best-first order inside a level is
  preserved by gain-ranking when the leaf budget runs out, but
  cross-level best-first interleaving is NOT — a documented
  divergence from the reference's queue (`serial_tree_learner.cpp`):
  per-sweep cost makes strict best-first (one sweep per leaf)
  ~num_leaves/depth times more expensive.
- Per-row state (score, leaf id) also lives on host and rides along
  each sweep; gradients are recomputed on device per block from the
  streamed score (cheaper than streaming g/h separately).

Supported configs (v1, all checked at construction): single-output
objectives (binary, regression family, xentropy) on numerical
features, serial learner, no row sampling. Everything else —
multiclass, ranking, categorical splits, GOSS/bagging, DART/RF,
linear trees, monotone/CEGB/interaction constraints, EFB, forced
splits, continuation — stays on the resident engine; `create_boosting`
only routes here when the data cannot fit (or ``tpu_streaming=true``
forces it). Split-rule parity (L1/L2, min_data, min_hessian,
min_gain, max_delta_step, path smoothing, extra-trees, missing
directions) comes for free: the same `find_best_split` evaluates the
accumulated histograms.
"""
from __future__ import annotations

import math
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Dataset
from ..metric import metrics_for_config
from ..objective import create_objective
from ..ops.pallas_histogram import multi_leaf_histogram_xla
from ..ops.split import SplitConfig, find_best_split
from ..tree import Tree
from ..utils import log


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _apply_table(bins_blk, leaf_blk, tbl):
    """Route rows through one level's split table (tbl arrays are [S]).
    Left child KEEPS the parent's leaf id; rows routed right get the
    new leaf id. NaN rows (last bin when has_nan) follow default_left —
    same semantics as the resident partition (learner/serial.py
    apply_splits). ``leaf_blk`` is int16 (device-resident per-row
    state: 2 bytes/row matters at 1e9 rows)."""
    lid = leaf_blk.astype(jnp.int32)
    mk = lid[:, None] == tbl["leaf"][None, :]            # [R, S]
    sel = jnp.any(mk, axis=1)

    def pick(a):
        return jnp.sum(jnp.where(mk, a[None, :].astype(jnp.int32), 0),
                       axis=1)

    feat_r = pick(tbl["feat"])
    thr_r = pick(tbl["thr"])
    dl_r = pick(tbl["dl"]) > 0
    new_r = pick(tbl["new_leaf"])
    nb_r = pick(tbl["nb"])
    hn_r = pick(tbl["hn"]) > 0
    col = jnp.take_along_axis(
        bins_blk.astype(jnp.int32),
        jnp.clip(feat_r, 0, bins_blk.shape[1] - 1)[:, None],
        axis=1)[:, 0]
    is_missing = hn_r & (col == nb_r - 1)
    goes_left = jnp.where(is_missing, dl_r, col <= thr_r)
    return jnp.where(sel & ~goes_left, new_r, lid).astype(jnp.int16)


def _make_sweep(objective, num_bins: int, rows_per_block: int):
    """Build the jitted per-block level sweep. Only ``bins_blk``
    streams from host; score/label/weight/leaf are device-resident
    block slots and the valid-row count rides as one scalar."""

    @jax.jit
    def sweep(bins_blk, score_blk, label_blk, weight_blk, n_valid,
              leaf_blk, tbl, frontier):
        leaf_new = _apply_table(bins_blk, leaf_blk, tbl)
        cnt = (jnp.arange(leaf_blk.shape[0], dtype=jnp.int32)
               < n_valid).astype(jnp.float32)
        g, h = objective.get_gradients(score_blk, label_blk, weight_blk)
        g = g.reshape(-1).astype(jnp.float32)
        h = h.reshape(-1).astype(jnp.float32)
        vals = jnp.stack([g * cnt, h * cnt, cnt], axis=1)
        hist = multi_leaf_histogram_xla(
            bins_blk, vals, leaf_new.astype(jnp.int32), frontier,
            num_bins=num_bins, rows_per_block=rows_per_block)
        return leaf_new, hist

    return sweep


def _make_final(objective, lr: float):
    """Jitted final sweep: apply the last split table and add leaf
    outputs to the device-resident score."""

    @jax.jit
    def final(bins_blk, score_blk, leaf_blk, tbl, leaf_out):
        leaf_new = _apply_table(bins_blk, leaf_blk, tbl)
        score_new = score_blk + lr * leaf_out[
            jnp.clip(leaf_new.astype(jnp.int32), 0,
                     leaf_out.shape[0] - 1)]
        return leaf_new, score_new

    return final


class StreamingGBDT:
    """Boosting engine for datasets whose binned matrix exceeds HBM.

    Quacks like `gbdt.GBDT` for the surfaces the Booster/engine.train
    loop and the model writer touch; everything per-row lives on host.
    """

    _UNSUPPORTED_MSG = (
        "tpu_streaming (out-of-core) supports single-output objectives "
        "on numerical features with tree_learner=serial and no row "
        "sampling; {what} requires the resident engine — reduce the "
        "dataset, raise the device budget, or drop the option")

    def __init__(self, config: Config, train_set: Dataset,
                 fobj=None, mesh=None, init_forest=None):
        self.config = config
        self.train_set = train_set.construct()
        ds = self.train_set

        def _no(cond, what):
            if cond:
                log.fatal(self._UNSUPPORTED_MSG.format(what=what))

        _no(fobj is not None, "a custom objective function")
        _no(init_forest is not None, "training continuation/init_model")
        _no(mesh is not None or config.tree_learner != "serial",
            f"tree_learner={config.tree_learner}")
        _no(config.num_tree_per_iteration > 1, "multiclass")
        _no(config.boosting in ("dart", "rf"), f"boosting={config.boosting}")
        _no(str(config.data_sample_strategy) == "goss", "GOSS")
        _no(config.bagging_fraction < 1.0 or config.bagging_freq > 0,
            "bagging")
        _no(bool(config.linear_tree), "linear_tree")
        _no(bool(config.monotone_constraints), "monotone constraints")
        _no(bool(config.interaction_constraints),
            "interaction constraints")
        _no(config.cegb_tradeoff != 1.0 or config.cegb_penalty_split > 0
            or bool(config.cegb_penalty_feature_coupled)
            or bool(config.cegb_penalty_feature_lazy), "CEGB")
        _no(bool(config.forcedsplits_filename), "forced splits")
        if getattr(config, "_quantize_auto", False):
            # auto-quantize (tpu_auto_quantize) targets the resident
            # int8 histogram kernels; out-of-core sweeps are PCIe-bound
            # so discretization buys nothing — quietly demote
            config.use_quantized_grad = False
        _no(bool(config.use_quantized_grad),
            "use_quantized_grad (stream blocks are already int8; "
            "gradient discretization adds nothing out-of-core)")
        is_cat = [ds.bin_mappers[f].bin_type == "categorical"
                  for f in ds.used_features]
        _no(any(is_cat), "categorical features")
        self.objective = create_objective(config)
        _no(getattr(self.objective, "is_ranking", False),
            "ranking objectives")

        self.num_class = 1
        self.average_output = False
        self.models: List[Tree] = []
        self.iter_ = 0
        self.valid_data: list = []
        self.valid_names: list = []
        self._valid_raw_cache: Dict[int, tuple] = {}
        self.fobj = None
        self.metrics = metrics_for_config(config)

        self.binned = ds.binned                     # host [n, F] uint
        if ds.device_ingested() is not None:
            # the streaming engine scans host blocks only — release a
            # device-resident ingest copy (possible when a standalone
            # construct picked device ingest before a forced
            # tpu_streaming run) instead of leaving it orphaned in HBM
            ds._ingest = None
        self.n = int(ds.num_data)
        F = len(ds.used_features)
        self.num_features = F
        num_bin = ds.feature_num_bins()
        self.max_num_bin = int(num_bin.max()) if F else 2
        self.B = max(8, _ceil_to(self.max_num_bin, 8))
        has_nan = np.array(
            [ds.bin_mappers[f].missing_type == "nan"
             for f in ds.used_features], dtype=bool)
        self.feat_num_bin = jnp.asarray(num_bin.astype(np.int32))
        self.feat_has_nan = jnp.asarray(has_nan)
        self._num_bin_np = num_bin.astype(np.int32)
        self._has_nan_np = has_nan

        # block size: bins block ~256 MB by default (PCIe-friendly,
        # far under any HBM), rounded to a lane multiple
        blk = int(config.tpu_stream_block_rows)
        if blk <= 0:
            blk = max(1 << 16, (256 << 20) // max(F, 1))
        blk = min(blk, max(self.n, 8))
        # the hist kernel's internal row chunk must divide the block;
        # blocks >= 16 Ki rows round up to a 16 Ki multiple (the last
        # block pads), smaller ones use the block itself as the chunk
        self.block_rows = (_ceil_to(blk, 1 << 14) if blk >= (1 << 14)
                           else _ceil_to(blk, 8))
        self.n_blocks = max(1, math.ceil(self.n / self.block_rows))

        if int(config.num_leaves) > 32767:
            log.fatal("tpu_streaming caps num_leaves at 32767 (int16 "
                      "row state)")
        md = ds.metadata
        self.label = np.asarray(md.label, np.float32)
        self.weight = (None if md.weight is None
                       else np.asarray(md.weight, np.float32))
        self.init_scores = np.zeros(1, dtype=np.float64)
        if md.label is not None:
            self.init_scores[0] = self.objective.init_score(
                md.label, md.weight)

        self._scfg = SplitConfig(
            lambda_l1=config.lambda_l1,
            lambda_l2=config.lambda_l2,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            path_smooth=config.path_smooth,
            extra_trees=config.extra_trees,
        )
        self.lr = float(config.learning_rate)
        self._hist_rows_per_block = min(self.block_rows, 1 << 14)
        self._sweep = _make_sweep(self.objective, self.B,
                                  self._hist_rows_per_block)
        self._final = _make_final(self.objective, self.lr)
        self._find = self._make_find()
        self._rng = np.random.default_rng(int(config.seed) & 0x7FFFFFFF)
        self._ff = float(config.feature_fraction)

        # device-resident per-row state, one slot per block: score f32,
        # leaf int16, label f32, weight f32 (if any) — ~10 bytes/row
        # total, so state for a 32 GiB (1.1e9-row) bin matrix fits v5e
        # HBM while the 28x-larger bins stream. Through the tunneled
        # chip this is also the latency fix: per sweep the ONLY host
        # traffic is the bins block up and one packed [K,13] pull down
        # (the D2H path measures ~60 MB/s here — round-tripping leaf
        # ids per sweep was the first version's wall).
        init = np.float32(self.init_scores[0])
        self._score_dev = []
        self._leaf_dev = []
        self._label_dev = []
        self._weight_dev = []
        zeros_leaf = jnp.zeros(self.block_rows, jnp.int16)
        ones_w = (jnp.ones(self.block_rows, jnp.float32)
                  if self.weight is None else None)  # shared constant
        for b, lo, hi in self._blocks():
            self._score_dev.append(
                jnp.full(self.block_rows, init, jnp.float32))
            self._leaf_dev.append(zeros_leaf)
            self._label_dev.append(
                jnp.asarray(self._pad_block(self.label, lo, hi)))
            self._weight_dev.append(
                jnp.asarray(self._pad_block(self.weight, lo, hi))
                if self.weight is not None else ones_w)
        self._zeros_leaf = zeros_leaf
        # the f32 copies were only needed for the device upload; at
        # 1e9+ rows they are multiple GiB of host RAM. (The Dataset's
        # own float64 metadata.label stays — it backs the public
        # get_label() API and is owned by the Dataset, not the engine.)
        self.label = self.weight = None
        log.info(
            f"streaming engine: {self.n} rows x {F} features binned on "
            f"host ({self.binned.nbytes / 2**30:.2f} GiB), "
            f"{self.n_blocks} blocks of {self.block_rows} rows")

    def _make_find(self):
        """Jitted per-level split search over the frontier. Everything
        the host loop needs comes back PACKED into one [K, 13] f32
        array (gain, feature, threshold_bin, default_left,
        left_sums[3], right_sums[3], parent_sums[3]) — through the
        tunneled chip every separate device->host pull pays ~30-100 ms
        of latency, and the unpacked dict was ~20 pulls per level.
        ``allowed`` is a TRACED argument (same [F] bool shape every
        call) so per-tree feature_fraction masks never recompile.
        With ``extra_trees``, per-(leaf, feature) uniforms ride a
        fourth traced argument (drawn host-side from ``self._rng`` per
        level — mirroring learner/serial.py's per-round draws), so the
        one-random-threshold-per-node semantics actually bind instead
        of silently degrading to plain GBDT (find_best_split skips the
        extra_trees filter when extra_u is None)."""
        use_extra = bool(self._scfg.extra_trees)

        def one(h, p, allowed, eu):
            r = find_best_split(h, p, self.feat_num_bin,
                                self.feat_has_nan, allowed, self._scfg,
                                extra_u=eu)
            return jnp.concatenate([
                jnp.stack([r["gain"], r["feature"].astype(jnp.float32),
                           r["threshold_bin"].astype(jnp.float32),
                           r["default_left"].astype(jnp.float32)]),
                r["left_sums"].astype(jnp.float32),
                r["right_sums"].astype(jnp.float32),
                p.astype(jnp.float32)])

        return jax.jit(jax.vmap(
            one, in_axes=(0, 0, None, 0 if use_extra else None)))

    def _leaf_out_np(self, g: float, h: float) -> float:
        """calc_leaf_output (ops/split.py) in host numpy — leaf outputs
        are needed per split on the host path and a device round-trip
        each costs tunnel latency."""
        l1, l2 = self._scfg.lambda_l1, self._scfg.lambda_l2
        t = np.sign(g) * max(abs(g) - l1, 0.0) if l1 > 0.0 else g
        denom = h + l2
        out = -t / max(denom, 1e-30) if denom > 0.0 else 0.0
        md = self._scfg.max_delta_step
        if md > 0.0:
            out = float(np.clip(out, -md, md))
        return float(out)

    # ------------------------------------------------------------- API
    def can_fuse_iters(self) -> bool:
        return True

    def num_trees(self) -> int:
        return len(self.models)

    @property
    def current_iteration(self) -> int:
        return self.iter_

    def add_valid(self, data, name):
        """Valid sets evaluate via the host model over the RAW valid
        features (the streaming engine never bins or uploads them —
        a valid set large enough to matter should be subsampled)."""
        raw = getattr(data, "data", None)
        if raw is None or isinstance(raw, str):
            log.fatal(self._UNSUPPORTED_MSG.format(
                what="valid sets without in-memory raw features "
                     "(file-backed, or already constructed with the "
                     "raw matrix freed — pass a fresh Dataset)"))
        if not hasattr(raw, "shape"):
            # scipy sparse would also fail later (len() raises on
            # sparse, and the host-model traversal reads dense rows) —
            # reject anything non-array-like up front with the standard
            # message instead of crashing mid-eval
            log.fatal(self._UNSUPPORTED_MSG.format(
                what="valid sets whose raw features are not an array"))
        if hasattr(raw, "tocsr") and not isinstance(raw, np.ndarray):
            log.fatal(self._UNSUPPORTED_MSG.format(
                what="sparse raw valid features (densify with "
                     ".toarray() first)"))
        self.valid_data.append(data)
        self.valid_names.append(name)

    @property
    def valid_scores(self):
        log.fatal(self._UNSUPPORTED_MSG.format(
            what="custom feval over valid sets"))

    def eval_set(self, which: int):
        """(data_name, metric_name, value, higher_better) tuples —
        the resident engine's contract (GBDT.eval_set), via the shared
        metric helper so the two engines cannot drift.

        Training eval (which=-1) pulls the full device-resident score
        each call — 4 bytes/row of D2H; at 1e9-row scale through a
        slow pull path enable it sparingly (metric_freq)."""
        from ..metric import eval_metric_rows
        if which < 0:
            name = "training"
            raw = np.concatenate(
                [np.asarray(self._score_dev[b])[:hi - lo]
                 for b, lo, hi in self._blocks()])
            md = self.train_set.metadata
            label, weight, qb = md.label, md.weight, md.query_boundaries
        else:
            ds = self.valid_data[which]
            name = self.valid_names[which]
            # incremental raw cache: only the NEW trees since the last
            # eval traverse the valid matrix (the host model folds the
            # init score into tree 0, so increments sum exactly);
            # without this, per-iteration eval would rebuild and
            # re-traverse the whole forest — O(T^2) over training
            # shape[0], not len(): valid row count must not depend on
            # the raw container's __len__ (absent on scipy sparse)
            done, raw = self._valid_raw_cache.get(
                which, (0, np.zeros(int(ds.data.shape[0]), np.float64)))
            n_now = len(self.models)
            if n_now > done:
                raw = raw + self.predict(
                    ds.data, raw_score=True, start_iteration=done,
                    num_iteration=n_now - done)
                self._valid_raw_cache[which] = (n_now, raw)
            if ds.metadata.init_score is not None:
                # per-row valid init score (resident engine adds it in
                # _init_score_tile; the host model knows nothing of it)
                raw = raw + np.asarray(ds.metadata.init_score,
                                       np.float64)
            label = ds.metadata.label
            weight = ds.metadata.weight
            qb = ds.metadata.query_boundaries
        return eval_metric_rows(self.objective, self.metrics, name,
                                raw, label, weight, qb, 1)

    def rollback_one_iter(self):
        log.fatal(self._UNSUPPORTED_MSG.format(what="rollback"))

    def train_chunk(self, k: int):
        for _ in range(k):
            self.train_one_iter()

    # -------------------------------------------------------- training
    def _blocks(self):
        for b in range(self.n_blocks):
            lo = b * self.block_rows
            hi = min(self.n, lo + self.block_rows)
            yield b, lo, hi

    def _pad_block(self, arr, lo, hi, fill=0):
        out = arr[lo:hi]
        if hi - lo < self.block_rows:
            pad = np.full((self.block_rows - (hi - lo),) + out.shape[1:],
                          fill, dtype=out.dtype)
            out = np.concatenate([out, pad])
        return out

    def _empty_table(self) -> Dict[str, np.ndarray]:
        z = np.zeros(1, np.int32)
        return {"leaf": z - 1, "feat": z, "thr": z, "dl": z,
                "new_leaf": z, "nb": z, "hn": z}

    def train_one_iter(self) -> None:
        L = int(self.config.num_leaves)
        max_depth = int(self.config.max_depth)
        F = self.num_features

        allowed = np.ones(F, bool)
        if self._ff < 1.0:
            k = max(1, int(F * self._ff))
            allowed[:] = False
            allowed[self._rng.choice(F, size=k, replace=False)] = True
        allowed_dev = jnp.asarray(allowed)

        for b in range(self.n_blocks):
            self._leaf_dev[b] = self._zeros_leaf
        nl = 1
        nn = 0
        # per-node host arrays (grown as splits land)
        sf, tb, dl, lc, rc, gains, ivals, icnts = \
            [], [], [], [], [], [], [], []
        leaf_parent_slot: Dict[int, tuple] = {}   # leaf -> (node, side)
        leaf_sums = np.zeros((L, 3), np.float64)
        frontier = [0]
        table = self._empty_table()
        depth = 0

        while frontier:
            K = len(frontier)
            # pad the frontier (and split table below) to powers of two:
            # -1 sentinel leaves match no rows, so the padding costs a
            # slice of wasted histogram width but caps the number of
            # distinct jit specializations at log2(L) — without it every
            # pruned-frontier shape recompiles (~30 s each on the
            # tunneled chip, dwarfing the sweep itself)
            K_pad = 1 << max(0, (K - 1)).bit_length()
            frontier_dev = jnp.asarray(np.asarray(
                frontier + [-1] * (K_pad - K), np.int32))
            tbl_dev = {k: jnp.asarray(v) for k, v in table.items()}
            hist = None
            prev = None          # (bins_blk, hist-after-that-block)
            for b, lo, hi in self._blocks():
                bins_blk = jnp.asarray(self._pad_block(self.binned, lo, hi))
                leaf_new, h_blk = self._sweep(
                    bins_blk, self._score_dev[b], self._label_dev[b],
                    self._weight_dev[b], np.int32(hi - lo),
                    self._leaf_dev[b], tbl_dev, frontier_dev)
                self._leaf_dev[b] = leaf_new    # stays on device
                hist = h_blk if hist is None else hist + h_blk
                # throttle + free with a 2-block in-flight window:
                # unthrottled async dispatch would enqueue EVERY
                # block's ~256 MB device buffer before the device
                # drains one — at 128 blocks that is ~34 GB of live
                # transients and an OOM (observed at the 32 GiB proof
                # shape). Blocking on the PREVIOUS block keeps upload
                # of block b+1 overlapped with compute of block b
                # while bounding transients to ~512 MB.
                if prev is not None:
                    jax.block_until_ready(prev[1])
                    prev[0].delete()
                prev = (bins_blk, hist)
            if prev is not None:
                jax.block_until_ready(prev[1])
                prev[0].delete()
            # leaf totals straight from the histogram: any one
            # feature's bins partition the leaf's rows
            parent_sums = jnp.sum(hist[:, 0, :, :], axis=1)
            # per-level extra_trees uniforms (one random threshold per
            # (leaf, feature)); None when off — the jitted find's
            # in_axes already match
            eu = (jnp.asarray(self._rng.random((K_pad, F)), jnp.float32)
                  if self._scfg.extra_trees else None)
            # ONE device->host pull per level (packed [K_pad, 13])
            bests = np.asarray(self._find(hist, parent_sums,
                                          allowed_dev, eu), np.float64)
            for i, lf in enumerate(frontier):
                leaf_sums[lf] = bests[i, 10:13]
            table = self._empty_table()
            depth += 1
            if nl >= L or (0 < max_depth <= depth - 1):
                frontier = []
                break
            gains_k = bests[:K, 0]                   # drop pad lanes
            order = np.argsort(-gains_k)             # best-first within
            budget = L - nl                          # the level
            chosen = [i for i in order[:budget]
                      if np.isfinite(gains_k[i]) and gains_k[i] > -1e37]
            if not chosen:
                frontier = []
                break
            tl, tf, tt, tdl, tnew, tnb, thn = [], [], [], [], [], [], []
            new_frontier = []
            for i in chosen:
                lf = frontier[i]
                feat = int(bests[i, 1])
                node = nn
                nn += 1
                right_leaf = nl
                nl += 1
                if lf in leaf_parent_slot:
                    pn, side = leaf_parent_slot.pop(lf)
                    (lc if side == 0 else rc)[pn] = node
                sf.append(feat)
                tb.append(int(bests[i, 2]))
                dl.append(bool(bests[i, 3] > 0.5))
                lc.append(~lf)
                rc.append(~right_leaf)
                gains.append(float(bests[i, 0]))
                ivals.append(self._leaf_out_np(leaf_sums[lf][0],
                                               leaf_sums[lf][1]))
                icnts.append(int(round(leaf_sums[lf][2])))
                leaf_parent_slot[lf] = (node, 0)
                leaf_parent_slot[right_leaf] = (node, 1)
                leaf_sums[lf] = bests[i, 4:7]
                leaf_sums[right_leaf] = bests[i, 7:10]
                tl.append(lf)
                tf.append(feat)
                tt.append(int(bests[i, 2]))
                tdl.append(int(bests[i, 3] > 0.5))
                tnew.append(right_leaf)
                tnb.append(int(self._num_bin_np[feat]))
                thn.append(int(self._has_nan_np[feat]))
                new_frontier.extend([lf, right_leaf])
            S = len(tl)
            S_pad = 1 << max(0, (S - 1)).bit_length()
            pad = [0] * (S_pad - S)
            table = {"leaf": np.asarray(tl + [-1] * (S_pad - S), np.int32),
                     "feat": np.asarray(tf + pad, np.int32),
                     "thr": np.asarray(tt + pad, np.int32),
                     "dl": np.asarray(tdl + pad, np.int32),
                     "new_leaf": np.asarray(tnew + pad, np.int32),
                     "nb": np.asarray(tnb + pad, np.int32),
                     "hn": np.asarray(thn + pad, np.int32)}
            frontier = new_frontier if nl < L and not (
                0 < max_depth <= depth) else []
            if not frontier:
                break

        # ---- final sweep: last split table + score update ------------
        leaf_out = np.zeros(max(nl, 1), np.float32)
        for lf in range(nl):
            leaf_out[lf] = self._leaf_out_np(leaf_sums[lf][0],
                                             leaf_sums[lf][1])
        tbl_dev = {k: jnp.asarray(v) for k, v in table.items()}
        leaf_out_dev = jnp.asarray(leaf_out)
        prev = None
        for b, lo, hi in self._blocks():
            bins_blk = jnp.asarray(self._pad_block(self.binned, lo, hi))
            leaf_new, score_new = self._final(
                bins_blk, self._score_dev[b], self._leaf_dev[b],
                tbl_dev, leaf_out_dev)
            self._leaf_dev[b] = leaf_new
            self._score_dev[b] = score_new
            if prev is not None:
                jax.block_until_ready(prev[1])
                prev[0].delete()
            prev = (bins_blk, score_new)
        if prev is not None:
            jax.block_until_ready(prev[1])
            prev[0].delete()

        tree_arrays = {
            "num_leaves": nl,
            "split_feature": np.asarray(sf, np.int32),
            "threshold_bin": np.asarray(tb, np.int32),
            "default_left": np.asarray(dl, bool),
            "left_child": np.asarray(lc, np.int32),
            "right_child": np.asarray(rc, np.int32),
            "split_gain": np.asarray(gains, np.float32),
            "internal_value": np.asarray(ivals, np.float32),
            "internal_count": np.asarray(icnts, np.int64),
            "leaf_value": leaf_out[:nl].astype(np.float64),
            "leaf_count": leaf_sums[:nl, 2].round().astype(np.int64),
            "leaf_weight": leaf_sums[:nl, 1].astype(np.float64),
        }
        self.models.append(Tree.from_device(
            tree_arrays, self.lr, self.train_set.bin_mappers,
            list(self.train_set.used_features)))
        self.iter_ += 1

    # ------------------------------------------------------- predict
    def predict(self, X, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, **_overrides) -> np.ndarray:
        # _overrides: tpu_predict_* serving knobs (resident-engine
        # traversal only; the host-model path here ignores them)
        from ..io.model_text import HostModel
        cache = getattr(self, "_hm_cache", (None, None))
        if cache[0] != len(self.models):
            cache = (len(self.models),
                     HostModel.from_engine(self, self.config))
            self._hm_cache = cache
        return cache[1].predict(X, raw_score=raw_score,
                                start_iteration=start_iteration,
                                num_iteration=num_iteration,
                                pred_leaf=pred_leaf)
