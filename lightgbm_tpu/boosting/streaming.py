"""Out-of-core (larger-than-HBM) boosting: host-resident bins, streamed
level sweeps — optionally SHARDED over a data mesh (beyond one host).

Closes the last scale-axis gap vs the reference (VERDICT r4 item 3):
upstream LightGBM trains any dataset that fits host RAM/disk — its
two-round loader + row-wise bin storage never require the binned matrix
on the accelerator (``src/io/dataset_loader.cpp``, SURVEY.md §2.1,
UNVERIFIED — empty mount). The resident engine here (`gbdt.GBDT`)
uploads the full binned matrix to HBM, capping trainable size at
~HBM/(F bytes-per-row). This module removes that cap for the configs
that need it, and with ``tree_learner=data`` removes the ONE-HOST cap
too: each rank streams only its own row shard's blocks and the
per-level histograms meet in a single collective.

Design (SURVEY.md §7.4 hard-part 4, "sharded binning on host, streamed
epochs"; §3.4 data-parallel learner for the sharded composition):

- The BINNED matrix (uint8/16, the big object) stays in host RAM; the
  native binner builds it at ~GB/s. Device-resident state is one row
  BLOCK at a time plus the accumulated `[K, F, B, 3]` histograms
  (~11 MB at K=128/F=28/B=256) — HBM use is O(block), not O(n).
- Trees grow LEVEL-WISE: one streamed pass over the blocks per level
  computes the histograms of every frontier leaf at once (the same
  multi-leaf one-hot-matmul histogram the resident engine uses), so a
  depth-d tree costs d+1 sweeps of PCIe traffic instead of the
  resident engine's zero. Best-first order inside a level is
  preserved by gain-ranking when the leaf budget runs out, but
  cross-level best-first interleaving is NOT — a documented
  divergence from the reference's queue (`serial_tree_learner.cpp`):
  per-sweep cost makes strict best-first (one sweep per leaf)
  ~num_leaves/depth times more expensive.
- SHARDED (``tree_learner=data``): the row range splits contiguously
  per rank (mesh device; on a multi-process gang each process streams
  only its own shard's blocks), every rank accumulates its local
  `[K, F, B, 3]` level histogram across its blocks exactly like the
  serial path, and then issues **ONE** ``psum`` (or ``psum_scatter``
  honoring ``tpu_hist_reduce``) of the ACCUMULATED histogram per tree
  level through the shared packed-int32 collective wire
  (learner/collective.py, the same wire the resident data-parallel
  learner reduces on) — never one collective per block. Split finding
  sees the global histogram, so every rank grows bit-identical trees;
  with exact (quantized-integer or small-scale bf16-rounded) histogram
  sums the trees are also bit-identical to single-shard streaming.
- Per-row state (score, leaf id) lives device-resident per block;
  gradients are recomputed on device per block from the streamed
  score (cheaper than streaming g/h separately).
- PIPELINED (``tpu_stream_overlap``, default on): the next block's
  upload stages on a worker thread while the device sweeps the
  current one, the per-level histogram collective dispatches without
  a blocking host sync, and the round-end score sweep drains behind
  the next round's first level sweep. Bit-identical on/off by
  construction — only where the host blocks moves — and checkpoint
  exports drain pending updates first (docs/perf.md
  "Communication/compute overlap").
- BAGGING / GOSS ride per-block row masks derived on device from a
  counter-based hash of each row's GLOBAL index — no mask storage, no
  host traffic, and the same row keeps the same draw no matter how
  the rows are cut into blocks or shards. GOSS thresholds come from a
  GLOBAL |g*h| order statistic via a small per-round collective (a
  65536-bucket float-bit histogram of the metric — the same
  small-collective pattern the serial learner's guard psum uses), so
  the kept set is shard-invariant; the selected count can exceed
  ``top_rate*n`` by the boundary bucket's population (<=0.4% relative
  metric granularity — a documented divergence from the resident
  engine's exact top-k).
- Quantized gradients (``use_quantized_grad``) are supported: integer
  level histograms make the accumulated sums EXACT at any scale (the
  bit-identical-across-shards guarantee) and engage the packed int32
  wire (2/3 payload) on the per-level collective.

Durable checkpoints / resume: the engine exports and imports complete
training state through the recovery subsystem (export_train_state /
import_train_state below) — a streamed, even sharded, run interrupted
mid-training resumes BIT-EXACT from its newest round-boundary
checkpoint (docs/robustness.md "Streamed (out-of-core) resume").

Supported configs (all checked at construction): single-output
objectives (binary, regression family, xentropy) on numerical
features, tree_learner serial or data, bagging (incl. pos/neg
fractions), GOSS, quantized gradients, feature_fraction, extra_trees.
Everything else — multiclass, ranking, categorical splits, DART/RF,
linear trees, monotone/CEGB/interaction constraints, EFB, forced
splits, continuation, voting-/feature-parallel learners — stays on
the resident engine; `create_boosting` only routes here when the data
cannot fit (or ``tpu_streaming=true`` forces it). Split-rule parity
(L1/L2, min_data, min_hessian, min_gain, max_delta_step, path
smoothing, extra-trees, missing directions) comes for free: the same
`find_best_split` evaluates the accumulated histograms.
"""
from __future__ import annotations

import math
import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Dataset
from ..metric import metrics_for_config
from ..objective import create_objective
from ..ops.pallas_histogram import multi_leaf_histogram_xla
from ..ops.split import SplitConfig, find_best_split
from ..tree import Tree
from ..utils import log
from ..utils.prefetch import BlockPrefetcher, InflightWindow

# |g*h| bucket count for the GOSS threshold histogram: the top 16 bits
# of the positive-f32 bit pattern (8 exponent + 8 mantissa bits) are
# monotone in the value, so a bucketed order statistic is exact up to
# one bucket width (~0.4% relative)
_GOSS_BUCKETS = 1 << 16


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _even_split(n: int, k: int) -> List[int]:
    """Contiguous near-even row split: first ``n % k`` parts get one
    extra row (the launcher's shard convention)."""
    base, rem = divmod(n, k)
    return [base + (1 if i < rem else 0) for i in range(k)]


def _hash_u01(idx_u32, salt_u32):
    """Counter-based uniform in [0, 1): a pure function of the GLOBAL
    row index and a per-round salt, so bagging/GOSS/stochastic-rounding
    draws are identical no matter how rows are cut into blocks or
    shards (lowne-style 32-bit mix; 24-bit mantissa-exact floats)."""
    x = idx_u32 + salt_u32 * jnp.uint32(0x9E3779B9)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return (x >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def _apply_table(bins_blk, leaf_blk, tbl):
    """Route rows through one level's split table (tbl arrays are [S]).
    Left child KEEPS the parent's leaf id; rows routed right get the
    new leaf id. NaN rows (last bin when has_nan) follow default_left —
    same semantics as the resident partition (learner/serial.py
    apply_splits). ``leaf_blk`` is int16 (device-resident per-row
    state: 2 bytes/row matters at 1e9 rows)."""
    lid = leaf_blk.astype(jnp.int32)
    mk = lid[:, None] == tbl["leaf"][None, :]            # [R, S]
    sel = jnp.any(mk, axis=1)

    def pick(a):
        return jnp.sum(jnp.where(mk, a[None, :].astype(jnp.int32), 0),
                       axis=1)

    feat_r = pick(tbl["feat"])
    thr_r = pick(tbl["thr"])
    dl_r = pick(tbl["dl"]) > 0
    new_r = pick(tbl["new_leaf"])
    nb_r = pick(tbl["nb"])
    hn_r = pick(tbl["hn"]) > 0
    col = jnp.take_along_axis(
        bins_blk.astype(jnp.int32),
        jnp.clip(feat_r, 0, bins_blk.shape[1] - 1)[:, None],
        axis=1)[:, 0]
    is_missing = hn_r & (col == nb_r - 1)
    goes_left = jnp.where(is_missing, dl_r, col <= thr_r)
    return jnp.where(sel & ~goes_left, new_r, lid).astype(jnp.int16)


class StreamingGBDT:
    """Boosting engine for datasets whose binned matrix exceeds HBM —
    single-shard, or data-parallel over a mesh when the per-rank shard
    would still exceed HBM (the Criteo-1TB-class composition).

    Quacks like `gbdt.GBDT` for the surfaces the Booster/engine.train
    loop and the model writer touch; everything per-row lives on host.
    """

    _UNSUPPORTED_MSG = (
        "tpu_streaming (out-of-core) supports single-output objectives "
        "on numerical features with tree_learner=serial or data "
        "(bagging, GOSS and quantized gradients included); {what} "
        "requires the resident engine — reduce the dataset, raise the "
        "device budget, or drop the option")

    def __init__(self, config: Config, train_set: Dataset,
                 fobj=None, mesh=None, init_forest=None):
        self.config = config
        self.train_set = train_set.construct()
        ds = self.train_set

        def _no(cond, what):
            if cond:
                log.fatal(self._UNSUPPORTED_MSG.format(what=what))

        # config-level eligibility: ONE walk of the capability table's
        # "streaming" column (lightgbm_tpu/capabilities.py) — the same
        # rows _streaming_compatible reads, so auto-routing and this
        # constructor can no longer drift (the PR-5 bug class; the
        # sweep in tests/test_streaming_sharded.py pins the iff).
        # Runtime-only features ride the `extra` flags.
        from .. import capabilities
        for name, cap, v in capabilities.engine_verdicts(
                "streaming", config,
                extra={"custom_objective": fobj is not None,
                       "continuation": init_forest is not None}):
            if v == capabilities.FATAL:
                _no(True, cap.describe)
            elif name == "auto_quantize":
                # DEMOTE: tpu_auto_quantize targets the resident int8
                # histogram kernels; an un-asked-for discretization
                # would change streamed numerics — quietly drop it. An
                # EXPLICIT use_quantized_grad stays honored: integer
                # level histograms are what make sharded streaming
                # bit-exact and engage the packed collective wire.
                config.use_quantized_grad = False
            else:
                # a DEMOTE row added to the table without a demotion
                # action here would otherwise be a silent no-op — the
                # one-side-edited drift this engine exists to refuse
                log.fatal(f"capability table DEMOTEs {name!r} for the "
                          f"streaming engine but StreamingGBDT has no "
                          f"demotion action for it — add one here")
        # runtime-shape gates (not feature drift; stay constructor-local)
        _no(mesh is not None and config.tree_learner == "serial",
            "an explicit mesh with tree_learner=serial")
        # dataset-level gate: pandas-category / auto-detected
        # categorical BINS fatal even when categorical_feature is unset
        is_cat = [ds.bin_mappers[f].bin_type == "categorical"
                  for f in ds.used_features]
        _no(any(is_cat), "categorical features")
        self.objective = create_objective(config)
        # belt-and-braces behind the table's name-based ranking row: a
        # custom objective OBJECT flagging is_ranking still fatals
        _no(getattr(self.objective, "is_ranking", False),
            "ranking objectives")

        self.num_class = 1
        self.average_output = False
        self.models: List[Tree] = []
        # mutation version for host-model / hot-swap cache keys (the
        # resident engine's _invalidate_forest_cache analog; bumped by
        # serving.ModelWatcher when it swaps a new forest in)
        self._models_version = 0
        self.iter_ = 0
        self.valid_data: list = []
        self.valid_names: list = []
        self._valid_raw_cache: Dict[int, tuple] = {}
        self.fobj = None
        self.metrics = metrics_for_config(config)

        self.binned = ds.binned                     # host [n, F] uint
        if ds.device_ingested() is not None:
            # streamed blocks are uploaded one at a time per rank —
            # release a device-resident ingest copy (possible when a
            # standalone construct picked device ingest before a forced
            # tpu_streaming run) instead of leaving it orphaned in HBM
            ds._ingest = None
        self.n = int(ds.num_data)
        F = len(ds.used_features)
        self.num_features = F
        num_bin = ds.feature_num_bins()
        self.max_num_bin = int(num_bin.max()) if F else 2
        self.B = max(8, _ceil_to(self.max_num_bin, 8))
        has_nan = np.array(
            [ds.bin_mappers[f].missing_type == "nan"
             for f in ds.used_features], dtype=bool)
        self.feat_num_bin = jnp.asarray(num_bin.astype(np.int32))
        self.feat_has_nan = jnp.asarray(has_nan)
        self._num_bin_np = num_bin.astype(np.int32)
        self._has_nan_np = has_nan

        # ---- mesh / rank layout (tree_learner=data) ------------------
        self.mesh = None
        self._axis = ""
        R = 1
        if config.tree_learner == "data":
            if mesh is not None:
                self.mesh = mesh
            else:
                from ..parallel.mesh import create_data_mesh
                nd = (int(config.tpu_mesh_shape)
                      if str(config.tpu_mesh_shape).strip() else None)
                self.mesh = create_data_mesh(nd)
            R = int(self.mesh.devices.size)
            if R == 1:
                self.mesh = None    # one shard: the serial path IS it
            else:
                self._axis = self.mesh.axis_names[0]
        self.R = R
        self._build_ranks()

        if int(config.num_leaves) > 32767:
            log.fatal("tpu_streaming caps num_leaves at 32767 (int16 "
                      "row state)")
        md = ds.metadata
        self.label = np.asarray(md.label, np.float32)
        self.weight = (None if md.weight is None
                       else np.asarray(md.weight, np.float32))
        self.init_scores = np.zeros(1, dtype=np.float64)
        if md.label is not None:
            self.init_scores[0] = self.objective.init_score(
                md.label, md.weight)

        self._scfg = SplitConfig(
            lambda_l1=config.lambda_l1,
            lambda_l2=config.lambda_l2,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            path_smooth=config.path_smooth,
            extra_trees=config.extra_trees,
        )
        self.lr = float(config.learning_rate)
        self._rng = np.random.default_rng(int(config.seed) & 0x7FFFFFFF)
        self._ff = float(config.feature_fraction)

        # ---- row sampling + quantization statics ---------------------
        c = config
        self._use_goss = str(c.data_sample_strategy) == "goss"
        self._use_bag = (not self._use_goss and c.bagging_freq > 0
                         and (c.bagging_fraction < 1.0
                              or c.pos_bagging_fraction < 1.0
                              or c.neg_bagging_fraction < 1.0))
        self._bag_posneg = self._use_bag and (
            c.pos_bagging_fraction < 1.0 or c.neg_bagging_fraction < 1.0)
        self._top_rate = float(c.top_rate)
        self._other_rate = float(c.other_rate)
        self._goss_amp = ((1.0 - self._top_rate)
                          / max(self._other_rate, 1e-12))
        self._use_quant = bool(c.use_quantized_grad)
        self._use_sr = self._use_quant and bool(c.stochastic_rounding)
        qbins = max(2, int(c.num_grad_quant_bins))
        self._glevels = max(qbins // 2, 1)
        self._hlevels = max(qbins - 1, 1)
        self._track_stats = self._use_goss or self._use_quant
        self._seed_u32 = np.uint32(int(c.seed) & 0xFFFFFFFF)
        self._bag_seed_u32 = np.uint32(int(c.bagging_seed) & 0xFFFFFFFF)
        self._pending_stats = None
        if (self._use_bag or self._use_goss or self._use_sr) \
                and self.n_global > 0x7FFFFFFF:
            log.fatal("tpu_streaming row sampling hashes int32 global "
                      "row indices; > 2^31-1 rows need sampling off")
        # collective wire mode (mirrors the resident data learner):
        # psum_scatter feature ownership when tpu_hist_reduce=scatter
        # and the width divides; packed int32 wire under quantization
        self._scatter = (str(c.tpu_hist_reduce) == "scatter"
                         and self.R > 1 and F > 0 and F % self.R == 0)
        self._packed_wire = (self._use_quant and self.R > 1
                             and bool(c.tpu_hist_packed_wire))
        # host-side comm/stream counters — always on (plain ints), the
        # obs registry mirrors them when metrics are enabled
        self.comm_stats = {"allreduce_calls": 0, "allreduce_bytes": 0,
                           "blocks_scanned": 0, "levels": 0}

        # communication/compute overlap (tpu_stream_overlap; docs/
        # perf.md "Communication/compute overlap"). auto = on: the
        # three pipelining moves (threaded H2D block staging, no host
        # sync before the per-level collective, deferred final sweep)
        # only change where the HOST blocks — accumulation order,
        # reduce payloads and score arithmetic are untouched, so the
        # trees are bit-identical on/off by construction. "false" is
        # the synchronous A/B arm (attribution + escape hatch).
        self._overlap = str(config.tpu_stream_overlap) != "false"
        # per-rank in-flight sweep windows, PERSISTENT across level
        # sweeps, the final sweep, and round boundaries: an item is
        # (bins_upload, sweep_output); completing it host-blocks on
        # the output and frees the upload. depth=1 keeps the historic
        # 2-block transient bound (~512 MB/rank at the default block).
        # Under overlap the windows deliberately stay non-empty across
        # the level->find and final->next-round seams — that IS the
        # pipelining; export_train_state drains them first (the PR 13
        # contract; _drain_inflight below).
        def _complete_inflight(item):
            bins_blk, done = item
            jax.block_until_ready(done)
            bins_blk.delete()
        self._inflight = [InflightWindow(1, _complete_inflight)
                          for _ in self._ranks]
        # cyclic one-ahead upload prefetcher over the step-major block
        # schedule (built lazily: _block_schedule needs the rank
        # layout final). Every sweep consumes exactly one full cycle,
        # so the feed stays aligned at sweep boundaries; take(expect=)
        # makes any drift a loud error.
        self._feed = None

        # buffer donation for the streamed score slots (tpu_donate;
        # docs/perf.md "Iteration floor"): each block's [block_rows]
        # f32 score is a pure carry — the final sweep's output fully
        # replaces the slot and every reader (eval_set, checkpoints,
        # the stats prepass) sees only the reassigned reference
        from ..utils.debug import donation_enabled
        self._donate = donation_enabled(config)
        self._hist_rows_per_block = min(self.block_rows, 1 << 14)
        self._sweep = self._make_sweep()
        self._final = self._make_final()
        self._stats_fn = (jax.jit(self._stats_core())
                          if self._track_stats else None)
        self._find = self._make_find()
        self._find_sharded = (self._make_find_sharded()
                              if self.R > 1 else None)
        self._stats_reduce = (self._make_stats_reduce()
                              if self._track_stats and self.R > 1
                              else None)

        # device-resident per-row state, one slot per (rank, block):
        # score f32, leaf int16, label f32, weight f32 (if any) — ~10
        # bytes/row total, so state for a 32 GiB (1.1e9-row) bin matrix
        # fits v5e HBM while the 28x-larger bins stream. Through the
        # tunneled chip this is also the latency fix: per sweep the
        # ONLY host traffic is the bins block up and one packed [K,13]
        # pull down (the D2H path measures ~60 MB/s here — round-
        # tripping leaf ids per sweep was the first version's wall).
        init = np.float32(self.init_scores[0])
        self._score_dev: List[list] = []
        self._leaf_dev: List[list] = []
        self._label_dev: List[list] = []
        self._weight_dev: List[list] = []
        self._zeros_leaf: List[jax.Array] = []
        for ri, rk in enumerate(self._ranks):
            dev = rk["dev"]
            zeros_leaf = self._put(
                np.zeros(self.block_rows, np.int16), dev)
            ones_w = (self._put(np.ones(self.block_rows, np.float32),
                                dev)
                      if self.weight is None else None)
            self._zeros_leaf.append(zeros_leaf)
            sc, lf, lb, wt = [], [], [], []
            for b, lo, hi in self._rank_blocks(ri):
                sc.append(self._put(
                    np.full(self.block_rows, init, np.float32), dev))
                lf.append(zeros_leaf)
                lb.append(self._put(
                    self._pad_block(self.label, lo, hi), dev))
                wt.append(self._put(
                    self._pad_block(self.weight, lo, hi), dev)
                    if self.weight is not None else ones_w)
            self._score_dev.append(sc)
            self._leaf_dev.append(lf)
            self._label_dev.append(lb)
            self._weight_dev.append(wt)
        # the f32 copies were only needed for the device upload; at
        # 1e9+ rows they are multiple GiB of host RAM. (The Dataset's
        # own float64 metadata.label stays — it backs the public
        # get_label() API and is owned by the Dataset, not the engine.)
        self.label = self.weight = None
        n_blocks_local = sum(rk["n_blocks"] for rk in self._ranks)
        self.n_blocks = n_blocks_local
        log.info(
            f"streaming engine: {self.n} rows x {F} features binned on "
            f"host ({self.binned.nbytes / 2**30:.2f} GiB), "
            f"{n_blocks_local} local blocks of {self.block_rows} rows"
            + (f", shard {[r['pos'] for r in self._ranks]} of "
               f"{self.R} ({self.n_global} global rows; one "
               f"{'psum_scatter' if self._scatter else 'psum'} per "
               f"level{', packed int32 wire' if self._packed_wire else ''})"
               if self.R > 1 else ""))

    # ------------------------------------------------------ rank layout
    def _put(self, arr, dev):
        """Device placement: committed to the rank's mesh device when
        sharded, the default device otherwise (matching the serial
        streaming path's uncommitted placement)."""
        if dev is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, dev)

    def _build_ranks(self):
        """Split this process's rows over its local mesh devices and
        learn every rank's GLOBAL row offset (the seed of the
        shard-invariant row hash). Single process: all ranks are local;
        a multi-process gang contributes its own shard (the launcher's
        ``data_fn`` row partition) and gathers the per-rank counts."""
        cfg = self.config
        R = self.R
        if R == 1:
            self._ranks = [{"pos": 0, "dev": None, "lo": 0,
                            "hi": self.n, "goff": 0}]
            self.n_global = self.n
            counts_all = np.asarray([self.n], np.int64)
        else:
            from ..parallel.mesh import local_mesh_positions
            flat = list(self.mesh.devices.flat)
            nproc = jax.process_count()
            if nproc > 1:
                my_pos, _ = local_mesh_positions(self.mesh)
                if not my_pos:
                    # a gang member outside the (possibly capped) mesh
                    # would silently drop its rows AND deadlock the
                    # in-mesh ranks' collectives — fatal like the
                    # zero-rows guard below
                    log.fatal(
                        f"streamed sharded training: process "
                        f"{jax.process_index()} owns no device of the "
                        f"{R}-shard mesh (tpu_mesh_shape smaller than "
                        f"the gang?) — its rows would be dropped; "
                        f"match the mesh size to the process count")
                sizes = _even_split(self.n, len(my_pos))
                counts = np.zeros(R, np.int64)
                for i, p in enumerate(my_pos):
                    counts[p] = sizes[i]
                from jax.experimental import multihost_utils
                g = np.asarray(
                    multihost_utils.process_allgather(counts)).reshape(
                        nproc, R)
                counts_all = g.sum(axis=0).astype(np.int64)
            else:
                my_pos = list(range(R))
                sizes = _even_split(self.n, R)
                counts_all = np.asarray(sizes, np.int64)
            goffs = np.concatenate(
                [[0], np.cumsum(counts_all)[:-1]]).astype(np.int64)
            self.n_global = int(counts_all.sum())
            lo = 0
            self._ranks = []
            for i, p in enumerate(my_pos):
                rows = int(counts_all[p]) if nproc > 1 else sizes[i]
                self._ranks.append({"pos": p, "dev": flat[p], "lo": lo,
                                    "hi": lo + rows,
                                    "goff": int(goffs[p])})
                lo += rows
        bad = ([int(p) for p in np.nonzero(counts_all <= 0)[0]]
               if R > 1 else [])
        if bad:
            # mirrors _cli_file_shard's early fatal: a rank that would
            # stream zero blocks deadlocks the per-level collective
            log.fatal(
                f"streamed sharded training would hand rank(s) "
                f"{bad[:8]} zero rows ({self.n_global} global rows "
                f"over {self.R} shards) — every rank must stream at "
                f"least one block; lower tpu_mesh_shape / the process "
                f"count, or feed more rows")

        # block size: bins block ~256 MB by default (PCIe-friendly, far
        # under any HBM), rounded to a lane multiple; per-RANK row
        # ranges cut into blocks of this size (the last block pads)
        rank_max = int(counts_all.max())
        blk = int(cfg.tpu_stream_block_rows)
        explicit = blk > 0
        if blk <= 0:
            blk = max(1 << 16, (256 << 20) // max(self.num_features, 1))
        blk = min(blk, max(rank_max, 8))
        # the hist kernel's internal row chunk must divide the block;
        # blocks >= 16 Ki rows round up to a 16 Ki multiple (the last
        # block pads), smaller ones use the block itself as the chunk
        self.block_rows = (_ceil_to(blk, 1 << 14) if blk >= (1 << 14)
                           else _ceil_to(blk, 8))
        if explicit and self.block_rows != blk:
            # warn only on a real ROUNDING of the requested size (the
            # histogram kernel's row chunk must divide the block) —
            # a value merely clamped to the per-rank row count is a
            # normal one-block configuration, not a mismatch
            log.warning(
                f"tpu_stream_block_rows={cfg.tpu_stream_block_rows} "
                f"does not divide cleanly against the per-rank row "
                f"range / histogram row chunk; rounded to "
                f"{self.block_rows}")
        for rk in self._ranks:
            rk["n_blocks"] = max(
                1, math.ceil((rk["hi"] - rk["lo"]) / self.block_rows))

    def _rank_blocks(self, ri: int):
        rk = self._ranks[ri]
        for b in range(rk["n_blocks"]):
            lo = rk["lo"] + b * self.block_rows
            hi = min(rk["hi"], lo + self.block_rows)
            yield b, lo, hi

    # --------------------------------------------------- jitted pieces
    def _make_sweep(self):
        """Build the jitted per-block level sweep. Only ``bins_blk``
        streams from host; score/label/weight/leaf are device-resident
        block slots and the valid-row count rides as one scalar.
        Bagging/GOSS masks are derived in-sweep from the block's GLOBAL
        row offset (``off``) + the per-round sampling scalars
        (``sampf``/``sampi``), so they cost zero host traffic and are
        invariant to the block/shard cut."""
        objective = self.objective
        num_bins = self.B
        rpb = self._hist_rows_per_block
        use_bag, posneg = self._use_bag, self._bag_posneg
        use_goss, amp = self._use_goss, self._goss_amp
        use_quant, use_sr = self._use_quant, self._use_sr
        c = self.config
        bag_frac = float(c.bagging_fraction)
        pos_frac = float(c.pos_bagging_fraction)
        neg_frac = float(c.neg_bagging_fraction)

        def masks(g, h, label_blk, cnt, idx_u32, sampf, sampi):
            if use_goss:
                metric = jnp.abs(g * h) * cnt
                live = cnt > 0
                is_top = (metric >= sampf[0]) & live
                u = _hash_u01(idx_u32, sampi[1])
                picked = live & ~is_top & (u < sampf[1])
                mask_gh = (is_top.astype(jnp.float32)
                           + picked.astype(jnp.float32)
                           * jnp.float32(amp))
                mask_cnt = (is_top | picked).astype(jnp.float32)
                return mask_gh, mask_cnt
            if use_bag:
                u = _hash_u01(idx_u32, sampi[0])
                if posneg:
                    keep = jnp.where(label_blk > 0, u < pos_frac,
                                     u < neg_frac)
                else:
                    keep = u < bag_frac
                m = cnt * keep.astype(jnp.float32)
                return m, m
            return cnt, cnt

        @jax.jit
        def sweep(bins_blk, score_blk, label_blk, weight_blk, n_valid,
                  leaf_blk, tbl, frontier, off, sampf, sampi):
            leaf_new = _apply_table(bins_blk, leaf_blk, tbl)
            ar = jnp.arange(leaf_blk.shape[0], dtype=jnp.int32)
            cnt = (ar < n_valid).astype(jnp.float32)
            idx_u32 = (off + ar).astype(jnp.uint32)
            g, h = objective.get_gradients(score_blk, label_blk,
                                           weight_blk)
            g = g.reshape(-1).astype(jnp.float32)
            h = h.reshape(-1).astype(jnp.float32)
            mask_gh, mask_cnt = masks(g, h, label_blk, cnt, idx_u32,
                                      sampf, sampi)
            gm = g * mask_gh
            hm = h * mask_gh
            if use_quant:
                # deterministic (or hash-seeded stochastic) rounding to
                # integer levels: exact in the bf16 histogram matmul,
                # exact under any summation order, and int16-packable
                # on the collective wire
                ng = ((_hash_u01(idx_u32, sampi[2]) - 0.5)
                      if use_sr else 0.0)
                nh = ((_hash_u01(idx_u32, sampi[3]) - 0.5)
                      if use_sr else 0.0)
                gq = jnp.round(gm / sampf[2] + ng)
                hq = jnp.round(hm / sampf[3] + nh)
                live = mask_cnt > 0
                gq = jnp.where(live, gq, 0.0)
                hq = jnp.where(live, hq, 0.0)
                vals = jnp.stack([gq, hq, mask_cnt], axis=1)
            else:
                vals = jnp.stack([gm, hm, mask_cnt], axis=1)
            hist = multi_leaf_histogram_xla(
                bins_blk, vals, leaf_new.astype(jnp.int32), frontier,
                num_bins=num_bins, rows_per_block=rpb)
            return leaf_new, hist

        return sweep

    def _stats_core(self):
        """Per-block round statistics from device-resident state ONLY
        (no bins traffic): unmasked |g|/h maxima (quantization scales)
        and, under GOSS, the 65536-bucket |g*h| float-bit histogram the
        global threshold is read from."""
        objective = self.objective
        use_goss = self._use_goss

        def core(score_blk, label_blk, weight_blk, n_valid):
            ar = jnp.arange(score_blk.shape[0], dtype=jnp.int32)
            cnt = (ar < n_valid).astype(jnp.float32)
            g, h = objective.get_gradients(score_blk, label_blk,
                                           weight_blk)
            g = g.reshape(-1).astype(jnp.float32)
            h = h.reshape(-1).astype(jnp.float32)
            ga = jnp.abs(g) * cnt
            hv = h * cnt
            maxs = jnp.stack([jnp.max(ga), jnp.max(hv)])
            if use_goss:
                metric = jnp.abs(g * h) * cnt
                b = (jax.lax.bitcast_convert_type(metric, jnp.int32)
                     >> 15)
                counts = jnp.zeros(_GOSS_BUCKETS, jnp.int32).at[b].add(
                    (cnt > 0).astype(jnp.int32))
            else:
                counts = jnp.zeros(1, jnp.int32)
            return maxs, counts

        return core

    def _make_final(self):
        """Jitted final sweep: apply the last split table, add leaf
        outputs to the device-resident score, and (under GOSS/quant)
        fold next round's statistics out of the NEW score — the stats
        prepass rides the sweep that was already touching every
        block."""
        lr = self.lr
        track = self._track_stats
        core = self._stats_core() if track else None

        def final(bins_blk, score_blk, label_blk, weight_blk, n_valid,
                  leaf_blk, tbl, leaf_out):
            leaf_new = _apply_table(bins_blk, leaf_blk, tbl)
            score_new = score_blk + lr * leaf_out[
                jnp.clip(leaf_new.astype(jnp.int32), 0,
                         leaf_out.shape[0] - 1)]
            if track:
                maxs, counts = core(score_new, label_blk, weight_blk,
                                    n_valid)
            else:
                maxs = jnp.zeros(2, jnp.float32)
                counts = jnp.zeros(1, jnp.int32)
            return leaf_new, score_new, maxs, counts

        # donate ONLY the score slot (argnum 1): the leaf slot cannot
        # donate — at round start every block's slot points at the
        # SHARED per-rank zeros block, and donating it on block 0's
        # dispatch would delete the buffer blocks 1..n still pass
        fn = jax.jit(final,
                     donate_argnums=(1,) if self._donate else ())
        if self._donate and self.config.tpu_debug_checks:
            from ..utils.debug import donation_guard
            fn = donation_guard(fn, "the streamed final sweep's "
                                    "donated score slot")
        return fn

    def _pack13(self, r, p):
        return jnp.concatenate([
            jnp.stack([r["gain"], r["feature"].astype(jnp.float32),
                       r["threshold_bin"].astype(jnp.float32),
                       r["default_left"].astype(jnp.float32)]),
            r["left_sums"].astype(jnp.float32),
            r["right_sums"].astype(jnp.float32),
            p.astype(jnp.float32)])

    def _make_find(self):
        """Jitted per-level split search over the frontier (single-
        shard path). Everything the host loop needs comes back PACKED
        into one [K, 13] f32 array (gain, feature, threshold_bin,
        default_left, left_sums[3], right_sums[3], parent_sums[3]) —
        through the tunneled chip every separate device->host pull pays
        ~30-100 ms of latency, and the unpacked dict was ~20 pulls per
        level. ``allowed`` is a TRACED argument (same [F] bool shape
        every call) so per-tree feature_fraction masks never recompile;
        ``scale`` rescales quantized integer level sums to real units
        (ones — an exact multiply — when quantization is off). With
        ``extra_trees``, per-(leaf, feature) uniforms ride a traced
        argument (drawn host-side from ``self._rng`` per level —
        mirroring learner/serial.py's per-round draws), so the
        one-random-threshold-per-node semantics actually bind instead
        of silently degrading to plain GBDT (find_best_split skips the
        extra_trees filter when extra_u is None)."""
        use_extra = bool(self._scfg.extra_trees)
        nb, hn = self.feat_num_bin, self.feat_has_nan
        scfg = self._scfg
        pack = self._pack13

        def one(h, p, allowed, eu):
            r = find_best_split(h, p, nb, hn, allowed, scfg,
                                extra_u=eu if use_extra else None)
            return pack(r, p)

        @jax.jit
        def find(hist, allowed, eu, scale):
            # leaf totals from the RAW histogram (integer-exact under
            # quantization, so identical on every shard/feature), then
            # rescale totals and histogram to real units together
            parent = jnp.sum(hist[:, 0, :, :], axis=1) * scale
            h = hist * scale
            return jax.vmap(one, in_axes=(0, 0, None,
                                          0 if use_extra else None))(
                h, parent, allowed, eu)

        return find

    def _make_find_sharded(self):
        """The sharded per-level program: ONE histogram collective
        (psum, or psum_scatter + best-split election under
        tpu_hist_reduce=scatter) of the accumulated [K, F, B, 3] level
        histogram through the shared packed-int32 wire
        (learner/collective.py), then the same packed [K, 13] split
        search — replicated output, identical on every rank."""
        from ..learner.collective import hist_allreduce
        from ..parallel.mesh import P, shard_map
        axis = self._axis
        R = self.R
        F = self.num_features
        scatter = self._scatter
        F_s = F // R if scatter else F
        packed_wire = self._packed_wire
        use_extra = bool(self._scfg.extra_trees)
        nb_full, hn_full = self.feat_num_bin, self.feat_has_nan
        scfg = self._scfg
        pack = self._pack13

        def impl(hist_blk, allowed, eu, scale):
            h = hist_allreduce(hist_blk[0], axis, scatter=scatter,
                               scatter_dim=1, packed=packed_wire)
            # leaf totals straight from the RAW reduced histogram: any
            # one owned feature's bins partition the leaf's rows, and
            # summing BEFORE the channel rescale keeps the totals
            # integer-exact under quantization — every shard derives
            # the identical [K, 3] no matter which feature it owns
            # (scaled sums differ in ULPs between features, which
            # would leak shard-dependent leaf values through the
            # elected record's parent slot)
            parent = jnp.sum(h[:, 0, :, :], axis=1) * scale
            h = h * scale
            if scatter:
                off = (jax.lax.axis_index(axis) * F_s).astype(jnp.int32)
                nb = jax.lax.dynamic_slice_in_dim(nb_full, off, F_s)
                hn = jax.lax.dynamic_slice_in_dim(hn_full, off, F_s)
                al = jax.lax.dynamic_slice_in_dim(allowed, off, F_s)
                eu_s = (jax.lax.dynamic_slice_in_dim(eu, off, F_s,
                                                     axis=1)
                        if use_extra else eu)
            else:
                off = jnp.zeros((), jnp.int32)
                nb, hn, al, eu_s = nb_full, hn_full, allowed, eu

            def one(hk, pk, euk):
                r = find_best_split(hk, pk, nb, hn, al, scfg,
                                    extra_u=euk if use_extra else None)
                r = dict(r)
                r["feature"] = r["feature"] + off
                return pack(r, pk)

            packed13 = jax.vmap(one, in_axes=(0, 0,
                                              0 if use_extra else None))(
                h, parent, eu_s)
            if scatter:
                # SyncUpGlobalBestSplit across feature owners: a small
                # [R, K, 13] all_gather + per-leaf max-gain election
                allp = jax.lax.all_gather(packed13, axis)
                win = jnp.argmax(allp[..., 0], axis=0)
                packed13 = jnp.take_along_axis(
                    allp, win[None, :, None].astype(jnp.int32),
                    axis=0)[0]
            return packed13

        return jax.jit(shard_map(
            impl, mesh=self.mesh,
            in_specs=(P(axis), P(), P(), P()),
            out_specs=P(), check_vma=False))

    def _make_stats_reduce(self):
        """Small per-round collective: pmax of the |g|/h maxima + psum
        of the GOSS bucket histogram (the 'tiny guard psum' pattern the
        serial packed wire uses)."""
        from ..parallel.mesh import P, shard_map
        axis = self._axis

        def impl(maxs, counts):
            return (jax.lax.pmax(maxs[0], axis),
                    jax.lax.psum(counts[0], axis))

        return jax.jit(shard_map(
            impl, mesh=self.mesh, in_specs=(P(axis), P(axis)),
            out_specs=(P(), P()), check_vma=False))

    def _global_of(self, parts):
        """Assemble per-rank device arrays (each ``[1, ...]`` on its
        mesh device) into one mesh-sharded global array — zero-copy;
        the collective program reads its shard in place."""
        from jax.sharding import NamedSharding
        from ..parallel.mesh import P
        shape = (self.R,) + tuple(parts[0].shape[1:])
        return jax.make_array_from_single_device_arrays(
            shape, NamedSharding(self.mesh, P(self._axis)), parts)

    # ---------------------------------------------- per-round sampling
    @staticmethod
    def _salt32(seed_u32, tag: int, k: int) -> int:
        x = (int(seed_u32) ^ ((tag * 0x9E3779B9) & 0xFFFFFFFF)
             ^ ((int(k) * 0x85EBCA6B) & 0xFFFFFFFF)) & 0xFFFFFFFF
        return x

    def _collect_stats(self):
        """Reduce the pending per-rank round statistics (folded out of
        the previous final sweep, or computed by a standalone device-
        only prepass on round 0) into global (gmax, hmax, buckets)."""
        if self._pending_stats is None:
            pend = []
            for ri in range(len(self._ranks)):
                maxs = counts = None
                for b, lo, hi in self._rank_blocks(ri):
                    m, c = self._stats_fn(
                        self._score_dev[ri][b], self._label_dev[ri][b],
                        self._weight_dev[ri][b], np.int32(hi - lo))
                    maxs = m if maxs is None else jnp.maximum(maxs, m)
                    counts = c if counts is None else counts + c
                pend.append((maxs, counts))
            self._pending_stats = pend
        pend = self._pending_stats
        self._pending_stats = None     # consumed; the final sweep refills
        if self.R == 1:
            maxs = np.asarray(pend[0][0], np.float64)
            counts = np.asarray(pend[0][1], np.int64)
        else:
            m, c = self._stats_reduce(
                self._global_of([p[0][None] for p in pend]),
                self._global_of([p[1][None] for p in pend]))
            maxs = np.asarray(m, np.float64)
            counts = np.asarray(c, np.int64)
        return float(maxs[0]), float(maxs[1]), counts

    def _round_sampling(self):
        """Host-side per-round sampling/quantization scalars:
        ``sampf`` = [goss_thr, goss_p_pick, scale_g, scale_h] (f32),
        ``sampi`` = [bag_salt, goss_salt, sr_g_salt, sr_h_salt] (u32),
        plus the [3] channel rescale for split finding. Derived from
        GLOBAL statistics, so every rank computes identical values."""
        it = self.iter_
        sampf = np.zeros(4, np.float32)
        sampi = np.zeros(4, np.uint32)
        if self._use_bag:
            k = it // max(int(self.config.bagging_freq), 1)
            sampi[0] = self._salt32(self._bag_seed_u32, 0xBA66, k)
        if self._track_stats:
            gmax, hmax, counts = self._collect_stats()
            if self._use_goss:
                sampi[1] = self._salt32(self._seed_u32, 0x6055, it)
                total = int(counts.sum())
                k_top = max(1, int(total * self._top_rate))
                rev = np.cumsum(counts[::-1])
                j = min(int(np.searchsorted(rev, k_top)),
                        _GOSS_BUCKETS - 1)
                thr_bucket = (_GOSS_BUCKETS - 1) - j
                count_top = int(rev[j])
                sampf[0] = np.array([thr_bucket << 15],
                                    np.uint32).view(np.float32)[0]
                n_rest = max(total - count_top, 0)
                k_rand = int(total * self._other_rate)
                sampf[1] = (min(1.0, k_rand / n_rest)
                            if n_rest > 0 else 0.0)
            if self._use_quant:
                # unmasked maxima bound the masked values; GOSS
                # amplification widens the bound by (1-a)/b so levels
                # stay within +-glevels (a coarser grid than the
                # resident engine's masked max — documented)
                ampf = self._goss_amp if self._use_goss else 1.0
                sampf[2] = max(gmax * ampf, 1e-30) / self._glevels
                sampf[3] = max(hmax * ampf, 1e-30) / self._hlevels
                if self._use_sr:
                    sampi[2] = self._salt32(self._seed_u32, 0x56A1, it)
                    sampi[3] = self._salt32(self._seed_u32, 0x56A2, it)
        scale = (np.asarray([sampf[2], sampf[3], 1.0], np.float32)
                 if self._use_quant else np.ones(3, np.float32))
        return sampf, sampi, scale

    def _leaf_out_np(self, g: float, h: float) -> float:
        """calc_leaf_output (ops/split.py) in host numpy — leaf outputs
        are needed per split on the host path and a device round-trip
        each costs tunnel latency."""
        l1, l2 = self._scfg.lambda_l1, self._scfg.lambda_l2
        t = np.sign(g) * max(abs(g) - l1, 0.0) if l1 > 0.0 else g
        denom = h + l2
        out = -t / max(denom, 1e-30) if denom > 0.0 else 0.0
        md = self._scfg.max_delta_step
        if md > 0.0:
            out = float(np.clip(out, -md, md))
        return float(out)

    # ------------------------------------------------------------- API
    def can_fuse_iters(self) -> bool:
        return True

    def num_trees(self) -> int:
        return len(self.models)

    @property
    def current_iteration(self) -> int:
        return self.iter_

    def add_valid(self, data, name):
        """Valid sets evaluate via the host model over the RAW valid
        features (the streaming engine never bins or uploads them —
        a valid set large enough to matter should be subsampled).
        Multi-process gangs evaluate each process's LOCAL valid shard."""
        raw = getattr(data, "data", None)
        if raw is None or isinstance(raw, str):
            log.fatal(self._UNSUPPORTED_MSG.format(
                what="valid sets without in-memory raw features "
                     "(file-backed, or already constructed with the "
                     "raw matrix freed — pass a fresh Dataset)"))
        if not hasattr(raw, "shape"):
            # scipy sparse would also fail later (len() raises on
            # sparse, and the host-model traversal reads dense rows) —
            # reject anything non-array-like up front with the standard
            # message instead of crashing mid-eval
            log.fatal(self._UNSUPPORTED_MSG.format(
                what="valid sets whose raw features are not an array"))
        if hasattr(raw, "tocsr") and not isinstance(raw, np.ndarray):
            log.fatal(self._UNSUPPORTED_MSG.format(
                what="sparse raw valid features (densify with "
                     ".toarray() first)"))
        self.valid_data.append(data)
        self.valid_names.append(name)

    @property
    def valid_scores(self):
        log.fatal(self._UNSUPPORTED_MSG.format(
            what="custom feval over valid sets"))

    def eval_set(self, which: int):
        """(data_name, metric_name, value, higher_better) tuples —
        the resident engine's contract (GBDT.eval_set), via the shared
        metric helper so the two engines cannot drift.

        Training eval (which=-1) pulls the full device-resident score
        each call — 4 bytes/row of D2H; at 1e9-row scale through a
        slow pull path enable it sparingly (metric_freq). On a
        multi-process gang metrics cover this process's LOCAL rows,
        and rank 0's values are broadcast so early stopping cannot
        take rank-divergent decisions (a rank unwinding early would
        deadlock the others in the per-level collective)."""
        from ..metric import eval_metric_rows
        if which < 0:
            name = "training"
            raw = np.concatenate(
                [np.asarray(self._score_dev[ri][b])[:hi - lo]
                 for ri in range(len(self._ranks))
                 for b, lo, hi in self._rank_blocks(ri)])
            md = self.train_set.metadata
            label, weight, qb = md.label, md.weight, md.query_boundaries
        else:
            ds = self.valid_data[which]
            name = self.valid_names[which]
            # incremental raw cache: only the NEW trees since the last
            # eval traverse the valid matrix (the host model folds the
            # init score into tree 0, so increments sum exactly);
            # without this, per-iteration eval would rebuild and
            # re-traverse the whole forest — O(T^2) over training
            # shape[0], not len(): valid row count must not depend on
            # the raw container's __len__ (absent on scipy sparse)
            done, raw = self._valid_raw_cache.get(
                which, (0, np.zeros(int(ds.data.shape[0]), np.float64)))
            n_now = len(self.models)
            if n_now > done:
                raw = raw + self.predict(
                    ds.data, raw_score=True, start_iteration=done,
                    num_iteration=n_now - done)
                self._valid_raw_cache[which] = (n_now, raw)
            if ds.metadata.init_score is not None:
                # per-row valid init score (resident engine adds it in
                # _init_score_tile; the host model knows nothing of it)
                raw = raw + np.asarray(ds.metadata.init_score,
                                       np.float64)
            label = ds.metadata.label
            weight = ds.metadata.weight
            qb = ds.metadata.query_boundaries
        res = eval_metric_rows(self.objective, self.metrics, name,
                               raw, label, weight, qb, 1)
        if self.R > 1 and jax.process_count() > 1:
            # every rank must reach the SAME early-stop decision or the
            # survivors deadlock in the next per-level collective —
            # local-shard metrics diverge, so rank 0's values are
            # broadcast (one small allgather; the engine loop calls
            # eval_set in lockstep on every rank)
            from jax.experimental import multihost_utils
            vals = np.asarray([v for (_, _, v, _) in res], np.float64)
            g = np.asarray(
                multihost_utils.process_allgather(vals)).reshape(
                    jax.process_count(), -1)
            res = [(nm, mt, float(v0), hb)
                   for (nm, mt, _, hb), v0 in zip(res, g[0])]
        return res

    def rollback_one_iter(self):
        log.fatal(self._UNSUPPORTED_MSG.format(what="rollback"))

    def train_chunk(self, k: int):
        from .. import obs
        for _ in range(k):
            self.train_one_iter()
            # liveness on the fused (no-callback) path: the engine.py
            # round loop is bypassed here, so the watchdog's heartbeat
            # must ride the chunk loop itself (gbdt.train_chunk stamps
            # the same way)
            obs.heartbeat("train")

    # -------------------------------------------------------- training
    def _pad_block(self, arr, lo, hi, fill=0):
        out = arr[lo:hi]
        if hi - lo < self.block_rows:
            pad = np.full((self.block_rows - (hi - lo),) + out.shape[1:],
                          fill, dtype=out.dtype)
            out = np.concatenate([out, pad])
        return out

    def _empty_table(self) -> Dict[str, np.ndarray]:
        z = np.zeros(1, np.int32)
        return {"leaf": z - 1, "feat": z, "thr": z, "dl": z,
                "new_leaf": z, "nb": z, "hn": z}

    # --------------------------------------------- block upload staging
    def _block_schedule(self):
        """The step-major ``(ri, b, lo, hi)`` dispatch order EVERY
        streamed sweep iterates (level sweeps, the final sweep, the
        next round's sweeps — identical by construction), flattened
        for the cyclic upload prefetcher."""
        iters = [list(self._rank_blocks(ri))
                 for ri in range(len(self._ranks))]
        seq = []
        for step in range(max(len(it) for it in iters)):
            for ri in range(len(iters)):
                if step < len(iters[ri]):
                    b, lo, hi = iters[ri][step]
                    seq.append((ri, b, lo, hi))
        return seq

    def _stage_bins(self, item):
        """Stage one block's bins on its rank's device. Runs on the
        prefetch worker thread under overlap: slice + pad + device_put
        ONLY — never a collective (utils/prefetch.py's threading
        contract; the collective-safety checker pins it)."""
        ri, _b, lo, hi = item
        return self._put(self._pad_block(self.binned, lo, hi),
                         self._ranks[ri]["dev"])

    def _next_bins(self, ri, b, lo, hi):
        """The next scheduled block's padded bins upload: staged one
        step ahead on the worker thread under overlap (the host
        slices/pads/wires block i+1 while the device sweeps block i),
        staged inline — the historic order — when overlap is off."""
        if self._feed is None:
            self._feed = BlockPrefetcher(
                self._stage_bins, self._block_schedule(),
                threaded=self._overlap)
        return self._feed.take(expect=(ri, b, lo, hi))

    def _drain_inflight(self) -> None:
        """Complete every pending streamed dispatch: host-block on the
        in-flight sweep outputs and free their bins uploads. The PR 13
        checkpoint contract — ``export_train_state`` must only ever
        see fully materialized score slots — and the synchronous-mode
        sweep barrier both land here."""
        for win in self._inflight:
            win.drain()

    def _level_hists(self, table, frontier_np, sampf, sampi):
        """One streamed pass over every local rank's blocks: apply the
        pending split table, accumulate each rank's [K, F, B, 3] level
        histogram across its blocks — NO collective here; the single
        per-level reduction happens in the find program."""
        from .. import obs
        n_ranks = len(self._ranks)
        tbl_dev, frontier_dev, sampf_dev, sampi_dev = [], [], [], []
        for rk in self._ranks:
            dev = rk["dev"]
            frontier_dev.append(self._put(frontier_np, dev))
            tbl_dev.append({k: self._put(v, dev)
                            for k, v in table.items()})
            sampf_dev.append(self._put(sampf, dev))
            sampi_dev.append(self._put(sampi, dev))
        hists = [None] * n_ranks
        iters = [list(self._rank_blocks(ri)) for ri in range(n_ranks)]
        blocks = 0
        # BLOCK-STEP-MAJOR over the ranks: dispatch step s for every
        # rank before host-blocking on any rank's step s-1, so all
        # local devices compute concurrently (rank-major order would
        # serialize the devices to ~1/R utilization single-process);
        # each rank still accumulates ITS blocks in order, so the
        # partial sums are unchanged bit for bit.
        for step in range(max(len(it) for it in iters)):
            for ri, rk in enumerate(self._ranks):
                if step >= len(iters[ri]):
                    continue
                b, lo, hi = iters[ri][step]
                bins_blk = self._next_bins(ri, b, lo, hi)
                off = np.int32(rk["goff"] + (lo - rk["lo"]))
                leaf_new, h_blk = self._sweep(
                    bins_blk, self._score_dev[ri][b],
                    self._label_dev[ri][b], self._weight_dev[ri][b],
                    np.int32(hi - lo), self._leaf_dev[ri][b],
                    tbl_dev[ri], frontier_dev[ri], off, sampf_dev[ri],
                    sampi_dev[ri])
                self._leaf_dev[ri][b] = leaf_new    # stays on device
                hists[ri] = (h_blk if hists[ri] is None
                             else hists[ri] + h_blk)
                blocks += 1
                # throttle + free with the per-rank 2-block in-flight
                # window: unthrottled async dispatch would enqueue
                # EVERY block's ~256 MB device buffer before the
                # device drains one — at 128 blocks that is ~34 GB of
                # live transients and an OOM (observed at the 32 GiB
                # proof shape). Blocking on the rank's PREVIOUS block
                # keeps upload of block s+1 overlapped with compute of
                # block s while bounding transients to ~512 MB/rank.
                self._inflight[ri].push((bins_blk, hists[ri]))
        if not self._overlap:
            # synchronous mode: the historic pre-reduce barrier. Under
            # overlap the tail items stay pending — the find program's
            # own result pull waits on them through data dependencies,
            # so the collective dispatches WITHOUT a host sync and the
            # leftover bins uploads are freed by the next sweep's
            # pushes (<= depth block buffers per rank carry over).
            self._drain_inflight()
        self.comm_stats["blocks_scanned"] += blocks
        if obs.enabled():
            obs.inc("stream.blocks_scanned", blocks)
        return hists

    def _find_level(self, hists, allowed_dev, eu, scale):
        """The ONE per-level collective + split search: returns the
        packed [K_pad, 13] host array (identical on every rank).

        Under ``tpu_stream_overlap`` this is called with the level's
        tail sweeps still in flight: the collective program dispatches
        immediately (async, ordered behind the accumulations by data
        dependency) and the host blocks only on the packed result
        pull — the reduce overlaps the tail sweeps and the next
        blocks' staging instead of waiting for a host-side barrier."""
        from .. import obs
        self.comm_stats["levels"] += 1
        if self.R == 1:
            return np.asarray(self._find(hists[0], allowed_dev, eu,
                                         scale), np.float64)
        t0 = time.perf_counter()
        hist_g = self._global_of([h[None] for h in hists])
        bests = np.asarray(self._find_sharded(hist_g, allowed_dev, eu,
                                              scale), np.float64)
        dt_ms = (time.perf_counter() - t0) * 1e3
        K_pad = int(hists[0].shape[0])
        payload = K_pad * self.num_features * self.B * 4 \
            * (2 if self._packed_wire else 3)
        self.comm_stats["allreduce_calls"] += 1
        self.comm_stats["allreduce_bytes"] += payload
        if obs.enabled():
            obs.inc("comm.allreduce_calls")
            obs.inc("comm.allreduce_bytes", payload)
            obs.observe("comm.allreduce_ms", dt_ms)
        return bests

    def train_one_iter(self) -> None:
        L = int(self.config.num_leaves)
        max_depth = int(self.config.max_depth)
        F = self.num_features

        allowed = np.ones(F, bool)
        if self._ff < 1.0:
            k = max(1, int(F * self._ff))
            allowed[:] = False
            allowed[self._rng.choice(F, size=k, replace=False)] = True
        allowed_dev = jnp.asarray(allowed)
        sampf, sampi, scale = self._round_sampling()
        scale_dev = jnp.asarray(scale)

        for ri in range(len(self._ranks)):
            for b in range(self._ranks[ri]["n_blocks"]):
                self._leaf_dev[ri][b] = self._zeros_leaf[ri]
        nl = 1
        nn = 0
        # per-node host arrays (grown as splits land)
        sf, tb, dl, lc, rc, gains, ivals, icnts = \
            [], [], [], [], [], [], [], []
        leaf_parent_slot: Dict[int, tuple] = {}   # leaf -> (node, side)
        leaf_sums = np.zeros((L, 3), np.float64)
        frontier = [0]
        table = self._empty_table()
        depth = 0

        while frontier:
            K = len(frontier)
            # pad the frontier (and split table below) to powers of two:
            # -1 sentinel leaves match no rows, so the padding costs a
            # slice of wasted histogram width but caps the number of
            # distinct jit specializations at log2(L) — without it every
            # pruned-frontier shape recompiles (~30 s each on the
            # tunneled chip, dwarfing the sweep itself)
            K_pad = 1 << max(0, (K - 1)).bit_length()
            frontier_np = np.asarray(frontier + [-1] * (K_pad - K),
                                     np.int32)
            hists = self._level_hists(table, frontier_np, sampf, sampi)
            # per-level extra_trees uniforms (one random threshold per
            # (leaf, feature)); None when off — drawn from the shared
            # host rng, so every rank draws the same field
            eu = (jnp.asarray(self._rng.random((K_pad, F)), jnp.float32)
                  if self._scfg.extra_trees
                  else np.zeros((1, 1), np.float32))
            # ONE device->host pull per level (packed [K_pad, 13]),
            # and — sharded — ONE histogram collective per level
            bests = self._find_level(hists, allowed_dev, eu, scale_dev)
            for i, lf in enumerate(frontier):
                leaf_sums[lf] = bests[i, 10:13]
            table = self._empty_table()
            depth += 1
            if nl >= L or (0 < max_depth <= depth - 1):
                frontier = []
                break
            gains_k = bests[:K, 0]                   # drop pad lanes
            order = np.argsort(-gains_k)             # best-first within
            budget = L - nl                          # the level
            chosen = [i for i in order[:budget]
                      if np.isfinite(gains_k[i]) and gains_k[i] > -1e37]
            if not chosen:
                frontier = []
                break
            tl, tf, tt, tdl, tnew, tnb, thn = [], [], [], [], [], [], []
            new_frontier = []
            for i in chosen:
                lf = frontier[i]
                feat = int(bests[i, 1])
                node = nn
                nn += 1
                right_leaf = nl
                nl += 1
                if lf in leaf_parent_slot:
                    pn, side = leaf_parent_slot.pop(lf)
                    (lc if side == 0 else rc)[pn] = node
                sf.append(feat)
                tb.append(int(bests[i, 2]))
                dl.append(bool(bests[i, 3] > 0.5))
                lc.append(~lf)
                rc.append(~right_leaf)
                gains.append(float(bests[i, 0]))
                ivals.append(self._leaf_out_np(leaf_sums[lf][0],
                                               leaf_sums[lf][1]))
                icnts.append(int(round(leaf_sums[lf][2])))
                leaf_parent_slot[lf] = (node, 0)
                leaf_parent_slot[right_leaf] = (node, 1)
                leaf_sums[lf] = bests[i, 4:7]
                leaf_sums[right_leaf] = bests[i, 7:10]
                tl.append(lf)
                tf.append(feat)
                tt.append(int(bests[i, 2]))
                tdl.append(int(bests[i, 3] > 0.5))
                tnew.append(right_leaf)
                tnb.append(int(self._num_bin_np[feat]))
                thn.append(int(self._has_nan_np[feat]))
                new_frontier.extend([lf, right_leaf])
            S = len(tl)
            S_pad = 1 << max(0, (S - 1)).bit_length()
            pad = [0] * (S_pad - S)
            table = {"leaf": np.asarray(tl + [-1] * (S_pad - S), np.int32),
                     "feat": np.asarray(tf + pad, np.int32),
                     "thr": np.asarray(tt + pad, np.int32),
                     "dl": np.asarray(tdl + pad, np.int32),
                     "new_leaf": np.asarray(tnew + pad, np.int32),
                     "nb": np.asarray(tnb + pad, np.int32),
                     "hn": np.asarray(thn + pad, np.int32)}
            frontier = new_frontier if nl < L and not (
                0 < max_depth <= depth) else []
            if not frontier:
                break

        # ---- final sweep: last split table + score update ------------
        leaf_out = np.zeros(max(nl, 1), np.float32)
        for lf in range(nl):
            leaf_out[lf] = self._leaf_out_np(leaf_sums[lf][0],
                                             leaf_sums[lf][1])
        from .. import obs
        n_ranks = len(self._ranks)
        tbl_dev, leaf_out_dev = [], []
        for rk in self._ranks:
            tbl_dev.append({k: self._put(v, rk["dev"])
                            for k, v in table.items()})
            leaf_out_dev.append(self._put(leaf_out, rk["dev"]))
        maxs = [None] * n_ranks
        counts = [None] * n_ranks
        iters = [list(self._rank_blocks(ri)) for ri in range(n_ranks)]
        blocks = 0
        # block-step-major like _level_hists: keep every local device
        # busy while the per-rank 2-block window bounds transients
        for step in range(max(len(it) for it in iters)):
            for ri, rk in enumerate(self._ranks):
                if step >= len(iters[ri]):
                    continue
                b, lo, hi = iters[ri][step]
                bins_blk = self._next_bins(ri, b, lo, hi)
                leaf_new, score_new, m_blk, c_blk = self._final(
                    bins_blk, self._score_dev[ri][b],
                    self._label_dev[ri][b], self._weight_dev[ri][b],
                    np.int32(hi - lo), self._leaf_dev[ri][b],
                    tbl_dev[ri], leaf_out_dev[ri])
                self._leaf_dev[ri][b] = leaf_new
                self._score_dev[ri][b] = score_new
                blocks += 1
                if self._track_stats:
                    # next round's statistics fold out of this sweep
                    # (gradients of the NEW score) — no extra pass
                    maxs[ri] = (m_blk if maxs[ri] is None
                                else jnp.maximum(maxs[ri], m_blk))
                    counts[ri] = (c_blk if counts[ri] is None
                                  else counts[ri] + c_blk)
                self._inflight[ri].push((bins_blk, score_new))
        if not self._overlap:
            # synchronous mode: complete the round before returning.
            # Under overlap the final sweep's tail DEFERS — the next
            # round's first level-sweep pushes complete it (its sweeps
            # read score_new, so device data dependencies order the
            # two rounds; the host never stalls between them). The
            # next reader either blocks through a data dependency
            # (eval_set / _collect_stats pulls) or drains explicitly
            # (export_train_state — the PR 13 checkpoint contract).
            # Note GOSS/quantized configs host-block at the next
            # round's _collect_stats anyway (the sampling scalars need
            # the folded stats), which bounds how much of the final
            # sweep those configs can actually hide.
            self._drain_inflight()
        self.comm_stats["blocks_scanned"] += blocks
        if obs.enabled():
            obs.inc("stream.blocks_scanned", blocks)
        if self._track_stats:
            self._pending_stats = list(zip(maxs, counts))

        tree_arrays = {
            "num_leaves": nl,
            "split_feature": np.asarray(sf, np.int32),
            "threshold_bin": np.asarray(tb, np.int32),
            "default_left": np.asarray(dl, bool),
            "left_child": np.asarray(lc, np.int32),
            "right_child": np.asarray(rc, np.int32),
            "split_gain": np.asarray(gains, np.float32),
            "internal_value": np.asarray(ivals, np.float32),
            "internal_count": np.asarray(icnts, np.int64),
            "leaf_value": leaf_out[:nl].astype(np.float64),
            "leaf_count": leaf_sums[:nl, 2].round().astype(np.int64),
            "leaf_weight": leaf_sums[:nl, 1].astype(np.float64),
        }
        self.models.append(Tree.from_device(
            tree_arrays, self.lr, self.train_set.bin_mappers,
            list(self.train_set.used_features)))
        self.iter_ += 1

    # ------------------------------------------ checkpoint / resume
    # The streamed engine is the one training path where preemption is
    # the NORM (out-of-core runs are the longest runs), so it carries
    # the same durable-checkpoint contract as the resident engine:
    # export everything that evolves across rounds, and a resumed run
    # is bit-exact vs an uninterrupted one BY CONSTRUCTION — the
    # bagging/GOSS/stochastic-rounding draws are counter-hashes of the
    # GLOBAL row index + per-round salts derived from (seed, iter), so
    # they need no saved state; what must travel is the device-resident
    # scores, the host RNG (feature_fraction / extra_trees draws), the
    # pending next-round statistics the last final sweep folded out
    # (saving them beats recomputing: a standalone stats prepass could
    # fuse differently under XLA than the folded one), and the shard/
    # block layout the scores are cut by.
    def _layout_fingerprint(self) -> Dict:
        return {
            "R": int(self.R),
            "n": int(self.n),
            "n_global": int(self.n_global),
            "block_rows": int(self.block_rows),
            "ranks": [(int(rk["pos"]), int(rk["lo"]), int(rk["hi"]),
                       int(rk["goff"]), int(rk["n_blocks"]))
                      for rk in self._ranks],
        }

    def export_train_state(self) -> Dict:
        # the PR 13 contract under tpu_stream_overlap: a deferred
        # final sweep may still be in flight at a round boundary —
        # drain it (block on the sweep outputs, free the uploads) so
        # the np.asarray score pulls below export fully materialized
        # slots, never a snapshot raced against pending updates
        self._drain_inflight()
        state = {
            "engine": type(self).__name__,
            "iteration": int(self.iter_),
            # exact pickled trees (model TEXT rounds values through
            # "{:g}" — not bit-exact), same as the resident engine
            "models": list(self.models),
            "process_index": int(jax.process_index()),
            "process_count": int(jax.process_count()),
            "init_scores": self.init_scores.copy(),
            "rng": self._rng.bit_generator.state,
            "layout": self._layout_fingerprint(),
            # the device-resident per-(rank, block) score slots — THE
            # accumulated floats a resumed run must continue from
            # (padded to block_rows; the pad lanes are inert)
            "scores": [[np.asarray(s) for s in per_rank]
                       for per_rank in self._score_dev],
            # next round's GOSS/quantization statistics, folded out of
            # the final sweep that just ran (None when untracked or
            # already consumed — a standalone prepass recomputes then)
            "pending_stats": (
                None if self._pending_stats is None else
                [(np.asarray(m), np.asarray(c))
                 for (m, c) in self._pending_stats]),
            # incremental valid-set raw caches (host f64 accumulators;
            # rebuilding them from scratch re-sums trees in a different
            # association order — not bit-identical)
            "valid_raw_cache": {int(k): (int(done), raw.copy())
                                for k, (done, raw)
                                in self._valid_raw_cache.items()},
        }
        return state

    def import_train_state(self, state: Dict) -> bool:
        """Adopt :meth:`export_train_state` output into a freshly
        constructed engine. The checkpoint is TOPOLOGY-FREE: when the
        live shard/block layout matches the saved fingerprint the
        exact score slots are adopted as-is, and when it differs (a
        resumed fleet at R′ ≠ R ranks, a changed block size, a
        narrower gang after a degrade) the per-(rank, block) score
        slots are RE-CUT — reassembled by global row index from the
        saved slots (reading sibling ranks' checkpoint files when the
        rows span old processes), or recomputed from the pickled trees
        for any rows no saved slot covers (a bit-exact device replay
        of the final sweeps' score arithmetic). Eligibility for the
        re-cut is a capability-table verdict
        (``capabilities.stream_recut_verdict``): bit-exact under
        quantized gradients, opt-in (``tpu_elastic_recut=true``) on
        the exact-f32 path, and a hard error naming what moved for
        genuinely incompatible state (different data, engine, or tree
        count). Returns True."""
        # a fresh engine's windows are empty, but adopting state into
        # a live one must not leave stale sweeps pending against the
        # slots being replaced
        self._drain_inflight()
        saved_engine = state.get("engine")
        if saved_engine is not None \
                and saved_engine != type(self).__name__:
            log.fatal(
                f"checkpoint was written by a {saved_engine} engine but "
                f"resume constructed {type(self).__name__} — the "
                f"boosting/tree_learner/tpu_streaming params must match "
                f"the original run")
        models = state.get("models")
        if models is None:
            log.fatal("checkpoint state holds no model trees — corrupt "
                      "or incompatible checkpoint")
        self.models = list(models)
        self._models_version += 1
        self.iter_ = int(state["iteration"])
        if len(self.models) != self.iter_:
            log.fatal(
                f"checkpoint state is for iteration "
                f"{state['iteration']} but holds {len(self.models)} "
                f"trees — mismatched checkpoint contents")
        if state.get("init_scores") is not None:
            self.init_scores = np.asarray(state["init_scores"],
                                          np.float64)
        self._rng.bit_generator.state = state["rng"]
        saved_layout = state.get("layout") or {}
        layout = self._layout_fingerprint()
        same_process = (
            int(state.get("process_count", 1)) == jax.process_count()
            and int(state.get("process_index", 0))
            == jax.process_index())
        if saved_layout == layout and same_process \
                and state.get("scores") is not None:
            # fast path: identical topology — adopt the exact slots
            scores = state["scores"]
            for ri, rk in enumerate(self._ranks):
                for b in range(rk["n_blocks"]):
                    self._score_dev[ri][b] = self._put(
                        np.asarray(scores[ri][b], np.float32),
                        rk["dev"])
            pend = state.get("pending_stats")
            if pend is not None and self._track_stats:
                self._pending_stats = [
                    (self._put(np.asarray(m, np.float32), rk["dev"]),
                     self._put(np.asarray(c, np.int32), rk["dev"]))
                    for (m, c), rk in zip(pend, self._ranks)]
            else:
                self._pending_stats = None
        else:
            self._import_recut(state, saved_layout, layout)
        for ri, rk in enumerate(self._ranks):
            # leaf slots are per-tree transients (reset at every round
            # start); point them back at the shared zero block
            for b in range(rk["n_blocks"]):
                self._leaf_dev[ri][b] = self._zeros_leaf[ri]
        self._valid_raw_cache = {
            int(k): (int(done), np.asarray(raw, np.float64))
            for k, (done, raw)
            in (state.get("valid_raw_cache") or {}).items()}
        self._hm_cache = (None, None)
        return True

    # ------------------------------------------- elastic re-cut (resume)
    def _import_recut(self, state: Dict, saved_layout: Dict,
                      layout: Dict) -> None:
        """Re-cut a checkpoint written under a DIFFERENT shard/block
        layout onto the live one. Streamed score slots are a
        deterministic function of trees × global rows, so the slots
        reassemble by global row index from whatever saved slots are
        reachable (this state's own, plus sibling old-rank checkpoint
        files) and any uncovered rows replay from the pickled trees —
        both bit-exact reconstructions of the per-row floats. Pending
        GOSS/quant round statistics re-reduce exactly (max / integer
        sum are grouping-invariant); when incomplete they are dropped
        and the round-0-style standalone prepass recomputes them."""
        from .. import capabilities, obs
        if not saved_layout:
            log.fatal("streamed checkpoint carries no shard/block "
                      "layout fingerprint — corrupt or incompatible "
                      "checkpoint")
        saved_nglobal = int(saved_layout.get("n_global", -1))
        if saved_nglobal != self.n_global:
            log.fatal(
                f"streamed resume cannot re-cut this checkpoint: the "
                f"GLOBAL row count moved ({saved_nglobal} saved, "
                f"{self.n_global} now) — scores are per-row state, so "
                f"a changed dataset is genuinely incompatible (elastic "
                f"resume re-cuts the same rows across a different "
                f"shard/block topology only)")
        if saved_layout != layout \
                or int(state.get("process_count", 1)) \
                != jax.process_count():
            # a REAL topology change: the re-cut continuation's
            # bit-equality is a capability-table verdict. (Same-layout
            # states that merely lack score slots skip this — the tree
            # replay below is bit-exact for any numerics.)
            diff = sorted(set(
                [k for k in layout
                 if saved_layout.get(k) != layout.get(k)]
                + ([] if int(state.get("process_count", 1))
                   == jax.process_count() else ["process_count"])))
            moved = ", ".join(
                f"{k}: {saved_layout.get(k)!r} -> {layout.get(k)!r}"
                for k in diff if k not in ("ranks", "process_count")
            ) or f"process topology ({state.get('process_count')} -> " \
                f"{jax.process_count()} rank(s))"
            verdict, why = capabilities.stream_recut_verdict(
                self.config)
            if verdict == capabilities.FATAL:
                log.fatal(
                    f"streamed resume found a changed shard/block "
                    f"layout ({moved}) and refused to re-cut: {why}")
            elif verdict == capabilities.DEMOTE:
                log.warning(f"streamed resume re-cutting a changed "
                            f"shard/block layout ({moved}): {why}")
            else:
                log.info(f"streamed resume re-cutting a changed "
                         f"shard/block layout ({moved}): {why}")
            obs.inc("train.topology_changes", force=True)
        else:
            log.warning("streamed resume: checkpoint layout matches "
                        "but carries no score slots; recomputing them "
                        "from the pickled trees")

        # ---- gather every reachable saved slot by GLOBAL row --------
        glob = np.zeros(self.n_global, np.float32)
        cov = np.zeros(self.n_global, bool)
        pend_by_pos: Dict[int, tuple] = {}
        for eng_state in [state] + self._peer_states(state):
            lay = eng_state.get("layout") or {}
            scores = eng_state.get("scores")
            pend = eng_state.get("pending_stats")
            sb = int(lay.get("block_rows", 0) or 0)
            for ri, rk in enumerate(lay.get("ranks") or []):
                pos, lo, hi, goff = (int(rk[0]), int(rk[1]),
                                     int(rk[2]), int(rk[3]))
                rows = hi - lo
                if scores is not None and sb > 0 \
                        and ri < len(scores):
                    for b, blk in enumerate(scores[ri]):
                        blo = b * sb
                        take = min(sb, rows - blo)
                        if take <= 0:
                            continue
                        s = np.asarray(blk, np.float32)
                        glob[goff + blo:goff + blo + take] = s[:take]
                        cov[goff + blo:goff + blo + take] = True
                if pend is not None and ri < len(pend):
                    pend_by_pos[pos] = pend[ri]

        # ---- fill the live slots (reshard; replay uncovered) --------
        init = np.float32(self.init_scores[0])
        replay_blocks = []
        for ri, rk in enumerate(self._ranks):
            for b, lo, hi in self._rank_blocks(ri):
                g0 = rk["goff"] + (lo - rk["lo"])
                if not cov[g0:g0 + (hi - lo)].all():
                    replay_blocks.append((ri, b, lo, hi))
                    continue
                slot = np.full(self.block_rows, init, np.float32)
                slot[:hi - lo] = glob[g0:g0 + (hi - lo)]
                self._score_dev[ri][b] = self._put(slot, rk["dev"])
        if replay_blocks:
            log.warning(
                f"elastic resume: {len(replay_blocks)} streamed score "
                f"block(s) had no reachable saved slot (missing or "
                f"unreadable old-rank checkpoint file); recomputing "
                f"them from the {len(self.models)} pickled trees — a "
                f"bit-exact device replay of the final-sweep score "
                f"arithmetic")
            self._replay_score_blocks(replay_blocks)

        # ---- pending round statistics -------------------------------
        R_saved = int(saved_layout.get("R", 1))
        if self._track_stats and pend_by_pos \
                and len(pend_by_pos) == R_saved:
            # grouping-invariant re-reduction: elementwise MAX of the
            # per-old-rank maxima, integer SUM of the bucket counts —
            # handed to mesh position 0 with zero-contributions
            # elsewhere, so the live pmax/psum reproduce the exact
            # global values the old topology would have reduced to
            maxs = np.max(np.stack(
                [np.asarray(m, np.float32)
                 for m, _c in pend_by_pos.values()]), axis=0)
            counts = np.sum(np.stack(
                [np.asarray(c, np.int64)
                 for _m, c in pend_by_pos.values()]),
                axis=0).astype(np.int32)
            self._pending_stats = [
                ((self._put(maxs, rk["dev"]),
                  self._put(counts, rk["dev"]))
                 if rk["pos"] == 0 else
                 (self._put(np.zeros_like(maxs), rk["dev"]),
                  self._put(np.zeros_like(counts), rk["dev"])))
                for rk in self._ranks]
        else:
            if self._track_stats and pend_by_pos:
                log.warning(
                    f"elastic resume: pending round statistics "
                    f"reachable for {len(pend_by_pos)} of {R_saved} "
                    f"old rank(s); dropping them — the standalone "
                    f"device prepass recomputes the same "
                    f"grouping-invariant maxima/counts at round start")
            self._pending_stats = None

    def _peer_states(self, state: Dict) -> List[Dict]:
        """Sibling OLD processes' engine states at this iteration,
        read from the shared checkpoint directory (multi-process
        elastic resume: a new rank's rows can span several old ranks'
        per-process score shards). Unreachable or incompatible peer
        files are skipped with a warning — their rows fall back to the
        tree replay."""
        P = int(state.get("process_count", 1))
        me = int(state.get("process_index", 0))
        d = str(state.get("_checkpoint_dir") or "")
        if P <= 1 or not d:
            return []
        from ..recovery.checkpoint import (CheckpointError,
                                           CheckpointManager)
        out = []
        for q in range(P):
            if q == me:
                continue
            try:
                st = CheckpointManager(d, rank=q).load(
                    iteration=self.iter_)
            except CheckpointError as e:
                log.warning(
                    f"elastic resume: old rank {q}'s checkpoint at "
                    f"iteration {self.iter_} is unreadable ({e}); its "
                    f"rows will be recomputed from the pickled trees")
                continue
            eng = (st or {}).get("engine") or {}
            lay = eng.get("layout") or {}
            if eng.get("engine") != type(self).__name__ \
                    or int(eng.get("iteration", -1)) != self.iter_ \
                    or int(lay.get("n_global", -1)) != self.n_global:
                log.warning(
                    f"elastic resume: old rank {q}'s checkpoint at "
                    f"iteration {self.iter_} is incompatible (engine/"
                    f"iteration/row-count mismatch); skipping it")
                continue
            out.append(eng)
        return out

    def _replay_fns(self):
        """Jitted tree-replay pieces mirroring the final sweep's score
        arithmetic EXACTLY (the same ``_apply_table`` routing, the
        same one ``lr * leaf_out[leaf]`` f32 add per tree) — what
        makes the recompute path a bit-exact reconstruction of the
        saved slots rather than a close one."""
        cached = getattr(self, "_replay_cache", None)
        if cached is not None:
            return cached
        lr = self.lr

        @jax.jit
        def apply_j(bins_blk, leaf_blk, tbl):
            return _apply_table(bins_blk, leaf_blk, tbl)

        @jax.jit
        def add_j(score_blk, leaf_blk, leaf_out):
            return score_blk + lr * leaf_out[
                jnp.clip(leaf_blk.astype(jnp.int32), 0,
                         leaf_out.shape[0] - 1)]

        self._replay_cache = (apply_j, add_j)
        return self._replay_cache

    def _tree_tables(self, tree) -> List[Dict[str, np.ndarray]]:
        """Reconstruct a pickled tree's per-level split tables — the
        exact shape ``train_one_iter`` fed ``_apply_table``. The
        construction invariants make this derivable from child
        topology alone: node j's right branch minted leaf j+1, its
        left branch kept the split leaf's id, and a leaf splits only
        at its own depth (an unchosen frontier leaf never re-enters
        the frontier)."""
        nn = int(tree.num_leaves) - 1
        if nn <= 0:
            return []
        leaf_of = np.zeros(nn, np.int32)
        depth_of = np.zeros(nn, np.int32)
        for i in range(nn):
            for side, child in ((0, int(tree.left_child[i])),
                                (1, int(tree.right_child[i]))):
                if child >= 0:
                    leaf_of[child] = leaf_of[i] if side == 0 \
                        else np.int32(i + 1)
                    depth_of[child] = depth_of[i] + 1
        tables = []
        for d in range(int(depth_of.max()) + 1):
            idx = np.flatnonzero(depth_of == d).astype(np.int32)
            S = len(idx)
            S_pad = 1 << max(0, (S - 1)).bit_length()
            zpad = np.zeros(S_pad - S, np.int32)
            feats = np.asarray(tree.split_feature)[idx].astype(np.int32)
            tables.append({
                "leaf": np.concatenate(
                    [leaf_of[idx], np.full(S_pad - S, -1, np.int32)]),
                "feat": np.concatenate([feats, zpad]),
                "thr": np.concatenate(
                    [np.asarray(tree.threshold_bin)[idx]
                     .astype(np.int32), zpad]),
                "dl": np.concatenate(
                    [np.asarray(tree.default_left)[idx]
                     .astype(np.int32), zpad]),
                "new_leaf": np.concatenate(
                    [(idx + 1).astype(np.int32), zpad]),
                "nb": np.concatenate([self._num_bin_np[feats], zpad]),
                "hn": np.concatenate(
                    [self._has_nan_np[feats].astype(np.int32), zpad]),
            })
        return tables

    def _replay_score_blocks(self, replay_blocks) -> None:
        """Recompute ``(ri, b, lo, hi)`` score slots from the pickled
        trees: route every tree's per-level split tables over the
        block's bins, add its ``lr * leaf_out`` — the identical f32
        accumulation order training ran, so the result is bit-equal to
        the slot the lost checkpoint held."""
        apply_j, add_j = self._replay_fns()
        init = np.float32(self.init_scores[0])
        prog = [(self._tree_tables(t),
                 (np.asarray(t.leaf_value, np.float64)
                  / self.lr).astype(np.float32))
                for t in self.models]
        dev_cache: Dict[int, list] = {}
        for ri, b, lo, hi in replay_blocks:
            rk = self._ranks[ri]
            if ri not in dev_cache:
                dev_cache[ri] = [
                    ([{k: self._put(v, rk["dev"])
                       for k, v in tbl.items()} for tbl in tables],
                     self._put(lo_np, rk["dev"]))
                    for tables, lo_np in prog]
            bins_blk = self._put(
                self._pad_block(self.binned, lo, hi), rk["dev"])
            score = self._put(
                np.full(self.block_rows, init, np.float32), rk["dev"])
            for tables_dev, leaf_out_dev in dev_cache[ri]:
                leaf = self._zeros_leaf[ri]
                for tbl_dev in tables_dev:
                    leaf = apply_j(bins_blk, leaf, tbl_dev)
                score = add_j(score, leaf, leaf_out_dev)
            jax.block_until_ready(score)
            bins_blk.delete()
            self._score_dev[ri][b] = score

    # ------------------------------------------------------- predict
    def predict(self, X, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, **_overrides) -> np.ndarray:
        # _overrides: tpu_predict_* serving knobs (resident-engine
        # traversal only; the host-model path here ignores them)
        from ..io.model_text import HostModel
        cache = getattr(self, "_hm_cache", (None, None))
        if cache[0] != len(self.models):
            cache = (len(self.models),
                     HostModel.from_engine(self, self.config))
            self._hm_cache = cache
        return cache[1].predict(X, raw_score=raw_score,
                                start_iteration=start_iteration,
                                num_iteration=num_iteration,
                                pred_leaf=pred_leaf)
