"""GBDT boosting engine: the per-iteration training loop.

Reference: ``GBDT::TrainOneIter`` (src/boosting/gbdt.cpp, UNVERIFIED —
empty mount, see SURVEY.md banner): gradients from the objective →
(bagging subset) → train one tree per class → shrinkage → update train +
valid scores → metrics.

TPU-first: one jitted ``step`` fuses gradient computation, the whole
leaf-wise tree growth, and train/valid score updates; the host loop only
orchestrates iterations, callbacks, and model bookkeeping (mirroring the
reference where everything inside an iteration is C++/CUDA and Python owns
the callback loop). Scores and the binned matrix stay device-resident
across iterations; per-iteration host traffic is just the finished tree's
flat arrays (the reference's CUDA learner syncs the same per-tree state,
cuda_single_gpu_tree_learner.cpp).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import capabilities, obs
from ..config import Config
from ..io.dataset import Dataset
from ..learner.serial import GrowConfig, grow_tree
from ..metric import Metric, metrics_for_config
from ..objective import Objective, create_objective
from ..ops.histogram import pad_rows
from ..ops.predict import forest_predict_binned, tree_predict_binned
from ..tree import Tree
from ..utils import log
from ..utils.prefetch import InflightWindow

# once-per-process marker for the tpu_hist_partition=auto stand-down
# warning (every train() builds a fresh GBDT; correct default behavior
# must not warn repeatedly)
_WARNED_PART_AUTO: list = []




def _cegb_u_fold(U, leaf_used, leaf_id, in_sample):
    """U |= path-features of each IN-SAMPLE row's leaf for one tree
    (cost_effective_gradient_boosting.hpp marks feature-used-in-data on
    split application, over the bagged/GOSS partition only): one-hot
    [n, L] x [L, F] matmul (0/1 exact in bf16, f32 accumulation).
    Runs inside the jitted step so the GOSS sample mask — computed
    device-side — governs acquisition exactly."""
    L = leaf_used.shape[0]
    oh = ((leaf_id[:, None]
           == jnp.arange(L, dtype=jnp.int32)[None, :])
          & in_sample[:, None]).astype(jnp.bfloat16)
    hit = jax.lax.dot_general(
        oh, leaf_used.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return U | (hit > 0.5)


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _next_pow2(x: int) -> int:
    return 1 << max(x - 1, 0).bit_length()


def predict_pad_rows(n_rows: int, chunk_rows, buckets) -> int:
    """Total rows the predict chunk plan allocates for an ``n_rows``
    request — THE serving pad policy (pow2 bucket under the chunk
    floor, whole same-shape chunks above it), shared between
    ``_run_forest_chunks``'s plan and serve/service.py's
    ``serve.batch_fill_ratio`` denominator so the gauge can never
    drift from the dispatched shape."""
    from ..config import coerce_bool
    chunk = max(int(chunk_rows), 1024)
    n = max(int(n_rows), 1)
    if n > chunk:
        return -(-n // chunk) * chunk
    return _predict_row_bucket(n, chunk) if coerce_bool(buckets) else n


# smallest pow2 row bucket a predict pads to; serve/service.py's
# warmup walk starts here so it visits exactly the engine's bucket set
PREDICT_ROW_BUCKET_FLOOR = 128


def _predict_row_bucket(n: int, cap: int) -> int:
    """Pad a predict batch up to the nearest power-of-two row bucket
    (floor 128), capped at the chunk size — arbitrary request sizes then
    hit a BOUNDED traversal compile cache (<= log2(cap/128) programs)
    instead of one program per distinct n."""
    b = max(_next_pow2(max(n, 1)), PREDICT_ROW_BUCKET_FLOOR)
    return b if b <= cap else cap

# stacked-forest cache entries kept per engine (distinct (start, num,
# pad) tree ranges in flight at once — full model + a few early-stop
# slices; each entry is only T * Ln * ~10 ints of HBM)
_STACK_CACHE_ENTRIES = 8


class _DeviceData:
    """Device-resident binned data + metadata for one dataset.

    With a mesh, rows are sharded over the DATA axis (the reference's
    per-machine row shards, dataset_loader.cpp rank-aware loading); padding
    rounds up so every shard holds whole histogram blocks.
    """

    def __init__(self, ds: Dataset, rows_per_block: int, mesh=None,
                 transposed: bool = False, shard_features: bool = False,
                 n_feature_pad: int = 0, binned_override=None,
                 n_layout: int = None):
        ds.construct()
        self.n = ds.num_data
        # feature-parallel replicates rows; data/voting shard them
        row_shards = (mesh.devices.size
                      if mesh is not None and not shard_features else 1)
        # multi-host placement requires every process to contribute the
        # SAME padded chunk shape (make_array_from_process_local_data);
        # with uneven shards (e.g. the distributed CLI's remainder on
        # the last rank) the pad target must be the LARGEST local shard,
        # agreed via a host-side counts allgather — otherwise shapes
        # (and thus the traced SPMD programs) diverge across processes.
        # The caller passes n_layout when it already gathered the max
        # (GBDT.__init__ does, for rows_per_block); valid sets gather
        # their own here.
        if n_layout is None:
            n_layout = self.n
            if (mesh is not None and not shard_features
                    and jax.process_count() > 1):
                from jax.experimental import multihost_utils
                g = np.asarray(multihost_utils.process_allgather(
                    np.asarray([self.n], np.int64)))
                n_layout = int(g.max())
        self.n_pad = pad_rows(max(n_layout, self.n),
                              rows_per_block * row_shards)
        # device-resident ingest (ops/ingest.py): the binned matrix was
        # PRODUCED on the accelerator — adopt it directly (row/column
        # padding happens on device) instead of round-tripping through
        # host. Meshes and the EFB bundled matrix keep the host upload
        # path (sharded placement consumes host numpy).
        dev = (ds.device_ingested() if binned_override is None else None)
        use_dev = dev is not None and mesh is None
        if use_dev:
            binned = None
            bins_width = int(dev.bins.shape[1])
            bins_itemsize = np.dtype(dev.bins.dtype).itemsize
        else:
            binned = (ds.binned if binned_override is None
                      else binned_override)   # EFB physical matrix
            if ds.device_ingested() is not None \
                    and getattr(ds, "_binned", None) is not None:
                # host fallback (mesh / EFB): the host copy is now
                # authoritative — drop the device-resident ingest
                # arrays instead of leaving them orphaned in HBM next
                # to the sharded uploads
                ds._ingest = None
            if n_feature_pad and binned.shape[1] < n_feature_pad:
                # pad feature columns so every device owns an equal slice
                # (scatter/feature-parallel); padded features never split
                # (num_bin=1, allowed=False in the engine's metadata)
                binned = np.concatenate(
                    [binned, np.zeros((binned.shape[0],
                                       n_feature_pad - binned.shape[1]),
                                      binned.dtype)], axis=1)
            if self.n_pad > self.n:
                pad = np.zeros((self.n_pad - self.n, binned.shape[1]),
                               dtype=binned.dtype)
                binned = np.concatenate([binned, pad], axis=0)
            bins_width = binned.shape[1]
            bins_itemsize = binned.itemsize

        from ..parallel.mesh import P, put, shard_rows
        axis = mesh.axis_names[0] if mesh is not None else None

        # HBM capacity guard: the dominant device residents are the
        # row-major bins and (Pallas path) the feature-major bins_t;
        # per-device share divides by the row shard count. Fail with an
        # actionable message instead of an opaque device OOM.
        from ..utils.hbm import (ENGINE_HBM_FRACTION, binned_device_bytes,
                                 hbm_bytes_limit)
        hbm_limit = hbm_bytes_limit()
        if hbm_limit:
            need = binned_device_bytes(self.n_pad, bins_width,
                                       bins_itemsize, transposed)
            # rows (data/voting) or columns (feature-parallel) shard
            # over every mesh device either way
            n_dev = mesh.devices.size if mesh is not None else 1
            per_dev = need // n_dev
            if obs.enabled():
                # the capacity-guard estimate as a gauge: HBM creep
                # shows as hbm.binned_estimate_bytes vs hbm.bytes_limit
                # trending together, not as a surprise fatal
                obs.set_gauge("hbm.binned_estimate_bytes", per_dev)
                obs.set_gauge("hbm.bytes_limit", hbm_limit)
            if per_dev > ENGINE_HBM_FRACTION * hbm_limit:
                from ..utils import log as _log
                _log.fatal(
                    f"binned data needs ~{per_dev / 2**30:.1f} GiB per "
                    f"device but HBM is {hbm_limit / 2**30:.1f} GiB. "
                    f"Shard rows over more devices "
                    f"(tree_learner=data), lower max_bin, or drop "
                    f"features")

        def place(a, extra_dims=1):
            if mesh is None:
                return jnp.asarray(a)
            if shard_features:
                # rows replicated under feature-parallel
                return put(mesh, np.asarray(a), P())
            return shard_rows(mesh, np.asarray(a), extra_dims)

        if use_dev:
            # no feature-column padding here: use_dev implies mesh is
            # None, and F_pad == F without a mesh (need_fpad is a
            # sharded-layout concern) — only rows can need padding
            bins = dev.bins
            assert not n_feature_pad or bins.shape[1] == n_feature_pad
            if bins.shape[0] < self.n_pad:
                bins = jnp.concatenate(
                    [bins, jnp.zeros((self.n_pad - bins.shape[0],
                                      bins.shape[1]), bins.dtype)])
            elif bins.shape[0] > self.n_pad:
                # a previous engine padded further (bigger block size);
                # pad rows are zeros, so trimming is exact
                bins = bins[:self.n_pad]
            self.bins = bins
            # swap the padded array back into the ingest result: the
            # UNPADDED original's HBM is released (host_binned slices
            # to n_rows, so Dataset consumers are unaffected) — without
            # this the dataset would hold a second full-size copy for
            # its whole lifetime
            dev.bins = bins
            self.bins_t = None
            if transposed:
                # feature-major int8 tile: the ingest kernel already
                # emitted it fused with the row-major pass; derive
                # on-device (bitcast transpose) when it did not — the
                # HOST transpose is gone either way
                bt = dev.bins_t
                if bt is None:
                    bt = jax.lax.bitcast_convert_type(
                        bins.T.astype(jnp.uint8), jnp.int8)
                if bt.shape[1] < self.n_pad:
                    bt = jnp.concatenate(
                        [bt, jnp.zeros((bt.shape[0],
                                        self.n_pad - bt.shape[1]),
                                       jnp.int8)], axis=1)
                elif bt.shape[1] > self.n_pad:
                    bt = bt[:, :self.n_pad]
                self.bins_t = bt
                dev.bins_t = bt
            elif dev.bins_t is not None:
                # this engine never reads the tile (non-Pallas config on
                # a dataset whose construct-time params emitted it) —
                # release its HBM instead of keeping a dead same-size
                # copy alive via the ingest result
                dev.bins_t = None
        else:
            if mesh is not None and shard_features:
                self.bins = put(mesh, binned, P(None, axis))
            else:
                self.bins = place(binned, extra_dims=2)
            self.bins_t = None
            if transposed:
                # feature-major int8 copy for the Pallas histogram kernel
                bt = np.ascontiguousarray(binned.T).astype(np.int8)
                if mesh is None:
                    self.bins_t = jnp.asarray(bt)
                elif shard_features:
                    self.bins_t = put(mesh, bt, P(axis, None))
                else:
                    self.bins_t = put(mesh, bt, P(None, axis))
        self._place = place
        md = ds.metadata

        def _pad1(a, fill=0.0):
            if a is None:
                return None
            a = np.asarray(a, dtype=np.float32)
            if a.ndim == 1 and len(a) < self.n_pad:
                a = np.concatenate(
                    [a, np.full(self.n_pad - len(a), fill, np.float32)])
            return place(a)

        self.label = _pad1(md.label)
        self.weight = _pad1(md.weight)
        self.init_score = (None if md.init_score is None
                           else np.asarray(md.init_score, np.float64))
        self.query_boundaries = md.query_boundaries
        self.valid_mask = place(
            (np.arange(self.n_pad) < self.n).astype(np.float32))


# tpu_auto_quantize only engages at the scale the A/B validated
# (docs/perf.md): below this, exact f32 gradients are the default.
# Policy constants live in the capability table (capabilities.py);
# this module-level alias stays monkeypatchable for tests.
AUTO_QUANT_MIN_ROWS = capabilities.AUTO_QUANT_MIN_ROWS


def goss_shard_valid_counts(n_local: int, n_pad_local: int,
                            n_global_devices: int, n_processes: int,
                            allgather=None):
    """Per-global-shard valid row counts for GOSS's exact subset sizes.

    Single-process: this process's rows span the whole mesh, so the
    counts fall out of the local block layout. Multi-host: each process
    computes its LOCAL devices' counts (its chunk is placed on its own
    addressable devices in mesh order by
    ``make_array_from_process_local_data``) and one host-side counts
    allgather concatenates them in process order — the same order the
    mesh's ``axis_index`` enumerates global shards. ``allgather`` is
    injectable for single-process tests.
    """
    if n_processes <= 1:
        blk = n_pad_local // n_global_devices
        return [max(0, min(n_local - s * blk, blk))
                for s in range(n_global_devices)]
    n_local_dev = max(1, n_global_devices // n_processes)
    blk = n_pad_local // n_local_dev
    loc = np.asarray([max(0, min(n_local - s * blk, blk))
                      for s in range(n_local_dev)], np.int64)
    if allgather is None:
        from jax.experimental import multihost_utils
        allgather = multihost_utils.process_allgather
    return [int(v) for v in np.asarray(allgather(loc)).reshape(-1)]


class GBDT:
    """Boosting engine (reference: GBDT class, src/boosting/gbdt.cpp)."""

    # score/valid-score carries may donate under tpu_donate (the step
    # outputs fully replace the inputs, nothing host-side re-reads the
    # pre-step buffers). DART re-reads score_pre/valid_pre to rescale
    # the new tree against the dropped set, and RF folds the step
    # output against held base/pred-sum buffers — both override False.
    _donate_carries = True

    def __init__(self, config: Config, train_set: Dataset,
                 fobj: Optional[Callable] = None, mesh=None,
                 init_forest=None):
        self.config = config
        self.train_set = train_set.construct()
        self.fobj = fobj
        # distributed learner selection (TreeLearner factory seam,
        # src/treelearner/tree_learner.cpp): serial runs single-device;
        # data/voting shard rows, feature shards columns over a mesh
        self.mesh = mesh
        if (self.mesh is None and config.tree_learner != "serial"
                and jax.device_count() > 1):
            from ..parallel.mesh import (create_data_mesh,
                                         create_feature_mesh)
            # tpu_mesh_shape: cap the mesh to the first N devices
            # ("" = all visible devices)
            nd = (int(config.tpu_mesh_shape)
                  if str(config.tpu_mesh_shape).strip() else None)
            self.mesh = (create_feature_mesh(nd)
                         if config.tree_learner == "feature"
                         else create_data_mesh(nd))
        if self.mesh is not None and config.tree_learner == "serial":
            self.mesh = None
        self.learner_type = config.tree_learner if self.mesh is not None \
            else "serial"
        self._shard_features = self.learner_type == "feature"
        if self._shard_features and jax.process_count() > 1:
            # feature-sharded placement has no process-local chunk
            # semantics (every process binned ALL columns); the
            # row-sharded learners are the multi-host story
            log.fatal("tree_learner=feature is not supported multi-host;"
                      " use data or voting")
        self.axis = (self.mesh.axis_names[0]
                     if self.mesh is not None else "")
        # measured-default quantized training (tpu_auto_quantize,
        # VERDICT r4 item 2): in the A/B's validated regime — >= 500k
        # rows, gbdt boosting, a level-sum-safe objective, no custom
        # fobj — int8 histograms were +18-36% throughput at
        # equal-or-better equal-round AUC (docs/perf.md). Explicit
        # use_quantized_grad settings always win; smaller data keeps
        # the exact-f32 default for reference bit-compatibility.
        if (bool(config.tpu_auto_quantize)
                and "use_quantized_grad" not in config.raw_params
                and not config.use_quantized_grad
                and config.boosting == "gbdt" and fobj is None
                and self.train_set.num_data >= AUTO_QUANT_MIN_ROWS
                and str(config.objective)
                in capabilities.AUTO_QUANTIZE_OBJECTIVES):
            config.use_quantized_grad = True
            config._quantize_auto = True
            log.info("tpu_auto_quantize: enabling quantized gradients "
                     "(int8 histograms) for this training — measured "
                     "equal-AUC and faster at this scale; set "
                     "use_quantized_grad=false to keep f32")
        self.objective: Objective = create_objective(config)
        if hasattr(self.objective, "prepare") and \
                self.train_set.metadata.label is not None:
            self.objective.prepare(self.train_set.metadata.label,
                                   self.train_set.metadata.weight)
        if self.objective.is_ranking:
            self.objective.setup_queries(
                self.train_set.metadata.query_boundaries,
                self.train_set.num_data,
                position=self.train_set.metadata.position)
        # stateful objectives (lambdarank_unbiased): per-rank propensity
        # state threads through the boosting step and updates host-side
        # each iteration (not rolled back by rollback_one_iter)
        self._pos_state = None
        if getattr(self.objective, "has_pos_state", False):
            if self.mesh is not None:
                log.fatal("position debiasing (a `position` field, or "
                          "lambdarank_unbiased=true) is not supported "
                          "with distributed tree_learner yet; drop the "
                          "position field / flag or use the serial "
                          "learner")
            self._pos_state = self.objective.init_pos_state()
        self.metrics: List[Metric] = metrics_for_config(config)
        self.num_class = config.num_tree_per_iteration
        self.models: List[Tree] = []
        self.iter_ = 0
        self.average_output = False  # RF subclass sets True
        # stacked-forest device cache bookkeeping: _models_version bumps
        # on ANY model mutation (growth, rollback, state import, DART/RF
        # leaf rescales) so cached device stacks can never serve stale
        # leaf values (_stack_model_list)
        self._models_version = 0
        self._stack_cache: Optional[Tuple[Tuple[int, int], Dict]] = None
        # device-resident SHAP path-table cache (predict_contrib):
        # same (len, version) key + LRU shape as _stack_cache, entries
        # keyed by (start_tree, n_trees, dtype) slice
        self._shap_cache: Optional[Tuple[Tuple[int, int], Dict]] = None
        # tree-sharded predict (serve/shard.py enable_tree_sharding):
        # when set, stacked forests are placed with the [T] axis
        # NamedSharding-split over this mesh and predicts take the
        # sharded traversal; _shard_consts caches the replicated
        # feat_num_bin/feat_has_nan copies so warm predicts re-place
        # nothing
        self._predict_mesh = None
        self._shard_consts: Optional[Tuple] = None

        n_shards = self.mesh.devices.size if self.mesh is not None else 1
        n_rows_layout = self.train_set.num_data
        if self.mesh is not None and jax.process_count() > 1:
            # uneven multi-host shards: every process must derive the
            # SAME block size or the traced SPMD programs diverge
            from jax.experimental import multihost_utils
            n_rows_layout = int(np.asarray(
                multihost_utils.process_allgather(
                    np.asarray([n_rows_layout], np.int64))).max())
        rows_per_block = min(
            config.tpu_rows_per_block,
            pad_rows(max(1, n_rows_layout // n_shards), 256))
        self.rows_per_block = rows_per_block

        F = len(self.train_set.used_features)
        self.num_features = F

        # ---- EFB (dataset_loader.cpp FindGroups/FastFeatureBundling) --
        # bundle mutually-exclusive sparse features into shared physical
        # columns; the learner scans F_phys columns and expands
        # histograms back to logical features (io/bundling.py). Composes
        # with serial / data-psum / voting (scatter and feature-parallel
        # keep their own feature-ownership layouts instead).
        self.has_bundles = False
        self.bundle_plan = None
        self._bundle_dev = None
        self._bundled_binned = None
        # under device-resident ingest the bundle probe would force a
        # full-matrix D2H materialization (Dataset.binned) during the
        # exact window ttfi_s exists to shrink — and dense accelerator
        # datasets essentially never bundle. Probe only when the host
        # copy exists anyway; tpu_ingest_device=false restores EFB.
        _host_bins_free = (self.train_set.device_ingested() is None
                           or getattr(self.train_set, "_binned", None)
                           is not None)
        if (config.enable_bundle and F >= 2 and not self._shard_features
                and not _host_bins_free):
            log.info("EFB bundle probe skipped: dataset is "
                     "device-resident (tpu_ingest_device); set "
                     "tpu_ingest_device=false to restore EFB")
        if (config.enable_bundle and F >= 2 and not self._shard_features
                and _host_bins_free):
            mappers = [self.train_set.bin_mappers[f]
                       for f in self.train_set.used_features]
            eligible = np.array(
                [(m.bin_type != "categorical")
                 and m.missing_type == "none" for m in mappers],
                dtype=bool)
            default_bins = np.array(
                [m.value_to_bin(0.0) if eligible[i] else 0
                 for i, m in enumerate(mappers)], dtype=np.int32)
            if int(eligible.sum()) >= 2:
                from ..io.bundling import find_bundles, plan_bundles
                nb_logical = self.train_set.feature_num_bins()
                multi = find_bundles(
                    self.train_set.binned, nb_logical, eligible,
                    default_bins,
                    max_conflict_rate=config.max_conflict_rate,
                    seed=config.data_random_seed)
                if multi:
                    self.bundle_plan = plan_bundles(nb_logical,
                                                    default_bins, multi)
                    self.has_bundles = True
                    log.info(
                        f"EFB: bundled {sum(len(b) for b in multi)} "
                        f"features into {len(multi)} bundles "
                        f"({F} -> {self.bundle_plan.n_phys} columns)")

        # pad feature count to a multiple of the shard count so scatter /
        # feature-parallel slices are equal-width (padded features carry
        # num_bin=1 + allowed=False, so they never win a split)
        need_fpad = self.mesh is not None and not self.has_bundles and (
            self._shard_features
            or (self.learner_type == "data"
                and config.tpu_hist_reduce == "scatter"))
        self.F_pad = (_ceil_to(max(F, 1), n_shards) if need_fpad else F)
        fpad = self.F_pad - F
        num_bin = self.train_set.feature_num_bins()
        self.max_num_bin = int(num_bin.max()) if F else 2
        if self.has_bundles:
            # one shared width covers both the physical scan and the
            # logical expansion
            self.max_num_bin = max(
                self.max_num_bin, int(self.bundle_plan.phys_num_bin.max()))
        # static histogram width: pad to a lane-friendly multiple
        self.B = max(8, _ceil_to(self.max_num_bin, 8))
        is_cat = np.array(
            [self.train_set.bin_mappers[f].bin_type == "categorical"
             for f in self.train_set.used_features], dtype=bool)
        # categorical NaN/unseen is bin 0 and routes via bitset-miss, not
        # the numerical last-bin NaN convention
        has_nan = np.array(
            [self.train_set.bin_mappers[f].missing_type == "nan"
             for f in self.train_set.used_features], dtype=bool) & ~is_cat
        if fpad:
            num_bin = np.concatenate([num_bin, np.ones(fpad, num_bin.dtype)])
            has_nan = np.concatenate([has_nan, np.zeros(fpad, bool)])
            is_cat = np.concatenate([is_cat, np.zeros(fpad, bool)])
        self.feat_num_bin = jnp.asarray(num_bin.astype(np.int32))
        self.feat_has_nan = jnp.asarray(has_nan)
        self.has_categorical = bool(is_cat.any())
        self.feat_is_cat = jnp.asarray(is_cat)
        # static categorical positions for the sliced split-search fast
        # path (ops/split.py cat_positions); scatter/feature-parallel
        # shards search dynamic slices, so they fall back to the masked
        # full-width scan
        self._cat_positions = tuple(int(i) for i in np.nonzero(is_cat)[0])

        # monotone constraints ([F_pad] int8 by used-feature index;
        # categorical features are never direction-constrained)
        mc = list(config.monotone_constraints or [])
        mono = np.zeros(self.F_pad, dtype=np.int8)
        if mc:
            for i, f in enumerate(self.train_set.used_features):
                if f < len(mc):
                    mono[i] = int(mc[f])
            mono[is_cat] = 0
        self.has_monotone = bool(np.any(mono != 0))
        self.feat_mono = jnp.asarray(mono) if self.has_monotone else None

        # feature_contri (config_auto.cpp feature_contri, the "fp"
        # feature-penalty aliases): per-feature split-gain multipliers,
        # given by ORIGINAL feature index, remapped to used features
        fc = list(config.feature_contri or [])
        self.has_contri = bool(fc) and any(float(c) != 1.0 for c in fc)
        self.feat_contri = None
        if self.has_contri:
            arr = np.ones(self.F_pad, dtype=np.float32)
            for i, f in enumerate(self.train_set.used_features):
                if f < len(fc):
                    arr[i] = float(fc[f])
            self.feat_contri = jnp.asarray(arr)

        # interaction constraints ([G, F_pad] bool over used features)
        from ..config import parse_interaction_constraints
        groups_spec = parse_interaction_constraints(
            config.interaction_constraints)
        self.has_interaction = bool(groups_spec)
        self.interaction_groups = None
        if self.has_interaction:
            orig_to_used = {f: i for i, f in
                            enumerate(self.train_set.used_features)}
            gm = np.zeros((len(groups_spec), self.F_pad), dtype=bool)
            for gi, grp in enumerate(groups_spec):
                for f in grp:
                    u = orig_to_used.get(int(f))
                    if u is not None:
                        gm[gi, u] = True
            self.interaction_groups = jnp.asarray(gm)

        if self.has_bundles:
            from ..io.bundling import apply_bundles, build_expand_maps
            self._bundled_binned = apply_bundles(self.train_set.binned,
                                                 self.bundle_plan)
            mpf, mpb, mvalid, mdef = build_expand_maps(
                self.bundle_plan, num_bin[:F], self.B)
            self._bundle_dev = (
                jnp.asarray(mpf), jnp.asarray(mpb), jnp.asarray(mvalid),
                jnp.asarray(mdef),
                jnp.asarray(self.bundle_plan.bundled),
                jnp.asarray(self.bundle_plan.phys_col),
                jnp.asarray(self.bundle_plan.start),
                jnp.asarray(self.bundle_plan.default_bin))

        # CEGB (cost_effective_gradient_boosting.hpp): split penalty +
        # coupled per-feature penalty charged until a feature first
        # enters the model (host-tracked, device array refreshed on
        # use) + LAZY per-row penalty (round 4): splitting leaf l on f
        # costs lazy[f] x (#rows in l that never met f on a tree path
        # yet) — the per-row feature-acquisition model. Acquisition
        # state is a device [n_pad, F_pad] matrix updated after each
        # tree from the per-leaf path-feature sets.
        coupled = list(config.cegb_penalty_feature_coupled or [])
        lazy = list(config.cegb_penalty_feature_lazy or [])
        self.has_cegb = bool(
            config.cegb_penalty_split > 0 or any(coupled) or any(lazy))
        self._cegb_coupled = None
        self._cegb_used = None
        self._cegb_pen_cache = None
        self._cegb_lazy = None
        self._cegb_U = None     # device [n_pad, F_pad] bool, lazy init
        if self.has_cegb and coupled:
            arr = np.zeros(self.F_pad, dtype=np.float32)
            for i, f in enumerate(self.train_set.used_features):
                if f < len(coupled):
                    arr[i] = float(coupled[f])
            self._cegb_coupled = arr * float(config.cegb_tradeoff)
            self._cegb_used = np.zeros(self.F_pad, dtype=bool)
        if self.has_cegb and any(lazy):
            if (self.mesh is not None or self.has_bundles
                    or getattr(self.objective, "has_pos_state", False)):
                log.fatal("cegb_penalty_feature_lazy requires the "
                          "serial single-device learner without EFB "
                          "bundling or position-state objectives")
            arr = np.zeros(self.F_pad, dtype=np.float32)
            for i, f in enumerate(self.train_set.used_features):
                if f < len(lazy):
                    arr[i] = float(lazy[f])
            self._cegb_lazy = jnp.asarray(
                arr * float(config.cegb_tradeoff))

        # ---- forced splits (forcedsplits_filename; ForceSplits in
        # serial_tree_learner.cpp — UNVERIFIED): JSON tree flattened
        # into a preorder table applied one entry per growth round ----
        self._forced_dev = None
        self._n_forced = 0
        fs_path = str(config.forcedsplits_filename or "").strip()
        if fs_path:
            if (self.mesh is not None or config.tpu_hist_mode != "pool"
                    or self.has_bundles):
                log.warning("forcedsplits_filename requires the serial "
                            "learner, tpu_hist_mode=pool and no EFB "
                            "bundles; ignoring forced splits")
            else:
                self._load_forced_splits(fs_path)

        # The fused Pallas kernel needs a TPU backend and int8-roundtrip
        # bin ids (B <= 256); anything else takes the XLA einsum path.
        # tpu_double_precision_hist also routes to the XLA path — the
        # Pallas kernel's operands are bf16 by design (quantized mode is
        # the exact-at-speed alternative).
        self.use_pallas = bool(config.tpu_use_pallas and F > 0
                               and self.B <= 256
                               and not config.tpu_double_precision_hist
                               and jax.default_backend() == "tpu")
        self.data = _DeviceData(self.train_set, rows_per_block, self.mesh,
                                transposed=self.use_pallas,
                                shard_features=self._shard_features,
                                # the bundled matrix is NARROWER than F —
                                # never pad it back to logical width
                                n_feature_pad=(0 if self.has_bundles
                                               else self.F_pad),
                                binned_override=self._bundled_binned,
                                n_layout=n_rows_layout)

        # ---- leaf-ordered device row partition (tpu_hist_partition;
        # ops/partition.py): rows ride the grow-loop carry grouped by
        # leaf so each round's histogram scans only the elected
        # children's spans (siblings by pool subtraction / rebuild
        # N-packing). Trees are structurally identical to the masked
        # path (bit-exact under quantized gradients). The per-round
        # repartition move costs ~2 compaction passes (docs/perf.md
        # "Partitioned histograms"), so AUTO only engages where the
        # cost model wins: the Pallas pool path over a large
        # un-compacted source, where per-round scan time is dominated
        # by its row-linear VPU one-hot term. Explicit "true" engages
        # anywhere the move machinery exists (CPU/XLA uses an exact
        # scatter move), "false" never.
        import math as _m
        self.part_rpb = _m.gcd(1024, rows_per_block)
        part_mode = str(config.tpu_hist_partition)
        # TPU without the Pallas kernels has no fast move (computed
        # scatters serialize, docs/perf.md) — partition never engages
        can_part = F > 0 and (self.use_pallas
                              or jax.default_backend() != "tpu")
        if part_mode == "true":
            if not can_part:
                log.warning(
                    "tpu_hist_partition=true needs the Pallas path on "
                    "TPU (max_bin<=255, tpu_use_pallas=true, no "
                    "tpu_double_precision_hist) or a non-TPU backend; "
                    "keeping the masked full-scan histograms")
            self.hist_partition = can_part
        elif part_mode == "false":
            self.hist_partition = False
        else:
            # the auto cost model lives in the capability table
            # (capabilities.hist_partition_auto); this block only owns
            # the warning etiquette
            engage, reason = capabilities.hist_partition_auto(
                config, self.use_pallas, self.data.n_pad)
            self.hist_partition = can_part and engage
            if can_part and not engage and reason is not None:
                big = (self.data.n_pad
                       >= capabilities.HIST_PARTITION_MIN_ROWS)
                msg = (f"tpu_hist_partition=auto: staying on masked "
                       f"histograms ({reason}); set "
                       f"tpu_hist_partition=true to force")
                # the stand-down is WARNING-visible only where the
                # partition plausibly applied (flagship-scale runs) and
                # once per process — default small/GOSS configs must
                # not pay a warning per train() for correct behavior
                if big and not _WARNED_PART_AUTO:
                    _WARNED_PART_AUTO.append(True)
                    log.warning(msg)
                else:
                    log.info(msg)
        if self.hist_partition:
            log.info("leaf-ordered row partition enabled: histograms "
                     "scan only the elected children's row spans")
        obs.set_gauge("hist.partition", float(self.hist_partition))

        self.grow_cfg = self._make_grow_cfg()

        # ---- initial scores (BoostFromAverage, gbdt.cpp) ------------------
        # Under continuation (init_model, gbdt.cpp::ResetTrainingData with
        # existing models) the loaded forest carries the original init
        # bias in its first trees, so boost-from-average is skipped —
        # EXCEPT for RF, where every tree independently carries the bias
        # and gradients are always evaluated at the init score (rf.hpp
        # computes BoostFromAverage regardless of existing models).
        label_np = self.train_set.metadata.label
        self.init_scores = np.zeros(self.num_class, dtype=np.float64)
        if label_np is not None and self.fobj is None \
                and (init_forest is None or config.boosting == "rf"):
            if self.num_class == 1:
                w_np = self.train_set.metadata.weight
                if jax.process_count() > 1 and config.boost_from_average:
                    # multi-host: each process holds only its row shard;
                    # sync the mean statistic across processes (the
                    # reference's Network::GlobalSyncUpByMean)
                    stats = self.objective.init_mean_stats(label_np, w_np)
                    if stats is None:
                        log.warning(
                            "boost_from_average for this objective is a "
                            "percentile statistic that cannot be synced "
                            "across hosts; using this process's local "
                            "shard only")
                        self.init_scores[0] = self.objective.init_score(
                            label_np, w_np)
                    else:
                        from jax.experimental import multihost_utils
                        tot = np.asarray(
                            multihost_utils.process_allgather(
                                jnp.asarray(stats, jnp.float64)
                                if jax.config.jax_enable_x64
                                else jnp.asarray(stats, jnp.float32)))
                        self.init_scores[0] = self.objective.init_from_mean(
                            float(tot[:, 0].sum()) / max(
                                float(tot[:, 1].sum()), 1e-30))
                else:
                    self.init_scores[0] = self.objective.init_score(
                        label_np, w_np)
        self.score = self._init_score_tile(self.data)
        if init_forest is not None:
            self._load_forest(init_forest)

        # valid sets registered later via add_valid
        self.valid_data: List[_DeviceData] = []
        self.valid_scores: List[jnp.ndarray] = []
        self.valid_names: List[str] = []
        self._valid_ds: List[Dataset] = []

        # linear trees (linear_tree_learner.cpp): structures grown by the
        # standard jitted learner, leaves refined by host-side per-leaf
        # weighted ridge (learner/linear.py)
        self.linear_tree = bool(config.linear_tree)
        if self.linear_tree and self.train_set._raw_for_linear is None:
            log.fatal("linear_tree=True requires the Dataset to be "
                      "constructed with linear_tree in its params "
                      "(raw feature values must be retained)")

        self._rng_feature = np.random.RandomState(
            config.feature_fraction_seed)
        self._rng_bagging = np.random.RandomState(config.bagging_seed)
        self._bag_mask = None  # device [n_pad] or None when no bagging
        self._train_metric_names: List[str] = [m.name for m in self.metrics]
        self._build_step()
        if self.hist_partition and self.mesh is None and obs.enabled():
            self._probe_partition_move()

    # ------------------------------------------------------------------
    def _init_score_tile(self, dd: "_DeviceData") -> jnp.ndarray:
        """Device [n_pad, K] tile of init scores + dataset init_score."""
        s0 = np.tile(self.init_scores.astype(np.float32), (dd.n_pad, 1))
        if dd.init_score is not None:
            m = dd.init_score.size
            if m not in (dd.n, dd.n * self.num_class):
                log.fatal(f"Length of init_score ({m}) does not match "
                          f"number of data ({dd.n}) or number of data * "
                          f"num_class ({dd.n * self.num_class})")
            s0[:dd.n] += dd.init_score.reshape(dd.n, -1).astype(np.float32)
        return dd._place(s0, extra_dims=2)

    def _logical_bins(self) -> jnp.ndarray:
        """The LOGICAL binned train matrix for tree traversal (score
        rebuilds, DART dropped-tree recomputation). Under EFB the
        resident matrix is the bundled physical one, so the logical
        layout is rebuilt on first use and cached — DART needs it every
        iteration, so under EFB+DART both layouts stay resident."""
        if not self.has_bundles:
            return self.data.bins
        if getattr(self, "_logical_bins_cache", None) is None:
            binned = self.train_set.binned
            if self.data.n_pad > binned.shape[0]:
                binned = np.concatenate(
                    [binned, np.zeros((self.data.n_pad - binned.shape[0],
                                       binned.shape[1]), binned.dtype)])
            self._logical_bins_cache = self.data._place(binned,
                                                        extra_dims=2)
        return self._logical_bins_cache

    def _load_forest(self, init_forest) -> None:
        """Continuation: adopt a loaded HostModel's trees and fold their
        predictions into the training score."""
        if init_forest.num_tree_per_iteration != self.num_class:
            log.fatal(
                f"Cannot continue training: the loaded model has "
                f"{init_forest.num_tree_per_iteration} trees per iteration"
                f", the new config {self.num_class}")
        # NB: compare against the config, not self.average_output — the
        # RF subclass sets that flag only after super().__init__ returns
        if bool(init_forest.average_output) != (self.config.boosting
                                                == "rf"):
            kind = "averaged (rf)" if init_forest.average_output \
                else "additive (gbdt/dart)"
            log.fatal(
                f"Cannot continue training: the loaded model is {kind} "
                f"but boosting={self.config.boosting} — the ensemble "
                f"semantics don't compose")
        for ht in init_forest.trees:
            self.models.append(Tree.rebin(
                ht, self.train_set.bin_mappers,
                self.train_set.used_features))
        self.iter_ = len(self.models) // self.num_class
        if self.models:
            if any(getattr(t, "is_linear", False) for t in self.models):
                # linear leaves need raw features: host-side rebuild
                if self.train_set._raw_for_linear is None:
                    log.fatal("Continuing from a linear-tree model "
                              "requires linear_tree=True params")
                Xu = self.train_set._raw_for_linear
                raw_np = np.zeros((self.data.n_pad, self.num_class),
                                  dtype=np.float32)
                for i, t in enumerate(self.models):
                    raw_np[:self.data.n, i % self.num_class] += \
                        t.predict_raw(Xu)
                self.score = self.score + self.data._place(
                    raw_np, extra_dims=2)
                return
            stacked, class_idx = self._stack_models(0, len(self.models))
            raw, _ = forest_predict_binned(
                stacked, self._logical_bins(), self.feat_num_bin,
                self.feat_has_nan, class_idx, self.num_class)
            self.score = self.score + raw

    def add_valid(self, ds: Dataset, name: str) -> None:
        # feature-parallel keeps valid sets unsharded (prediction needs
        # every column); data/voting shard valid rows like train rows
        if self.linear_tree and not ds._constructed:
            ds.params.setdefault("linear_tree", True)
        self._valid_ds.append(ds)
        dd = _DeviceData(ds.construct(), self.rows_per_block,
                         None if self._shard_features else self.mesh)
        score0 = self._init_score_tile(dd)
        if self.models:
            stacked, class_idx = self._stack_models(0, len(self.models))
            raw, _ = forest_predict_binned(
                stacked, dd.bins, self.feat_num_bin, self.feat_has_nan,
                class_idx, self.num_class)
            score0 = score0 + raw
        self.valid_data.append(dd)
        self.valid_scores.append(score0)
        self.valid_names.append(name)
        # valid-set count changed: the valid_update jit closure must see it
        self._build_step()

    def _learning_rate(self) -> float:
        """Per-tree shrinkage; RF overrides to 1.0 (rf.hpp stores raw)."""
        return float(self.config.learning_rate)

    def _load_forced_splits(self, path: str) -> None:
        """Parse a forcedsplits_filename JSON tree ({"feature",
        "threshold", nested "left"/"right"}) into the preorder table
        grow_tree consumes. Numerical thresholds map to bin ids;
        CATEGORICAL entries (round 4) take "threshold" as a category
        value or list of values, binned into a goes-left bitset.
        Entries on unused features are skipped with their subtrees,
        like the reference's validity checks."""
        import json
        from ..io.binning import BIN_TYPE_CATEGORICAL
        with open(path) as f:
            spec = json.load(f)
        orig_to_used = {f: i for i, f in
                        enumerate(self.train_set.used_features)}
        W = (self.B + 31) // 32
        parents, lefts, feats, tbins, iscat, bitsets = \
            [], [], [], [], [], []

        def walk(node, parent_idx, is_left):
            if not isinstance(node, dict) or "feature" not in node:
                return
            fo = int(node["feature"])
            u = orig_to_used.get(fo)
            mapper = (self.train_set.bin_mappers[fo]
                      if fo < len(self.train_set.bin_mappers) else None)
            if u is None or mapper is None:
                log.warning(f"forced split on unused feature {fo} "
                            f"skipped (with its subtree)")
                return
            if len(parents) >= self.config.num_leaves - 1:
                log.warning("more forced splits than num_leaves-1; "
                            "extra entries ignored")
                return
            bits = np.zeros(W, np.uint32)
            if mapper.bin_type == BIN_TYPE_CATEGORICAL:
                thr = node["threshold"]
                cats = thr if isinstance(thr, (list, tuple)) else [thr]
                hit = 0
                for cv in cats:
                    b = (mapper.cat_to_bin or {}).get(int(cv))
                    if b is None:
                        log.warning(f"forced categorical split: "
                                    f"category {cv} of feature {fo} "
                                    f"was not seen at bin time; "
                                    f"ignored")
                        continue
                    bits[b >> 5] |= np.uint32(1) << np.uint32(b & 31)
                    hit += 1
                if hit == 0:
                    log.warning(f"forced categorical split on feature "
                                f"{fo} matched no known category; "
                                f"skipped (with its subtree)")
                    return
                tb = 0
                cat = True
            else:
                tb = mapper.value_to_bin(float(node["threshold"]))
                cat = False
            idx = len(parents)
            parents.append(parent_idx)
            lefts.append(bool(is_left))
            feats.append(u)
            tbins.append(tb)
            iscat.append(cat)
            bitsets.append(bits)
            walk(node.get("left"), idx, True)
            walk(node.get("right"), idx, False)

        walk(spec, -1, False)
        if parents:
            if any(iscat) and not self.has_categorical:
                # cannot happen via normal construction (cat mappers
                # imply has_categorical), but guard the invariant the
                # learner's bitset lanes rely on
                log.fatal("forced categorical splits require a dataset "
                          "with categorical features")
            self._n_forced = len(parents)
            self._forced_dev = (
                jnp.asarray(np.asarray(parents, np.int32)),
                jnp.asarray(np.asarray(lefts, bool)),
                jnp.asarray(np.asarray(feats, np.int32)),
                jnp.asarray(np.asarray(tbins, np.int32)),
                jnp.asarray(np.asarray(iscat, bool)),
                jnp.asarray(np.stack(bitsets)))
            log.info(f"applying {self._n_forced} forced split(s) at "
                     f"the top of every tree")

    def _make_grow_cfg(self) -> GrowConfig:
        config = self.config
        _hist_scatter = (self.learner_type == "data"
                         and config.tpu_hist_reduce == "scatter"
                         and not self.has_bundles)
        return GrowConfig(
            num_leaves=config.num_leaves,
            max_depth=config.max_depth,
            lambda_l1=config.lambda_l1,
            lambda_l2=config.lambda_l2,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            num_bins=self.B,
            rows_per_block=self.rows_per_block,
            precise_histogram=config.tpu_double_precision_hist,
            leaf_batch=max(1, config.tpu_leaf_batch),
            use_pallas=self.use_pallas,
            # int8 histogram path: stochastic rounding can push a level
            # to qbins, so int8 needs num_grad_quant_bins <= 127; the
            # int32 accumulator must also hold qbins * n_rows without
            # wrapping (the bf16 path degrades gracefully there instead)
            int_hist=(self.use_pallas
                      and bool(config.use_quantized_grad)
                      and int(config.num_grad_quant_bins) <= 127
                      and self.data.n_pad
                      * int(config.num_grad_quant_bins) < 2**31),
            axis_name=(self.axis if self.mesh is not None
                       and not self._shard_features else ""),
            has_categorical=self.has_categorical,
            cat_positions=(self._cat_positions
                           if not (self._shard_features or _hist_scatter)
                           else ()),
            max_cat_threshold=config.max_cat_threshold,
            cat_smooth=config.cat_smooth,
            cat_l2=config.cat_l2,
            max_cat_to_onehot=config.max_cat_to_onehot,
            min_data_per_group=config.min_data_per_group,
            hist_scatter=_hist_scatter,
            packed_wire=bool(config.tpu_hist_packed_wire),
            num_shards=(self.mesh.devices.size
                        if self.mesh is not None else 1),
            voting=self.learner_type == "voting",
            top_k=config.top_k,
            feature_axis=(self.axis if self._shard_features else ""),
            has_monotone=self.has_monotone,
            monotone_intermediate=(
                str(config.monotone_constraints_method).lower()
                in ("intermediate", "advanced")),
            monotone_advanced=(
                str(config.monotone_constraints_method).lower()
                == "advanced"),
            monotone_penalty=config.monotone_penalty,
            has_interaction=self.has_interaction,
            has_bundles=self.has_bundles,
            hist_rebuild=(config.tpu_hist_mode == "rebuild"),
            partition=self.hist_partition,
            part_rpb=self.part_rpb,
            feature_fraction_bynode=config.feature_fraction_bynode,
            has_cegb=self.has_cegb,
            cegb_tradeoff=config.cegb_tradeoff,
            cegb_penalty_split=config.cegb_penalty_split,
            has_cegb_lazy=self._cegb_lazy is not None,
            path_smooth=config.path_smooth,
            extra_trees=config.extra_trees,
            extra_seed=config.extra_seed,
            has_contri=self.has_contri,
            n_forced=self._n_forced,
        )

    # ------------------------------------------------------------------
    def _build_step(self) -> None:
        obj = self.objective
        K = self.num_class
        # re-derive growth config so reset_parameter takes effect
        self.grow_cfg = self._make_grow_cfg()
        gcfg = self.grow_cfg
        lr = self._learning_rate()
        mesh = self.mesh

        needs_rng = getattr(obj, "needs_rng", False)
        self._step_state = self._step_goss_state = None

        def gradients(score, label, weight, key):
            s = score[:, 0] if K == 1 else score
            if needs_rng:
                return obj.get_gradients(s, label, weight, key=key)
            return obj.get_gradients(s, label, weight)

        # gradient quantization (use_quantized_grad; reference:
        # cuda_gradient_discretizer.cu): grad/hess become small integer
        # levels — EXACT in the bf16 histogram matmul and int-valued on
        # the reduction wire — with stochastic rounding for unbiasedness
        use_quant = bool(self.config.use_quantized_grad)
        qbins = max(2, int(self.config.num_grad_quant_bins))
        renew_quant = bool(self.config.quant_train_renew_leaf)
        use_sr = bool(self.config.stochastic_rounding)
        glevels = max(qbins // 2, 1)
        hlevels = max(qbins - 1, 1)

        def quantize(gk_m, hk_m, mask_count, qkey):
            gmax = jnp.max(jnp.abs(gk_m))
            hmax = jnp.max(hk_m)
            if gcfg.axis_name:
                gmax = jax.lax.pmax(gmax, gcfg.axis_name)
                hmax = jax.lax.pmax(hmax, gcfg.axis_name)
            scale_g = jnp.maximum(gmax / glevels, 1e-30)
            scale_h = jnp.maximum(hmax / hlevels, 1e-30)
            if qkey is not None and use_sr:
                # stochastic_rounding=false -> deterministic nearest
                # rounding (gradient_discretizer semantics)
                kg, kh = jax.random.split(qkey)
                ng = jax.random.uniform(kg, gk_m.shape,
                                        minval=-0.5, maxval=0.5)
                nh = jax.random.uniform(kh, hk_m.shape,
                                        minval=-0.5, maxval=0.5)
            else:
                ng = nh = 0.0
            gq = jnp.round(gk_m / scale_g + ng)
            hq = jnp.round(hk_m / scale_h + nh)
            # stochastic rounding must not resurrect masked-out rows
            live = mask_count > 0
            gq = jnp.where(live, gq, 0.0)
            hq = jnp.where(live, hq, 0.0)
            scale = jnp.stack([scale_g, scale_h,
                               jnp.asarray(1.0, jnp.float32)])
            return gq, hq, scale

        def leaf_contrib(tree, leaf_id):
            """Per-row leaf_value[leaf_id] * lr. As a one-hot matmul: a
            per-row gather into a [L] table runs on the TPU scalar unit
            (~9ms/Mrow); the masked contraction is ~free on the MXU. The
            one-hot operand is O(n*L), so fall back to the gather for
            very wide trees where it would dominate HBM."""
            Lq = tree["leaf_value"].shape[0]
            if Lq <= 512:
                onehot = (leaf_id[:, None]
                          == jnp.arange(Lq, dtype=jnp.int32)[None, :])
                return jax.lax.dot_general(
                    onehot.astype(jnp.float32),
                    tree["leaf_value"][:, None],
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST)[:, 0] * lr
            return tree["leaf_value"][leaf_id] * lr

        def grow_all(bins, bins_t, score, g, h, mask_gh, mask_count,
                     allowed, qkey=None, cegb_pen=None, cegb_U=None):
            trees, leaf_ids = [], []
            new_score = score
            U_new = cegb_U
            if cegb_U is not None:
                # reference parity: the lazy penalty counts rows of the
                # SAMPLED partition (bagging/GOSS) — out-of-sample rows
                # are treated as fully acquired so they carry no mass
                in_sample = mask_count > 0
                U_eff = cegb_U | ~in_sample[:, None]
            for k in range(K):
                gk = g if K == 1 else g[:, k]
                hk = h if K == 1 else h[:, k]
                gk_m = gk * mask_gh
                hk_m = hk * mask_gh
                chan_scale = None
                if use_quant:
                    kq = (None if qkey is None
                          else jax.random.fold_in(qkey, k))
                    gk_q, hk_q, chan_scale = quantize(
                        gk_m, hk_m, mask_count, kq)
                    vals = jnp.stack([gk_q, hk_q, mask_count], axis=1)
                else:
                    vals = jnp.stack([gk_m, hk_m, mask_count], axis=1)
                tree, leaf_id = grow_tree(
                    bins, vals, self.feat_num_bin, self.feat_has_nan,
                    allowed, gcfg, bins_t=bins_t,
                    is_cat=self.feat_is_cat, mono=self.feat_mono,
                    groups=self.interaction_groups,
                    bundle=self._bundle_dev, chan_scale=chan_scale,
                    node_key=(None if qkey is None
                              else jax.random.fold_in(qkey, 0xB14D + k)),
                    cegb_pen=cegb_pen, contri=self.feat_contri,
                    forced=self._forced_dev,
                    lazy=(None if cegb_U is None
                          else (U_eff, self._cegb_lazy)))
                if cegb_U is not None:
                    # class-k+1's tree sees class-k's acquisitions
                    # (the reference trains per-class trees serially
                    # and marks on split application)
                    U_new = _cegb_u_fold(U_new, tree["leaf_used"],
                                         leaf_id, in_sample)
                    U_eff = U_new | ~in_sample[:, None]
                    tree = {kk: v for kk, v in tree.items()
                            if kk != "leaf_used"}
                if use_quant and renew_quant:
                    # re-derive leaf outputs from FULL-precision sums
                    # (quant_train_renew_leaf)
                    from ..ops.split import calc_leaf_output
                    Lq = tree["leaf_value"].shape[0]
                    oh = (leaf_id[:, None]
                          == jnp.arange(Lq, dtype=jnp.int32)[None, :])
                    sums = jax.lax.dot_general(
                        oh.astype(jnp.float32),
                        jnp.stack([gk_m, hk_m], axis=1),
                        dimension_numbers=(((0,), (0,)), ((), ())),
                        precision=jax.lax.Precision.HIGHEST)   # [L, 2]
                    if gcfg.axis_name:
                        sums = jax.lax.psum(sums, gcfg.axis_name)
                    renewed = calc_leaf_output(
                        sums[:, 0], sums[:, 1], gcfg.lambda_l1,
                        gcfg.lambda_l2, gcfg.max_delta_step)
                    tree = dict(tree)
                    tree["leaf_value"] = jnp.where(
                        tree["leaf_count"] > 0, renewed,
                        tree["leaf_value"])
                new_score = new_score.at[:, k].add(
                    leaf_contrib(tree, leaf_id))
                trees.append(tree)
                leaf_ids.append(leaf_id)
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
            return stacked, jnp.stack(leaf_ids), new_score, U_new

        def step_impl(bins, bins_t, label, weight, score, mask_gh,
                      mask_count, allowed, cegb_pen, key, cegb_U=None):
            g, h = gradients(score, label, weight, key)
            return grow_all(bins, bins_t, score, g, h, mask_gh, mask_count,
                            allowed, qkey=jax.random.fold_in(key, 0x9e37),
                            cegb_pen=cegb_pen, cegb_U=cegb_U)

        # ---- tpu_debug: checkify validation pass (SURVEY.md §5) --------
        # a separate jitted checkify program (cheap: gradients only, no
        # tree growth) so the hot step stays checkify-free
        self._debug_check = None
        if bool(self.config.tpu_debug):
            from jax.experimental import checkify

            def _dbg_impl(score, label, weight, key, pos_state):
                n_bad_s = jnp.sum(~jnp.isfinite(score))
                checkify.check(
                    n_bad_s == 0,
                    "model scores contain {n} non-finite value(s) — "
                    "non-finite labels/init_score, or a previous "
                    "iteration diverged (try a lower learning_rate)",
                    n=n_bad_s)
                if getattr(obj, "has_pos_state", False):
                    s = score[:, 0] if K == 1 else score
                    g, h, _ = obj.get_gradients(s, label, weight,
                                                pos_state=pos_state)
                else:
                    g, h = gradients(score, label, weight, key)
                n_bad_g = jnp.sum(~jnp.isfinite(g))
                n_bad_h = jnp.sum(~jnp.isfinite(h))
                n_neg_h = jnp.sum(h < 0)
                checkify.check(
                    n_bad_g == 0,
                    "objective produced {n} non-finite gradient "
                    "value(s) — check labels/init_score/custom fobj",
                    n=n_bad_g)
                checkify.check(
                    n_bad_h == 0,
                    "objective produced {n} non-finite hessian "
                    "value(s) — check labels/init_score/custom fobj",
                    n=n_bad_h)
                checkify.check(
                    n_neg_h == 0,
                    "objective produced {n} negative hessian value(s) "
                    "— leaf outputs would be unbounded", n=n_neg_h)
                return n_bad_g

            self._debug_check = jax.jit(
                checkify.checkify(_dbg_impl,
                                  errors=checkify.user_checks))
            # oob-bin audit (host-side, once): every stored bin id must
            # be < the feature's bin count. (Skipped under EFB — the
            # physical bundle columns use offset bin spaces that the
            # logical feat_num_bin does not describe.)
            _ing = self.train_set.device_ingested()
            if not self.has_bundles and (
                    _ing.n_rows if _ing is not None
                    else len(self.train_set.binned)):
                nb_host = np.asarray(self.feat_num_bin)
                if _ing is not None and getattr(
                        self.train_set, "_binned", None) is None:
                    # device-resident dataset: audit the device array
                    # (pad rows are bin 0 — never the max) instead of
                    # D2H-materializing and permanently caching a full
                    # host copy just for a check
                    F_chk = min(_ing.bins.shape[1], len(nb_host))
                    col_max = np.asarray(
                        jnp.max(_ing.bins[:, :F_chk], axis=0))
                else:
                    binned_chk = self.train_set.binned
                    F_chk = min(binned_chk.shape[1], len(nb_host))
                    col_max = binned_chk[:, :F_chk].max(axis=0)
                bad = np.nonzero(col_max >= nb_host[:F_chk])[0]
                if len(bad):
                    log.fatal(f"tpu_debug: out-of-bounds bin ids in "
                              f"feature column(s) {bad.tolist()[:8]} "
                              f"(max bin {col_max[bad[0]]} >= num_bin "
                              f"{int(nb_host[bad[0]])}) — corrupt "
                              f"binned data or mismatched bin mappers")

        top_rate = float(self.config.top_rate)
        other_rate = float(self.config.other_rate)
        # goss.hpp truncates the DOUBLE product (static_cast<data_size_t>
        # of rate * cnt); an f32 floor on device can differ by one when
        # the product lands within an f32 ulp of an integer (e.g.
        # 0.35*180). The per-shard valid counts are static (padding mask
        # only — GOSS replaces bagging), so the exact counts are
        # precomputed host-side in double and closed over as constants.
        _rows_sharded = self.mesh is not None and not self._shard_features
        # Exact counts at ANY process count (VERDICT r4 item 7): the
        # per-global-shard valid row counts are assembled host-side at
        # init — single-host directly, multi-host via one counts
        # allgather (each process contributes its local devices' counts
        # in mesh order, mirroring make_array_from_process_local_data's
        # process-contiguous chunk placement) — so the double-precision
        # truncation of goss.hpp's subset sizes holds on every shard.
        if _rows_sharded:
            _local_valid = goss_shard_valid_counts(
                self.data.n, self.data.n_pad, self.mesh.devices.size,
                jax.process_count())
        else:
            _local_valid = [self.data.n]
        goss_axis = self.axis if _rows_sharded else None
        # goss.hpp floors top_k at 1 (std::max(1, top_k)); a shard with
        # zero valid rows still selects nothing because is_top is masked
        # by the valid mask
        _k_top_list = [max(1, int(v * top_rate)) for v in _local_valid]
        _k_rand_list = [int(v * other_rate) for v in _local_valid]
        goss_k_top_tbl = jnp.asarray(_k_top_list, jnp.int32)
        goss_k_rand_tbl = jnp.asarray(_k_rand_list, jnp.int32)
        # static top-k bounds (max over shards): the threshold
        # extraction below selects ORDER STATISTICS, so the full n-row
        # %sort the round-5 trace flagged (~4% of device busy) is
        # replaced by lax.top_k over the bounding k — same selected
        # values bit-for-bit, no total order materialized. Near-1.0
        # rates keep the sort (top_k at k ~ n IS a sort).
        _k_top_max = max(_k_top_list)
        _k_rand_max = max(_k_rand_list)

        def goss_masks(g, h, valid_mask, key):
            """GOSS (goss.hpp): keep top-a by |g*h|, sample b of the rest,
            amplify the sampled rest by (1-a)/b. Per-shard under the mesh,
            matching the reference's per-machine local bagging."""
            metric = jnp.abs(g * h)
            if K > 1:
                metric = jnp.sum(metric, axis=1)
            metric = metric * valid_mask
            n_local = metric.shape[0]
            n_valid = jnp.sum(valid_mask)
            sid = (jax.lax.axis_index(goss_axis)
                   if goss_axis is not None else 0)
            k_top = goss_k_top_tbl[sid]
            k_rand = goss_k_rand_tbl[sid].astype(jnp.float32)
            k_rest = jnp.maximum(n_valid - k_top, 1.0)
            if _k_top_max < n_local:
                # the k_top-th largest metric: index k_top-1 of the
                # descending top-k pool == sorted_m[n_local - k_top]
                top_pool = jax.lax.top_k(metric, _k_top_max)[0]
                thresh = top_pool[jnp.clip(k_top, 1, _k_top_max) - 1]
            else:
                sorted_m = jnp.sort(metric)
                thresh_idx = jnp.clip(n_local - k_top, 0, n_local - 1)
                thresh = sorted_m[thresh_idx]
            # EXACT top-k (goss.hpp partitions exactly k rows): ties at
            # the threshold break by row index via a cumulative count,
            # so the selected count is deterministic — required both for
            # reference parity and so the compact path's fixed buffer
            # (tpu_goss_compact) can never truncate
            valid = valid_mask > 0
            above = (metric > thresh) & valid
            k_need = k_top - jnp.sum(above).astype(jnp.int32)
            tie = (metric == thresh) & valid
            tie_rank = jnp.cumsum(tie.astype(jnp.int32))
            is_top = above | (tie & (tie_rank <= k_need))
            rest = valid & ~is_top
            # EXACT-size uniform sample of the rest (goss.hpp samples a
            # fixed-size subset): keep the k_cap smallest uniform draws
            # among rest rows — unbiased in row position, unlike a
            # Bernoulli draw truncated by prefix. Ties in the k-th draw
            # break by row index via the same cumulative-count trick as
            # the top-k side.
            k_cap = jnp.minimum(k_rand, k_rest).astype(jnp.int32)
            u = jnp.where(rest, jax.random.uniform(key, (n_local,)),
                          jnp.inf)
            if 0 < _k_rand_max < n_local:
                # the k_cap-th SMALLEST draw: ascending top-k of -u
                # bounded by the static max over shards; k_cap = 0
                # indexes the minimum, matching the clip below (picked
                # is force-emptied by the k_cap > 0 mask either way)
                u_small = -jax.lax.top_k(-u, _k_rand_max)[0]
                u_thresh = u_small[jnp.clip(k_cap - 1, 0,
                                            _k_rand_max - 1)]
            elif _k_rand_max == 0:
                # other_rate rounds to zero rows everywhere: nothing is
                # ever picked; any threshold value works
                u_thresh = jnp.float32(0.0)
            else:
                u_sorted = jnp.sort(u)
                u_thresh = u_sorted[jnp.clip(k_cap - 1, 0, n_local - 1)]
            strictly = rest & (u < u_thresh)
            at_t = rest & (u == u_thresh)
            need = k_cap - jnp.sum(strictly).astype(jnp.int32)
            at_rank = jnp.cumsum(at_t.astype(jnp.int32))
            picked = (strictly | (at_t & (at_rank <= need))) & (k_cap > 0)
            amp = (1.0 - top_rate) / max(other_rate, 1e-12)
            mask_gh = (is_top.astype(jnp.float32)
                       + picked.astype(jnp.float32) * amp)
            mask_count = (is_top | picked).astype(jnp.float32)
            return mask_gh, mask_count

        def step_goss_impl(bins, bins_t, label, weight, score, valid_mask,
                           allowed, cegb_pen, key, cegb_U=None):
            kg, km = jax.random.split(key)
            g, h = gradients(score, label, weight, kg)
            mask_gh, mask_count = goss_masks(g, h, valid_mask, km)
            return grow_all(bins, bins_t, score, g, h, mask_gh, mask_count,
                            allowed, qkey=jax.random.fold_in(key, 0x9e37),
                            cegb_pen=cegb_pen, cegb_U=cegb_U)

        def step_custom_impl(bins, bins_t, score, g, h, mask_gh,
                             mask_count, allowed, cegb_pen, key,
                             cegb_U=None):
            return grow_all(bins, bins_t, score, g, h, mask_gh, mask_count,
                            allowed, qkey=key, cegb_pen=cegb_pen,
                            cegb_U=cegb_U)

        # ---- GOSS histogram-only compaction (tpu_goss_compact) ---------
        # The masked formulation scans ALL rows with zero weights; the
        # reference's GOSS scans only the sampled subset
        # (goss.hpp bag_data_indices_). Here: ONE lax.sort moves the
        # sampled rows into a fixed-size front buffer (static n_sub >=
        # worst-case sample), HISTOGRAMS scan only that buffer, and the
        # full-row leaf_id partition + one-hot score update stay exactly
        # as in the masked path (perf.md measured them cheap — the
        # round-2 traversal-based score update is what made full
        # compaction lose). Sample choice is bit-identical to the
        # masked path (same RNG stream); histogram float sums may
        # differ only in accumulation order (exact in quantized mode).
        renews_obj = (type(obj).renew_tree_output
                      is not Objective.renew_tree_output)
        # Round 3 compacted via ONE multi-operand lax.sort, whose
        # superlinear compile cost gated it to F <= ~32 packed columns.
        # Round 4 replaced the sort with the Pallas row-compaction
        # kernel (ops/compact.py): per-block permutation matmuls at any
        # width (~5 ms vs 13 ms at 1M x 28, and Bosch F=200 / Criteo /
        # MSLR widths now compact too — docs/perf.md "Row compaction
        # kernel").
        import math as _math
        from ..ops.compact import (compact_rows, compact_rows_xla,
                                   compaction_out_cols, plan_compaction)
        # compaction block size: <= 1024 (kernel VMEM budget) and a
        # divisor of n_pad (which is a rows_per_block multiple); a
        # degenerate divisor (odd tpu_rows_per_block values) would
        # shred the kernel grid into sub-lane-width matmuls, so those
        # shapes keep the masked path
        R_c = _math.gcd(1024, gcfg.rows_per_block)
        frac = top_rate + other_rate
        n_sub = compaction_out_cols(
            int(np.ceil(self.data.n_pad * frac)) + 8192,
            R_c, gcfg.rows_per_block)
        use_goss_compact = (bool(self.config.tpu_goss_compact)
                           and self.config.data_sample_strategy == "goss"
                           and mesh is None and not self.has_bundles
                           and not self.linear_tree and not renews_obj
                           and not (use_quant and renew_quant)
                           and not getattr(obj, "has_pos_state", False)
                           and top_rate + other_rate < 1.0
                           and R_c >= 256
                           # the compacted buffer (sampled rows + write
                           # slack) must genuinely shrink the scan; tiny
                           # datasets / near-1.0 fractions keep the
                           # masked path (also guarantees the kernel's
                           # write windows never clamp = never drop a
                           # sampled row)
                           and n_sub < self.data.n_pad
                           # the XLA scatter fallback serializes ON TPU
                           # (docs/perf.md) — without the Pallas path
                           # (max_bin>256 / tpu_double_precision_hist /
                           # tpu_use_pallas=false) keep the masked scan
                           and (self.use_pallas
                                or jax.default_backend() != "tpu"))
        self._use_goss_compact = use_goss_compact
        # the partition-move probe (hist.partition_ms) must time the
        # shape the grow loop actually repartitions: the compacted
        # buffer under GOSS hist-compact, the full padded rows otherwise
        self._goss_n_sub = n_sub if use_goss_compact else None

        # ---- buffer donation (tpu_donate; docs/perf.md "Iteration
        # floor"): the r5 trace pins ~9% of device busy on loop-state
        # %copy — donate the carries so XLA aliases them in place.
        # The [n_pad, K] score carry is donation-safe only when no
        # host path re-reads the PRE-step buffer after dispatch:
        # leaf-output renewal reads the old score for its percentile
        # refit, linear leaves read score_pre in _apply_linear_fit,
        # and DART/RF blend with held pre-step score/valid buffers
        # (those engines set _donate_carries=False).
        from ..utils.debug import donation_enabled, donation_guard
        _donate = donation_enabled(self.config)
        _donate_score = (_donate and self._donate_carries
                         and not renews_obj and not self.linear_tree)
        _donate_valid = _donate and self._donate_carries
        _dbg_checks = bool(self.config.tpu_debug_checks)

        def _jit_don(fn, don, site):
            # jit with donation; tpu_debug_checks wraps DONATING jits
            # in the use-after-donate guard — a jit that donates
            # nothing cannot use-after-donate, and wrapping it would
            # only misattribute an unrelated deleted-array error to
            # this site (plus pay a per-call leaf scan for nothing)
            j = jax.jit(fn, donate_argnums=don)
            return donation_guard(j, site) if (don and _dbg_checks) \
                else j
        if use_goss_compact:
            dd = self.data
            n_full = dd.n_pad

            def step_goss_compact_impl(bins, bins_t, label, weight,
                                       valid_mask, score, allowed,
                                       cegb_pen, key, cegb_U=None):
                kg, km = jax.random.split(key)
                g, h = gradients(score, label, weight, kg)
                mask_gh, mask_count = goss_masks(g, h, valid_mask, km)
                sel = mask_count > 0
                # TPU note: jnp.nonzero / gathers at computed indices
                # lower to serialized scatter/slice loops (~1s at 1M
                # rows). The compaction kernel moves the sampled rows
                # into a fixed-size front buffer with per-block one-hot
                # permutation matmuls instead; grad/hess/masks ride as
                # value channels of the same kernel call.
                g2 = g if K > 1 else g[:, None]
                h2 = h if K > 1 else h[:, None]
                vals_all = jnp.concatenate(
                    [g2.T, h2.T, mask_gh[None], mask_count[None]],
                    axis=0).astype(jnp.float32)       # [2K+2, n]
                dest, algn, rem = plan_compaction(sel, R_c, n_sub)
                if bins_t is not None:
                    bins_t_c, vc = compact_rows(
                        bins_t, vals_all, dest, algn, rem,
                        out_cols=n_sub, rows_per_block=R_c)
                    # int8 -> uint8 reinterpret restores bin values for
                    # the row-major partition path
                    bins_c = bins_t_c.T.astype(bins.dtype)
                else:
                    bt_any, vc = compact_rows_xla(
                        bins.T, vals_all, dest, algn, rem,
                        out_cols=n_sub, rows_per_block=R_c)
                    bins_c = bt_any.T
                    bins_t_c = None
                g_c = vc[:K].T
                h_c = vc[K:2 * K].T
                mgh_c = vc[2 * K]
                mc_c = vc[2 * K + 1]
                qkey = jax.random.fold_in(key, 0x9e37)
                import dataclasses as _dc
                gcfg_c = _dc.replace(gcfg, hist_compact=True)
                trees, leaf_ids = [], []
                new_score = score
                U_new = cegb_U
                if cegb_U is not None:
                    in_sample = sel
                    U_eff = cegb_U | ~in_sample[:, None]
                for k in range(K):
                    gk = g_c[:, k] * mgh_c
                    hk = h_c[:, k] * mgh_c
                    chan_scale = None
                    if use_quant:
                        kq = jax.random.fold_in(qkey, k)
                        gk, hk, chan_scale = quantize(gk, hk, mc_c, kq)
                    vals_c = jnp.stack([gk, hk, mc_c], axis=1)
                    tree, leaf_id = grow_tree(
                        bins, vals_c, self.feat_num_bin,
                        self.feat_has_nan, allowed, gcfg_c,
                        bins_t=bins_t, is_cat=self.feat_is_cat,
                        mono=self.feat_mono,
                        groups=self.interaction_groups,
                        chan_scale=chan_scale,
                        node_key=jax.random.fold_in(qkey, 0xB14D + k),
                        cegb_pen=cegb_pen, contri=self.feat_contri,
                        compact=(bins_c, bins_t_c, vals_c),
                        forced=self._forced_dev,
                        lazy=(None if cegb_U is None
                              else (U_eff, self._cegb_lazy)))
                    if cegb_U is not None:
                        U_new = _cegb_u_fold(U_new, tree["leaf_used"],
                                             leaf_id, in_sample)
                        U_eff = U_new | ~in_sample[:, None]
                        tree = {kk: v for kk, v in tree.items()
                                if kk != "leaf_used"}
                    # FULL leaf ids came from the in-loop partition; the
                    # score update is the same one-hot matmul as the
                    # masked path (no per-row traversal)
                    new_score = new_score.at[:, k].add(
                        leaf_contrib(tree, leaf_id))
                    trees.append(tree)
                    leaf_ids.append(leaf_id)
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
                return stacked, jnp.stack(leaf_ids), new_score, U_new

            # donate cegb_U so the lazy-acquisition matrix updates in
            # place ([n_pad, F_pad] bool — 2.5 GB at 10M x 256) instead
            # of holding two copies across the step, plus the score
            # carry when nothing re-reads it (tpu_donate)
            _don_c = (((9,) if _donate else ())
                      + ((5,) if _donate_score else ()))
            _compact_j = _jit_don(step_goss_compact_impl, _don_c,
                                  "the GOSS-compact step's donated "
                                  "score")

            def _step_goss_compact(score, allowed, cegb_pen, key):
                return _compact_j(dd.bins, dd.bins_t, dd.label,
                                  dd.weight, dd.valid_mask, score,
                                  allowed, cegb_pen, key,
                                  self._cegb_U_arg())

            self._step_goss_compact = _step_goss_compact
        else:
            self._step_goss_compact = None

        def valid_update_impl(valid_bins_scores, stacked_trees):
            # apply this iteration's K trees to each valid set's raw scores
            out = []
            for bins, vscore in valid_bins_scores:
                new = vscore
                for k in range(K):
                    tree_k = jax.tree.map(lambda a, k=k: a[k],
                                          stacked_trees)
                    vals, _ = tree_predict_binned(
                        tree_k, bins, self.feat_num_bin, self.feat_has_nan)
                    new = new.at[:, k].add(vals * lr)
                out.append(new)
            return out

        # NOTE on jit boundaries: device arrays CLOSED OVER by a jitted
        # function are embedded into the lowered HLO as constants, so the
        # (remote) compile payload grows with the dataset. Every step jit
        # below therefore takes the big arrays as ARGUMENTS; thin Python
        # wrappers supply them per call (no transfer cost — they are
        # device-resident).
        # valid scores are a pure carry on the engines that donate
        # (every reader sees only the reassigned list): donate them so
        # each per-iteration valid update aliases in place too
        _valid_update_j = _jit_don(
            lambda vbins, valid_scores, stacked_trees: valid_update_impl(
                list(zip(vbins, valid_scores)), stacked_trees),
            (1,) if _donate_valid else (),
            "the valid-update's donated scores")

        def plain_valid_update(valid_scores, stacked_trees):
            vbins = tuple(self.valid_data[i].bins
                          for i in range(len(valid_scores)))
            return _valid_update_j(vbins, tuple(valid_scores),
                                   stacked_trees)

        if mesh is None:
            d = self.data
            _step_j = _jit_don(
                step_impl,
                (((10,) if _donate else ())
                 + ((4,) if _donate_score else ())),
                "the step's donated score")
            _goss_j = _jit_don(
                step_goss_impl,
                (((9,) if _donate else ())
                 + ((4,) if _donate_score else ())),
                "the GOSS step's donated score")
            _custom_j = _jit_don(
                step_custom_impl,
                (((10,) if _donate else ())
                 + ((2,) if _donate_score else ())),
                "the custom-fobj step's donated score")

            def step(score, mask_gh, mask_count, allowed, cegb_pen, key):
                return _step_j(d.bins, d.bins_t, d.label, d.weight, score,
                               mask_gh, mask_count, allowed, cegb_pen,
                               key, self._cegb_U_arg())

            def step_goss(score, allowed, cegb_pen, key):
                return _goss_j(d.bins, d.bins_t, d.label, d.weight,
                               score, d.valid_mask, allowed, cegb_pen,
                               key, self._cegb_U_arg())

            def step_custom(score, g, h, mask_gh, mask_count, allowed,
                            cegb_pen, key):
                return _custom_j(d.bins, d.bins_t, score, g, h,
                                 mask_gh, mask_count, allowed, cegb_pen,
                                 key, self._cegb_U_arg())

            if getattr(obj, "has_pos_state", False):
                # stateful objective: gradients also return updated
                # position-bias state, threaded by train_one_iter
                def grads_state(score, label, weight, pos_state):
                    s = score[:, 0] if K == 1 else score
                    return obj.get_gradients(s, label, weight,
                                             pos_state=pos_state)

                def _state_impl(bins, bins_t, label, weight, score,
                                mask_gh, mask_count, allowed, cegb_pen,
                                key, pos_state):
                    g, h, new_state = grads_state(score, label, weight,
                                                  pos_state)
                    stacked, lids, ns, _ = grow_all(
                        bins, bins_t, score, g, h, mask_gh,
                        mask_count, allowed,
                        qkey=jax.random.fold_in(key, 0x9e37),
                        cegb_pen=cegb_pen)
                    return stacked, lids, ns, new_state

                def _goss_state_impl(bins, bins_t, label, weight, score,
                                     valid_mask, allowed, cegb_pen, key,
                                     pos_state):
                    kg, km = jax.random.split(key)
                    g, h, new_state = grads_state(score, label, weight,
                                                  pos_state)
                    mask_gh, mask_count = goss_masks(g, h, valid_mask,
                                                     km)
                    stacked, lids, ns, _ = grow_all(
                        bins, bins_t, score, g, h, mask_gh,
                        mask_count, allowed,
                        qkey=jax.random.fold_in(key, 0x9e37),
                        cegb_pen=cegb_pen)
                    return stacked, lids, ns, new_state

                _don_st = (4,) if _donate_score else ()
                _state_j = _jit_don(_state_impl, _don_st,
                                    "the stateful step's donated score")
                _goss_state_j = _jit_don(
                    _goss_state_impl, _don_st,
                    "the stateful GOSS step's donated score")

                def step_state(score, mask_gh, mask_count, allowed,
                               cegb_pen, key, pos_state):
                    return _state_j(d.bins, d.bins_t, d.label, d.weight,
                                    score, mask_gh, mask_count, allowed,
                                    cegb_pen, key, pos_state)

                def step_goss_state(score, allowed, cegb_pen, key,
                                    pos_state):
                    return _goss_state_j(d.bins, d.bins_t, d.label,
                                         d.weight, score, d.valid_mask,
                                         allowed, cegb_pen, key,
                                         pos_state)

                self._step_state = step_state
                self._step_goss_state = step_goss_state

            valid_update = plain_valid_update
        else:
            # SPMD distributed: data/voting shard rows over the mesh axis
            # (histograms psum / psum_scatter / vote-reduce inside
            # grow_tree per GrowConfig); feature-parallel shards COLUMNS,
            # replicating rows, with the split search sliced per device
            # and the winner elected by all_gather. Tree decisions end up
            # replicated either way — mirroring the reference parallel
            # learners' global sync (SURVEY.md §3.4) without any
            # per-split host round-trip.
            # check_vma=False: the varying-manual-axes checker cannot
            # trace through grow_tree's nested jit + Pallas call (tested:
            # TypeError in the histogram scan); replication correctness
            # is covered instead by the serial-equivalence tests at
            # rtol=1e-4 under precise histograms
            # (tests/test_distributed.py).
            from ..parallel.mesh import P, shard_map
            d = self.data
            ax = self.axis
            rep = P()
            if self._shard_features:
                row2 = rep          # rows replicated
                row1 = rep
                bins_spec = P(None, ax)     # [n, F] columns sharded
                bt_spec = P(ax, None)       # [F, n]
                leaf_id_spec = rep
            else:
                row2 = P(ax, None)
                row1 = P(ax)
                bins_spec = row2
                bt_spec = P(None, ax)       # [F, n] sharded over rows
                leaf_id_spec = P(None, ax)
            tree_keys = ["num_leaves", "split_feature", "threshold_bin",
                         "default_left", "left_child", "right_child",
                         "split_gain", "internal_value", "internal_count",
                         "leaf_value", "leaf_count", "leaf_weight",
                         # rows-scanned telemetry: psum'd inside
                         # grow_tree, so replicated like the tree
                         "hist_rows"]
            if self.has_categorical:
                tree_keys += ["is_cat", "cat_bitset"]
            tree_specs = {k: rep for k in tree_keys}
            # 4th output = cegb_U (always None under mesh — lazy CEGB
            # requires the serial learner; the spec matches structure
            # only, None carries no leaves)
            out_specs = (tree_specs, leaf_id_spec, row2, None)

            w_spec = rep if d.weight is None else row1
            sharded_step = shard_map(
                step_impl, mesh=mesh,
                in_specs=(bins_spec, bt_spec, row1, w_spec, row2, row1,
                          row1, rep, rep, rep),
                out_specs=out_specs, check_vma=False)
            sharded_goss = shard_map(
                step_goss_impl, mesh=mesh,
                in_specs=(bins_spec, bt_spec, row1, w_spec, row2, row1,
                          rep, rep, rep),
                out_specs=out_specs, check_vma=False)
            grad_spec = row2 if K > 1 else row1
            sharded_custom = shard_map(
                step_custom_impl, mesh=mesh,
                in_specs=(bins_spec, bt_spec, row2, grad_spec, grad_spec,
                          row1, row1, rep, rep, rep),
                out_specs=out_specs, check_vma=False)

            # the sharded score carry donates like the serial one: the
            # mesh-sharded [n_pad, K] global array aliases shard-wise
            _sh_step_j = _jit_don(
                sharded_step, (4,) if _donate_score else (),
                "the sharded step's donated score")
            _sh_goss_j = _jit_don(
                sharded_goss, (4,) if _donate_score else (),
                "the sharded GOSS step's donated score")
            _sh_custom_j = _jit_don(
                sharded_custom, (2,) if _donate_score else (),
                "the sharded custom-fobj step's donated score")

            def step(score, mask_gh, mask_count, allowed, cegb_pen, key):
                return _sh_step_j(d.bins, d.bins_t, d.label, d.weight,
                                  score, mask_gh, mask_count, allowed,
                                  cegb_pen, key)

            def step_goss(score, allowed, cegb_pen, key):
                return _sh_goss_j(d.bins, d.bins_t, d.label, d.weight,
                                  score, d.valid_mask, allowed,
                                  cegb_pen, key)

            def step_custom(score, g, h, mask_gh, mask_count, allowed,
                            cegb_pen, key):
                return _sh_custom_j(d.bins, d.bins_t, score, g, h,
                                    mask_gh, mask_count, allowed,
                                    cegb_pen, key)

            if self._shard_features:
                # feature-parallel valid sets are replicated (prediction
                # needs all columns); plain jit, no shard_map
                valid_update = plain_valid_update
            else:
                def _sh_valid_impl(valid_scores, stacked_trees):
                    n_valid = len(valid_scores)
                    fn = shard_map(
                        lambda bins_scores, trees: tuple(valid_update_impl(
                            list(bins_scores), trees)),
                        mesh=mesh,
                        in_specs=(tuple((row2, row2)
                                        for _ in range(n_valid)),
                                  tree_specs),
                        out_specs=tuple(row2 for _ in range(n_valid)),
                        check_vma=False)
                    pairs = tuple((self.valid_data[i].bins, s)
                                  for i, s in enumerate(valid_scores))
                    return list(fn(pairs, stacked_trees))

                valid_update = _jit_don(
                    _sh_valid_impl, (0,) if _donate_valid else (),
                    "the sharded valid-update's donated scores")

        @jax.jit
        def apply_renewed(score, leaf_ids, renewed_leaf_values):
            # re-apply renewed leaf outputs: score = score + lr * renewed
            for k in range(K):
                contrib = renewed_leaf_values[k][leaf_ids[k]] * lr
                score = score.at[:, k].add(contrib)
            return score

        # ---- fused multi-iteration chunk (one dispatch per n iters) ----
        # Over a tunneled TPU each jit dispatch costs a latency round-trip
        # (~80ms); scanning the whole boosting step amortizes it. Only the
        # pure-jit path qualifies (checked in train_chunk). Keyed by the
        # bare goss_now bool train_chunk looks up.
        self._chunk_cache: Dict[bool, Callable] = {}
        F = self.num_features

        def make_chunk(goss: bool):
            allowed_all = jnp.asarray(np.arange(self.F_pad) < F)
            d_ = self.data

            def chunk_impl(bins, bins_t, label, weight, score, valid_mask,
                           keys):
                def body(sc, bkey):
                    # lazy CEGB is chunk-ineligible (can_fuse_iters),
                    # so the steps' cegb_U output is always None here
                    if goss and use_goss_compact:
                        stacked, _lid, ns, _ = step_goss_compact_impl(
                            bins, bins_t, label, weight, valid_mask,
                            sc, allowed_all, None, bkey)
                    elif goss:
                        stacked, _lid, ns, _ = step_goss_impl(
                            bins, bins_t, label, weight, sc, valid_mask,
                            allowed_all, None, bkey)
                    else:
                        stacked, _lid, ns, _ = step_impl(
                            bins, bins_t, label, weight, sc, valid_mask,
                            valid_mask, allowed_all, None, bkey)
                    return ns, stacked
                return jax.lax.scan(body, score, keys)

            # the chunk carry donates whenever the per-step score does
            # (can_fuse_iters already excludes every host re-reader):
            # without it the [n_pad, K] score rides an H2H copy through
            # EVERY chunk even though the per-step jits alias theirs
            if mesh is None:
                _chunk_j = _jit_don(
                    chunk_impl, (4,) if _donate_score else (),
                    "the fused chunk's donated score")

                def chunk(score, keys):
                    return _chunk_j(d_.bins, d_.bins_t, d_.label,
                                    d_.weight, score, d_.valid_mask,
                                    keys)
                return chunk

            sharded_chunk = shard_map(
                chunk_impl, mesh=mesh,
                in_specs=(bins_spec, bt_spec, row1, w_spec, row2, row1,
                          rep),
                out_specs=(row2, tree_specs), check_vma=False)

            _sh_chunk_j = _jit_don(
                sharded_chunk, (4,) if _donate_score else (),
                "the sharded fused chunk's donated score")

            def chunk(score, keys):
                return _sh_chunk_j(d_.bins, d_.bins_t, d_.label,
                                   d_.weight, score, d_.valid_mask,
                                   keys)
            return chunk

        self._make_chunk = make_chunk

        self._step = step
        self._step_goss = step_goss
        self._step_custom = step_custom
        self._valid_update = valid_update
        self._apply_renewed = apply_renewed

    # ------------------------------------------------------------------
    def _probe_partition_move(self) -> None:
        """One timed repartition move at the real data shape, recorded
        as the ``hist.partition_ms`` gauge. The in-training move is
        fused into the jitted growth while_loop where host timers
        cannot see it; this standalone probe (worst case: half the rows
        move) is the number the enable/disable decision trades against
        per-round scan savings (docs/perf.md "Partitioned
        histograms")."""
        import time as _time

        from ..ops import partition as part_ops
        d = self.data
        # under GOSS hist-compact the in-loop move operates on the
        # compacted buffer, not the full rows — time THAT shape, or the
        # gauge overstates the cost by ~1/(top_rate+other_rate)
        n = self._goss_n_sub or d.n_pad
        full = self._goss_n_sub is None
        moved = jnp.asarray((np.arange(n) & 1).astype(bool))
        F_h = d.bins.shape[1]
        if self.use_pallas:
            def mv(bins_t, vals_t, mvd):
                _, n_front, _ = part_ops.plan_split_move(mvd)
                return part_ops.move_cols_tpu(bins_t, vals_t, mvd,
                                              n_front, self.part_rpb)
            args = (d.bins_t if full else jnp.zeros((F_h, n), jnp.int8),
                    jnp.zeros((4, n), jnp.float32), moved)
        else:
            def mv(bins, vals, mvd):
                dest, _, _ = part_ops.plan_split_move(mvd)
                return part_ops.move_rows_xla([bins, vals], dest)
            args = (d.bins if full
                    else jnp.zeros((n, F_h), d.bins.dtype),
                    jnp.zeros((n, 4), jnp.float32), moved)
        fn = jax.jit(mv)
        jax.block_until_ready(fn(*args))          # compile
        t0 = _time.perf_counter()
        jax.block_until_ready(fn(*args))
        obs.set_gauge("hist.partition_ms",
                      (_time.perf_counter() - t0) * 1e3)

    # ------------------------------------------------------------------
    def _cegb_U_arg(self) -> Optional[jnp.ndarray]:
        """Device [n_pad, F_pad] per-row feature-acquisition matrix for
        the lazy CEGB penalty; padding rows start fully acquired so
        they never contribute penalty mass."""
        if self._cegb_lazy is None:
            return None
        if self._cegb_U is None:
            m = np.zeros((self.data.n_pad, self.F_pad), bool)
            m[self.data.n:] = True
            self._cegb_U = jnp.asarray(m)
        return self._cegb_U

    def _cegb_pen(self) -> Optional[jnp.ndarray]:
        """Per-feature coupled CEGB penalty ([F_pad]); zero for features
        the model already uses. None when CEGB is off (the split-cost
        part is static in GrowConfig)."""
        if self._cegb_coupled is None:
            return None
        if self._cegb_pen_cache is None:
            self._cegb_pen_cache = jnp.asarray(
                np.where(self._cegb_used, 0.0, self._cegb_coupled)
                .astype(np.float32))
        return self._cegb_pen_cache

    def _feature_mask(self) -> jnp.ndarray:
        F = self.num_features
        frac = self.config.feature_fraction
        mask = np.zeros(self.F_pad, dtype=bool)
        if frac >= 1.0 or F == 0:
            mask[:F] = True
        else:
            k = max(1, int(np.ceil(F * frac)))
            chosen = self._rng_feature.choice(F, size=k, replace=False)
            mask[chosen] = True
        return jnp.asarray(mask)

    def _bagging_masks(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Returns (mask_gh, mask_count) incorporating row validity."""
        c = self.config
        d = self.data
        use_bagging = (c.bagging_freq > 0
                       and (c.bagging_fraction < 1.0
                            or c.pos_bagging_fraction < 1.0
                            or c.neg_bagging_fraction < 1.0))
        if not use_bagging:
            return d.valid_mask, d.valid_mask
        if self._bag_mask is None or self.iter_ % c.bagging_freq == 0:
            n = d.n
            label = None
            if (c.pos_bagging_fraction < 1.0
                    or c.neg_bagging_fraction < 1.0):
                label = np.asarray(self.train_set.metadata.label)
                pos = label > 0
                keep = np.zeros(n, dtype=np.float32)
                keep[pos] = (self._rng_bagging.rand(int(pos.sum()))
                             < c.pos_bagging_fraction)
                keep[~pos] = (self._rng_bagging.rand(int((~pos).sum()))
                              < c.neg_bagging_fraction)
            else:
                keep = (self._rng_bagging.rand(n)
                        < c.bagging_fraction).astype(np.float32)
            full = np.zeros(d.n_pad, dtype=np.float32)
            full[:n] = keep
            self._bag_mask = d._place(full)
        return self._bag_mask, self._bag_mask

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> None:
        """One boosting iteration (optionally with custom fobj grads)."""
        score_pre = self.score       # gradient point (linear-leaf refit)
        allowed = self._feature_mask()
        key = jax.random.PRNGKey(self.config.objective_seed + self.iter_)
        # GOSS kicks in after 1/learning_rate iterations (goss.hpp keeps
        # the first iterations un-subsampled)
        goss_active = (
            self.config.data_sample_strategy == "goss" and grad is None
            and self.iter_ >= int(1.0 / max(self.config.learning_rate,
                                            1e-6)))
        if self._debug_check is not None:
            from jax.experimental import checkify as _checkify
            if grad is not None:
                # custom-fobj grads arrive host-side: validate directly
                for nm, a in (("gradient", grad), ("hessian", hess)):
                    bad = int(np.sum(~np.isfinite(np.asarray(a))))
                    if bad:
                        log.fatal(
                            f"tpu_debug at iteration {self.iter_}: "
                            f"custom fobj produced {bad} non-finite "
                            f"{nm} value(s)")
            else:
                err, _ = self._debug_check(
                    self.score, self.data.label, self.data.weight, key,
                    self._pos_state)
                try:
                    err.throw()
                except _checkify.JaxRuntimeError as e:
                    log.fatal(f"tpu_debug at iteration {self.iter_}: "
                              f"{e}")
        cegb_U_new = None
        # the fused XLA step dispatch (gradients + grow + split + score
        # apply run as ONE device program, so the host can only time
        # the dispatch boundary; completion lands in train/fetch_trees
        # where the tree arrays materialize)
        with obs.span("train/step", iteration=self.iter_):
            if grad is not None:
                mask_gh, mask_count = self._bagging_masks()
                g = self._pad_custom(grad)
                h = self._pad_custom(hess)
                stacked, leaf_ids, new_score, cegb_U_new = \
                    self._step_custom(
                        self.score, g, h, mask_gh, mask_count, allowed,
                        self._cegb_pen(), key)
            elif goss_active:
                if self._pos_state is not None:
                    stacked, leaf_ids, new_score, self._pos_state = \
                        self._step_goss_state(self.score, allowed,
                                              self._cegb_pen(), key,
                                              self._pos_state)
                elif self._step_goss_compact is not None:
                    stacked, leaf_ids, new_score, cegb_U_new = \
                        self._step_goss_compact(
                            self.score, allowed, self._cegb_pen(), key)
                else:
                    stacked, leaf_ids, new_score, cegb_U_new = \
                        self._step_goss(
                            self.score, allowed, self._cegb_pen(), key)
            else:
                mask_gh, mask_count = self._bagging_masks()
                if self._pos_state is not None:
                    stacked, leaf_ids, new_score, self._pos_state = \
                        self._step_state(self.score, mask_gh, mask_count,
                                         allowed, self._cegb_pen(), key,
                                         self._pos_state)
                else:
                    stacked, leaf_ids, new_score, cegb_U_new = self._step(
                        self.score, mask_gh, mask_count, allowed,
                        self._cegb_pen(), key)
        # start device->host copies of the (tiny) tree arrays immediately:
        # over a tunneled TPU each sync transfer is a latency round-trip,
        # so issue them all async and overlap with the step itself
        for leaf in jax.tree.leaves(stacked):
            leaf.copy_to_host_async()
        # leaf-output renewal (L1/quantile/MAPE percentile re-fit,
        # ObjectiveFunction::RenewTreeOutput): recompute leaf values from
        # per-leaf residual percentiles of the PRE-update score, then
        # redo the score update with the renewed values
        renews = (grad is None
                  and type(self.objective).renew_tree_output
                  is not Objective.renew_tree_output)
        if renews:
            label = np.asarray(self.train_set.metadata.label)
            weight = self.train_set.metadata.weight
            old = np.asarray(self.score)[:self.data.n]
            lid = np.asarray(leaf_ids)[:, :self.data.n]
            renewed = np.stack([
                self.objective.renew_tree_output(
                    old[:, k], label, weight, lid[k],
                    self.config.num_leaves)
                for k in range(self.num_class)]).astype(np.float32)
            renewed_dev = jnp.asarray(renewed)
            stacked = dict(stacked)
            stacked["leaf_value"] = renewed_dev
            new_score = self._apply_renewed(self.score, leaf_ids,
                                            renewed_dev)
        self.score = new_score
        if self.valid_scores:
            with obs.span("train/valid_update"):
                self.valid_scores = self._valid_update(self.valid_scores,
                                                       stacked)
        with obs.span("train/fetch_trees"):
            host_trees = self._fetch_tree_arrays(stacked)
        self._append_host_trees(host_trees)
        obs.inc("train.iterations")
        obs.heartbeat("train")
        if cegb_U_new is not None:
            # device-side acquisition fold already ran inside the step
            # (_cegb_u_fold): in-sample rows acquired their leaf-path
            # features for each class tree
            self._cegb_U = cegb_U_new
        if self.linear_tree and grad is None:
            self._apply_linear_fit(leaf_ids, score_pre)
            self._invalidate_forest_cache()   # leaves refined in place
        if self.config.tpu_debug_checks:
            # NaN/inf guard (aux failure-detection subsystem): catch
            # divergence at the iteration that produced it
            for t in self.models[-self.num_class:]:
                if not np.isfinite(t.leaf_value).all():
                    log.fatal(f"Non-finite leaf values at iteration "
                              f"{self.iter_} — check learning_rate/"
                              f"objective inputs")
            if not np.isfinite(np.asarray(self.score)).all():
                log.fatal(f"Non-finite training scores at iteration "
                          f"{self.iter_}")
        self.iter_ += 1

    def _apply_linear_fit(self, leaf_ids, score_pre) -> None:
        """Refine the just-grown trees' leaves with per-leaf weighted
        ridge models and patch the train/valid scores with the delta
        (LinearTreeLearner semantics; learner/linear.py)."""
        from ..learner.linear import fit_linear_leaves, predict_linear
        K = self.num_class
        n = self.data.n
        Xu = self.train_set._raw_for_linear
        old = np.asarray(score_pre)[:n]
        lid = np.asarray(leaf_ids)[:, :n]
        sc = jnp.asarray(old[:, 0] if K == 1 else old)
        label = jnp.asarray(self.train_set.metadata.label)
        w = self.train_set.metadata.weight
        w = None if w is None else jnp.asarray(w)
        if getattr(self.objective, "has_pos_state", False):
            # post-update state (the pre-update state is gone by now);
            # the propensity drift between two iterations is negligible
            # for the leaf refit
            g, h, _ = self.objective.get_gradients(
                sc, label, w, pos_state=self._pos_state)
        elif getattr(self.objective, "needs_rng", False):
            # the SAME key the grown tree's gradients used
            g, h = self.objective.get_gradients(
                sc, label, w, key=jax.random.PRNGKey(
                    self.config.objective_seed + self.iter_))
        else:
            g, h = self.objective.get_gradients(sc, label, w)
        g = np.asarray(g).reshape(n, -1)
        h = np.asarray(h).reshape(n, -1)
        bag = None
        if self._bag_mask is not None:
            bag = np.asarray(self._bag_mask)[:n]
        deltas = np.zeros((self.data.n_pad, K), dtype=np.float32)
        for k in range(K):
            t = self.models[-K + k]
            # mask BOTH g and h so out-of-bag rows drop out of both
            # sides of the normal equations
            hk = h[:, k] if bag is None else h[:, k] * bag
            gk = g[:, k] if bag is None else g[:, k] * bag
            delta = fit_linear_leaves(
                t, lid[k], Xu, gk, hk, self.config.lambda_l2,
                self.config.linear_lambda, self._learning_rate())
            deltas[:n, k] = delta
        self.score = self.score + self.data._place(deltas, extra_dims=2)
        for vi, dd in enumerate(self.valid_data):
            Xv = getattr(self._valid_ds[vi], "_raw_for_linear", None)
            if Xv is None:
                if not getattr(self, "_warned_valid_linear", False):
                    log.warning(
                        "valid set was constructed without linear_tree "
                        "params; its eval metrics track constant leaves,"
                        " not the linear model")
                    self._warned_valid_linear = True
                continue
            vdeltas = np.zeros((dd.n_pad, K), dtype=np.float32)
            for k in range(K):
                t = self.models[-K + k]
                if not getattr(t, "is_linear", False):
                    continue
                leaf = t.predict_leaf_raw(Xv)
                dv = predict_linear(t, Xv, leaf) - t.leaf_value[leaf]
                vdeltas[:dd.n, k] = dv
            self.valid_scores[vi] = (self.valid_scores[vi]
                                     + dd._place(vdeltas, extra_dims=2))

    def _fetch_tree_arrays(self, stacked) -> Dict[str, np.ndarray]:
        """Device->host transfer of the stacked tree arrays: issue every
        copy async first (over a tunneled TPU each sync transfer is a
        latency round-trip), then materialize."""
        for leaf in jax.tree.leaves(stacked):
            leaf.copy_to_host_async()
        return jax.tree.map(np.asarray, stacked)

    def _append_host_trees(self, host: Dict[str, np.ndarray]) -> None:
        """Append one iteration's K per-class trees (host arrays with a
        leading class dim) to the model list."""
        if "hist_rows" in host:
            # rows the histogram scans touched (all K class trees):
            # masked path = n x rounds, partitioned = sum of elected
            # children's padded spans (the structural win this metric
            # exists to watch — docs/perf.md "Partitioned histograms")
            host = dict(host)
            obs.inc("hist.rows_scanned",
                    float(np.sum(host.pop("hist_rows"))))
        for k in range(self.num_class):
            arrays = {key: v[k] for key, v in host.items()}
            t = Tree.from_device(
                arrays, self._learning_rate(),
                self.train_set.bin_mappers, self.train_set.used_features)
            if self._cegb_used is not None and t.num_nodes:
                newly = np.setdiff1d(t.split_feature[:t.num_nodes],
                                     np.flatnonzero(self._cegb_used))
                if len(newly):
                    self._cegb_used[newly] = True
                    self._cegb_pen_cache = None   # refresh on next step
            self.models.append(t)
        self._invalidate_forest_cache()

    def _invalidate_forest_cache(self) -> None:
        """The model list changed (or trees mutated in place): drop the
        stacked-forest device cache and bump the version every consumer
        keys on (engine predict, Booster._to_host_model)."""
        self._models_version = getattr(self, "_models_version", 0) + 1
        self._stack_cache = None
        self._shap_cache = None

    def can_fuse_iters(self) -> bool:
        """True when boosting iterations are expressible as one scanned
        device program: no custom fobj, no host-side leaf renewal, no
        host-RNG bagging, no per-tree feature sampling, no valid-set score
        carries."""
        c = self.config
        renews = (type(self.objective).renew_tree_output
                  is not Objective.renew_tree_output)
        use_bagging = (c.bagging_freq > 0
                       and (c.bagging_fraction < 1.0
                            or c.pos_bagging_fraction < 1.0
                            or c.neg_bagging_fraction < 1.0))
        return (self.fobj is None and not renews and not use_bagging
                and c.feature_fraction >= 1.0 and not self.valid_data
                and self._cegb_coupled is None
                and self._cegb_lazy is None and not self.linear_tree
                and not c.tpu_debug_checks and not c.tpu_debug
                and self._pos_state is None)

    def train_chunk(self, n_iters: int) -> None:
        """Run ``n_iters`` boosting iterations in one device dispatch
        (``lax.scan`` over the fused step). Produces the same models as
        ``n_iters`` calls of train_one_iter (same per-iter RNG keys);
        falls back to the per-iter loop when ineligible."""
        if n_iters <= 0:
            return
        c = self.config
        if n_iters == 1 or c.tpu_fuse_iters <= 1 \
                or not self.can_fuse_iters():
            for _ in range(n_iters):
                self.train_one_iter()
            return
        is_goss = c.data_sample_strategy == "goss"
        goss_start = (int(1.0 / max(c.learning_rate, 1e-6))
                      if is_goss else None)
        # fixed scan length: every distinct length is a separate XLA
        # compile (trip count is static), so run whole chunks of D and
        # finish the remainder per-iter
        D = max(2, c.tpu_fuse_iters)
        done = 0
        while done < n_iters:
            it0 = self.iter_
            goss_now = is_goss and it0 >= goss_start
            avail = n_iters - done
            if is_goss and not goss_now:
                avail = min(avail, goss_start - it0)
            if avail < D:
                for _ in range(avail):
                    self.train_one_iter()
                done += avail
                continue
            n = D
            if goss_now not in self._chunk_cache:
                self._chunk_cache[goss_now] = self._make_chunk(goss_now)
            # identical keys to train_one_iter's PRNGKey(seed + iter):
            # pack the threefry hi/lo uint32 halves explicitly, matching
            # PRNGKey's truncation behavior (hi word only under x64)
            seeds64 = (np.arange(it0, it0 + n, dtype=np.int64)
                       + np.int64(c.objective_seed)).astype(np.uint64)
            hi = ((seeds64 >> np.uint64(32)).astype(np.uint32)
                  if jax.config.jax_enable_x64
                  else np.zeros(n, np.uint32))
            keys = jnp.asarray(np.stack(
                [hi, (seeds64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)],
                axis=1))
            with obs.span("train/fused_chunk", iterations=n,
                          start=it0):
                new_score, stacked = self._chunk_cache[goss_now](
                    self.score, keys)
                self.score = new_score
                with obs.span("train/fetch_trees"):
                    host = self._fetch_tree_arrays(stacked)
                for i in range(n):
                    self._append_host_trees(
                        {kk: v[i] for kk, v in host.items()})
            obs.inc("train.iterations", n)
            obs.heartbeat("train")
            self.iter_ += n
            done += n

    def _pad_custom(self, arr: np.ndarray) -> jnp.ndarray:
        arr = np.asarray(arr, dtype=np.float32)
        if self.num_class > 1:
            arr = arr.reshape(self.num_class, -1).T \
                if arr.ndim == 1 else arr
            out = np.zeros((self.data.n_pad, self.num_class), np.float32)
            out[:self.data.n] = arr
        else:
            out = np.zeros(self.data.n_pad, np.float32)
            out[:self.data.n] = arr.ravel()
        return jnp.asarray(out)

    # ------------------------------------------------------------------
    # fault-tolerant training state (recovery subsystem). The model
    # trees travel separately as model text; this is everything ELSE
    # that evolves across iterations and that init_model continuation
    # loses: host RNG streams, the exact score arrays, the current
    # bagging mask, CEGB acquisition state, position-bias state.
    def _rows_to_host(self, arr) -> Optional[np.ndarray]:
        """Host copy of a per-row device array: the process-LOCAL row
        chunk under a multi-process mesh (each process checkpoints its
        own shard), the full array otherwise."""
        if arr is None:
            return None
        if self.mesh is not None and jax.process_count() > 1:
            shards = {(s.index[0].start or 0): s
                      for s in arr.addressable_shards}
            return np.concatenate(
                [np.asarray(shards[k].data) for k in sorted(shards)],
                axis=0)
        return np.asarray(arr)

    def export_train_state(self) -> Dict[str, Any]:
        """Complete training state for a durable checkpoint (the model
        itself is serialized separately as model text)."""
        return {
            "engine": type(self).__name__,
            "iteration": int(self.iter_),
            # the engine's host trees travel as exact pickled copies
            # (model TEXT rounds internal_value/leaf_weight through
            # "{:g}", which would break bit-exact DART drop traversal)
            "models": list(self.models),
            "process_index": int(jax.process_index()),
            "process_count": int(jax.process_count()),
            "init_scores": self.init_scores.copy(),
            "rng_feature": self._rng_feature.get_state(),
            "rng_bagging": self._rng_bagging.get_state(),
            "bag_mask": self._rows_to_host(self._bag_mask),
            "score": self._rows_to_host(self.score),
            "valid_scores": [self._rows_to_host(s)
                             for s in self.valid_scores],
            "cegb_used": (None if self._cegb_used is None
                          else np.asarray(self._cegb_used).copy()),
            "cegb_U": (None if self._cegb_U is None
                       else np.asarray(self._cegb_U)),
            "pos_state": (None if self._pos_state is None
                          else jax.tree.map(np.asarray, self._pos_state)),
        }

    def import_train_state(self, state: Dict[str, Any]) -> bool:
        """Restore :meth:`export_train_state` output into a freshly
        constructed engine (no init_forest — the checkpoint's pickled
        trees are adopted directly). Returns True when the exact score
        arrays were restored (bit-exact resume); False when they were
        rebuilt from the restored forest (topology/shape mismatch —
        training stays correct but is no longer bit-exact vs an
        uninterrupted run)."""
        saved_engine = state.get("engine")
        if saved_engine is not None \
                and saved_engine != type(self).__name__:
            log.fatal(
                f"checkpoint was written by a {saved_engine} engine but "
                f"resume constructed {type(self).__name__} — the "
                f"boosting/tree_learner params must match the original "
                f"run")
        models = state.get("models")
        if models is None:
            log.fatal("checkpoint state holds no model trees — corrupt "
                      "or incompatible checkpoint")
        self.models = list(models)
        self._invalidate_forest_cache()
        self.iter_ = len(self.models) // self.num_class
        if int(state["iteration"]) != self.iter_:
            log.fatal(
                f"checkpoint state is for iteration "
                f"{state['iteration']} but holds "
                f"{self.iter_} iterations of trees — mismatched "
                f"checkpoint contents")
        self._rng_feature.set_state(state["rng_feature"])
        self._rng_bagging.set_state(state["rng_bagging"])
        if state.get("init_scores") is not None:
            # the checkpoint's model text is UNBIASED (no AddBias fold);
            # the bias lives here and is re-folded at the next save
            self.init_scores = np.asarray(state["init_scores"],
                                          dtype=np.float64)
        same_topo = (
            int(state.get("process_count", 1)) == jax.process_count()
            and int(state.get("process_index", 0)) == jax.process_index())
        cur = self._rows_to_host(self.score)
        sc = state.get("score")
        saved_valid = state.get("valid_scores") or []
        # valid sets are guarded like the train score: a changed valid
        # set (count or padded shape) must not silently adopt the old
        # set's accumulated predictions into this run's eval state
        valid_ok = (len(saved_valid) == len(self.valid_scores)
                    and all(v is not None and v.shape
                            == self._rows_to_host(
                                self.valid_scores[i]).shape
                            for i, v in enumerate(saved_valid)))
        restored = bool(same_topo and sc is not None
                        and sc.shape == cur.shape and valid_ok)
        if restored:
            self.score = self.data._place(sc, extra_dims=2)
            bm = state.get("bag_mask")
            self._bag_mask = (None if bm is None
                              else self.data._place(bm))
            for i, vs in enumerate(saved_valid):
                self.valid_scores[i] = self.valid_data[i]._place(
                    vs, extra_dims=2)
        else:
            log.warning(
                "checkpoint scores were saved under a different process "
                "topology, data shape, or valid-set layout; rebuilding "
                "scores from the restored model (training continues "
                "correctly but is not bit-exact vs an uninterrupted "
                "run)")
            # rebuild with the RESTORED init_scores (the checkpoint's
            # model text carries no bias of its own)
            self._recompute_scores()
        if state.get("cegb_used") is not None \
                and self._cegb_used is not None:
            self._cegb_used[:] = state["cegb_used"]
            self._cegb_pen_cache = None
        if state.get("cegb_U") is not None and self._cegb_lazy is not None:
            self._cegb_U = jnp.asarray(state["cegb_U"])
        if state.get("pos_state") is not None \
                and self._pos_state is not None:
            self._pos_state = jax.tree.map(jnp.asarray,
                                           state["pos_state"])
        return restored

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter — drop the last iteration's trees."""
        if self.iter_ == 0:
            return
        self.models = self.models[:-self.num_class]
        self._invalidate_forest_cache()
        self.iter_ -= 1
        self._recompute_scores()

    def _recompute_scores(self) -> None:
        score = self._init_score_tile(self.data)
        if self.models:
            stacked, class_idx = self._stack_models(0, len(self.models))
            raw, _ = forest_predict_binned(
                stacked, self._logical_bins(), self.feat_num_bin,
                self.feat_has_nan, class_idx, self.num_class)
            score = score + raw
        self.score = score
        for vi, dd in enumerate(self.valid_data):
            v = self._init_score_tile(dd)
            if self.models:
                raw, _ = forest_predict_binned(
                    stacked, dd.bins, self.feat_num_bin, self.feat_has_nan,
                    class_idx, self.num_class)
                v = v + raw
            self.valid_scores[vi] = v

    # ------------------------------------------------------------------
    def _stack_models(self, start: int, num: int):
        """Stack host trees [start, start+num) into device arrays."""
        return self._stack_model_list(list(range(start, start + num)))

    def _stack_model_list(self, indices: List[int], pad_count: int = 0,
                          pad_leaves: int = 0, use_cache=None):
        """Stack an arbitrary subset of host trees into device arrays
        (DART needs non-contiguous dropped-tree subsets).

        ``pad_count``/``pad_leaves`` stabilize the stacked SHAPES so the
        consumer jit does not recompile per distinct subset: the stack is
        padded to ``pad_count`` single-leaf zero-value trees (inert under
        traversal) and every per-tree array to ``pad_leaves`` slots.

        Contiguous index ranges are memoized on the engine (the
        stacked-forest device cache, keyed by (model count+version,
        start, num, pad shape)): repeat ``predict`` calls on an
        unchanged model reuse the device-resident stack — zero host
        re-stacking, zero HBM re-upload. ``_invalidate_forest_cache``
        drops it on any model mutation; DART's random drop subsets are
        non-contiguous and bypass it."""
        if use_cache is None:
            use_cache = bool(getattr(self.config, "tpu_predict_cache",
                                     True))
        key = None
        if (use_cache and indices
                and list(indices) == list(range(indices[0],
                                                indices[0] + len(indices)))):
            key = (indices[0], len(indices), int(pad_count),
                   int(pad_leaves))
            ver = (len(self.models), self._models_version)
            cache = self._stack_cache
            if cache is not None and cache[0] == ver:
                hit = cache[1].get(key)
                if hit is not None:
                    # LRU refresh: re-insert so slice-shape churn can
                    # never evict the hot full-model entry (tolerate a
                    # concurrent pop — threaded serving must not crash)
                    try:
                        cache[1][key] = cache[1].pop(key)
                    except KeyError:
                        pass
                    obs.inc("predict.stack_cache_hits")
                    return hit
        # observable for the zero-restack serving guarantee (tests pin
        # that warm predicts never reach this point)
        self._stack_builds = getattr(self, "_stack_builds", 0) + 1
        obs.inc("predict.stack_cache_misses")
        trees = [self.models[i] for i in indices]
        n_real = len(trees)
        n_pad = max(pad_count, n_real)
        L = max(max((t.num_leaves for t in trees), default=1), pad_leaves)
        Ln = max(L - 1, 1)

        def padded(getter, size, dtype, fill=0):
            out = np.full((n_pad, size), fill, dtype=dtype)
            for i, t in enumerate(trees):
                a = getter(t)
                out[i, :len(a)] = a
            return jnp.asarray(out)

        stacked = {
            "num_leaves": jnp.asarray(np.array(
                [t.num_leaves for t in trees] + [1] * (n_pad - n_real),
                np.int32)),
            "split_feature": padded(lambda t: t.split_feature, Ln, np.int32),
            "threshold_bin": padded(lambda t: t.threshold_bin, Ln, np.int32),
            "default_left": padded(lambda t: t.default_left, Ln, bool),
            "left_child": padded(lambda t: t.left_child, Ln, np.int32),
            "right_child": padded(lambda t: t.right_child, Ln, np.int32),
            "leaf_value": padded(
                lambda t: t.leaf_value.astype(np.float32), L, np.float32),
        }
        force_cat = pad_count > 0 and self.has_categorical
        if force_cat or any(t.cat_bitset_bins is not None for t in trees):
            # under shape-stabilizing padding, the bitset width and the
            # presence of the cat keys must not depend on WHICH trees
            # were drawn, or the consumer jit recompiles per drop set
            W = ((self.B + 31) // 32 if force_cat else
                 max(t.cat_bitset_bins.shape[1] for t in trees
                     if t.cat_bitset_bins is not None))
            bs = np.zeros((n_pad, Ln, W), dtype=np.uint32)
            for i, t in enumerate(trees):
                if t.cat_bitset_bins is not None:
                    a = t.cat_bitset_bins
                    bs[i, :a.shape[0], :a.shape[1]] = a
            stacked["is_cat"] = padded(
                lambda t: (t.is_categorical if t.is_categorical is not None
                           else np.zeros(t.num_nodes, bool)), Ln, bool)
            stacked["cat_bitset"] = jnp.asarray(bs)
        class_idx = jnp.asarray(np.asarray(
            list(indices) + [0] * (n_pad - n_real),
            dtype=np.int32) % self.num_class)
        if getattr(self, "_predict_mesh", None) is not None:
            # tree-sharded serving: commit the stack with its [T] axis
            # split over the mesh BEFORE caching, so every warm predict
            # reuses the sharded placement (re-placing per call would
            # re-upload the forest per request)
            from ..serve.shard import place_tree_sharded
            stacked, class_idx = place_tree_sharded(
                stacked, class_idx, self._predict_mesh)
        if key is not None:
            cache = self._stack_cache
            if cache is None or cache[0] != ver:
                cache = (ver, {})
                self._stack_cache = cache
            if len(cache[1]) >= _STACK_CACHE_ENTRIES:
                cache[1].pop(next(iter(cache[1])))
            cache[1][key] = (stacked, class_idx)
        return stacked, class_idx

    # ------------------------------------------------------------------
    def eval_set(self, which: int) -> List[Tuple[str, str, float, bool]]:
        """Evaluate metrics: which=-1 train, else valid index.

        Returns list of (data_name, metric_name, value, higher_better).
        """
        from ..metric import eval_metric_rows
        if which < 0:
            dd, name = self.data, "training"
            raw = np.asarray(self.score)[:dd.n]
        else:
            dd = self.valid_data[which]
            name = self.valid_names[which]
            raw = np.asarray(self.valid_scores[which])[:dd.n]
        label = np.asarray(dd.label)[:dd.n] if dd.label is not None else None
        weight = (np.asarray(dd.weight)[:dd.n]
                  if dd.weight is not None else None)
        return eval_metric_rows(self.objective, self.metrics, name,
                                raw, label, weight,
                                dd.query_boundaries, self.num_class)

    def _convert_output_np(self, raw: np.ndarray) -> np.ndarray:
        if self.num_class == 1:
            raw = raw[:, 0]
        return np.asarray(self.objective.convert_output(jnp.asarray(raw)))

    # ------------------------------------------------------------------
    def predict(self, X: np.ndarray, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False, **overrides) -> np.ndarray:
        """Predict on raw features (binned through the train mappers).

        ``overrides``: per-call serving-knob overrides (upstream's
        predict-kwargs-as-params convention) — ``tpu_predict_
        parallel_trees`` / ``tpu_predict_buckets`` /
        ``tpu_predict_chunk_rows`` tune one call without mutating the
        engine config."""
        if not obs.any_enabled():
            return self._predict_impl(X, raw_score, start_iteration,
                                      num_iteration, pred_leaf,
                                      **overrides)
        return obs.predict_instrumented(
            lambda: self._predict_impl(X, raw_score, start_iteration,
                                       num_iteration, pred_leaf,
                                       **overrides), X)

    def _predict_impl(self, X: np.ndarray, raw_score: bool = False,
                      start_iteration: int = 0, num_iteration: int = -1,
                      pred_leaf: bool = False,
                      **overrides) -> np.ndarray:
        if self.linear_tree:
            # linear leaves need raw feature values — host-model path
            # (cached; the model list only grows)
            from ..io.model_text import HostModel
            hm_key = (len(self.models), self._models_version)
            cache = getattr(self, "_hm_cache", (None, None))
            if cache[0] != hm_key:
                cache = (hm_key,
                         HostModel.from_engine(self, self.config))
                self._hm_cache = cache
            return cache[1].predict(X, raw_score=raw_score,
                                    start_iteration=start_iteration,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf)
        ds = self.train_set
        sparse_in = hasattr(X, "tocsc") and not isinstance(X, np.ndarray)
        if sparse_in:
            # scipy sparse: bin column-at-a-time without densifying the
            # full matrix (same path training binning uses — Criteo-
            # scale sparse predict must not materialize n x F floats)
            Xc = X.tocsc()
            n_rows = Xc.shape[0]
            if Xc.shape[1] != ds.num_total_features:
                log.fatal(
                    f"The number of features in data ({Xc.shape[1]}) is "
                    f"not the same as it was in training data "
                    f"({ds.num_total_features})")
        else:
            from ..io.dataset import apply_pandas_categorical
            X = apply_pandas_categorical(
                X, getattr(ds, "pandas_categorical", None))
            X = Dataset._to_matrix(X)
            n_rows = X.shape[0]
            if X.shape[1] != ds.num_total_features:
                log.fatal(
                    f"The number of features in data ({X.shape[1]}) is "
                    f"not the same as it was in training data "
                    f"({ds.num_total_features})")
        # one native row-major pass over all columns where possible
        # (Dataset._bin_all_columns; the strided per-column fallback
        # otherwise) — same binning the training construct used
        src = Xc if sparse_in else X
        bins = ds._bin_all_columns(src, sparse_in, ds.binned_dtype(),
                                   n_rows=n_rows)
        total_iters = len(self.models) // self.num_class
        if num_iteration <= 0:
            num_iteration = total_iters - start_iteration
        num_iteration = min(num_iteration, total_iters - start_iteration)
        n_trees = num_iteration * self.num_class
        start_tree = start_iteration * self.num_class
        n = n_rows
        if n_trees <= 0:
            if pred_leaf:
                return np.zeros((n, 0), dtype=np.int32)
            raw = np.tile(self.init_scores, (n, 1))
            if raw_score:
                return raw[:, 0] if self.num_class == 1 else raw
            return self._convert_output_np(raw)

        def post(raw_np: np.ndarray) -> np.ndarray:
            # per-chunk post-processing on the still-PADDED rows (all
            # steps are row-local, so padded rows never affect real
            # ones, and the convert step's jit sees only the bounded
            # bucket/chunk shapes — not one shape per request size)
            if self.average_output:
                # RF: trees carry the init-score bias; average them
                raw_np = raw_np / num_iteration
            elif start_iteration == 0:
                raw_np = raw_np + self.init_scores[None, :]
            if raw_score:
                return raw_np[:, 0] if self.num_class == 1 else raw_np
            return self._convert_output_np(raw_np)

        from ..config import coerce_bool
        use_cache = (coerce_bool(overrides["tpu_predict_cache"])
                     if "tpu_predict_cache" in overrides else None)
        stacked, class_idx = self._stack_for_predict(
            start_tree, n_trees, use_cache=use_cache)
        out, leaves = self._run_forest_chunks(
            stacked, class_idx, bins, n_trees, want_leaves=pred_leaf,
            # pred_leaf discards raw scores: skip their copy + convert
            postprocess=None if pred_leaf else post, overrides=overrides)
        if pred_leaf:
            return leaves.T.astype(np.int32)
        return out

    # ------------------------------------------------------------------
    def _stack_for_predict(self, start_tree: int, n_trees: int,
                           use_cache=None):
        """Stack the requested tree range with shape-stabilizing
        padding. The full forest stacks exactly (the serving steady
        state — one stacked shape per model size, and the same shape
        the score-rebuild/valid-eval paths already compiled). Partial
        ranges — ``num_iteration``/``start_iteration`` early-stop
        serving — pad the tree count to the next power of two and every
        tree to the config leaf cap, so each distinct slice length
        reuses a bucketed traversal compile instead of triggering a
        fresh one (the same ``pad_count``/``pad_leaves`` knobs DART's
        drop stacks use).

        ``_stable_predict_shapes`` (set by serving.ModelWatcher when
        this engine serves under a checkpoint watch) extends the
        bucketed padding to the FULL forest too: successive hot-swapped
        models whose actual max leaf counts differ would otherwise
        stack to different shapes and recompile the warm path on every
        swap — padded to (pow2 tree count, config num_leaves), every
        swap in the same bucket reuses the compiled programs
        (CompileWatch-pinned in tests/test_chaos.py)."""
        if (not getattr(self, "_stable_predict_shapes", False)
                and start_tree == 0 and n_trees == len(self.models)):
            return self._stack_model_list(list(range(n_trees)),
                                          use_cache=use_cache)
        pad_count = _next_pow2(n_trees)
        mesh = getattr(self, "_predict_mesh", None)
        if mesh is not None:
            # NamedSharding needs the tree axis divisible by the mesh:
            # pad further with inert single-leaf trees (a pow2 count
            # already divides pow2 meshes; this covers the rest)
            pad_count = _ceil_to(pad_count, int(mesh.devices.size))
        return self._stack_model_list(
            list(range(start_tree, start_tree + n_trees)),
            pad_count=pad_count,
            pad_leaves=self.config.num_leaves, use_cache=use_cache)

    def _run_forest_chunks(self, stacked, class_idx, bins, n_trees: int,
                           want_leaves: bool = False, postprocess=None,
                           overrides=None):
        """Traverse the stacked forest over host-binned rows with
        batch-shape bucketing and chunked double-buffered streaming.

        Small batches pad up to power-of-two row buckets (bounded
        compile cache under arbitrary request sizes); jobs larger than
        ``tpu_predict_chunk_rows`` stream in fixed-size chunks — every
        chunk the SAME shape — with ``copy_to_host_async`` issued
        before the next chunk's dispatch so device compute and the
        device->host copy overlap (the dispatch-latency lesson
        docs/perf.md records for training). ``postprocess`` (row-local:
        score averaging / init-score add / output convert) runs per
        chunk while rows are still padded, so its jit also sees only
        bucket shapes. Padded rows are sliced off before returning;
        real-row outputs are identical to one unpadded pass.

        Returns (per-row output ``[n, ...]`` f64,
                 leaf indices ``[n_trees, n]`` int32 or None).
        """
        from ..config import coerce_bool
        cfg = self.config

        def knob(name, cast):
            if overrides and name in overrides:
                return cast(overrides[name])
            return cast(getattr(cfg, name))

        n_rows = bins.shape[0]
        mode = (None if knob("tpu_predict_parallel_trees", coerce_bool)
                else "scan")
        mesh = getattr(self, "_predict_mesh", None)
        consts = getattr(self, "_shard_consts", None)
        feat_num_bin, feat_has_nan = (
            consts if (mesh is not None and consts is not None)
            else (self.feat_num_bin, self.feat_has_nan))
        chunk = max(knob("tpu_predict_chunk_rows", int), 1024)
        if n_rows <= chunk:
            pad_to = predict_pad_rows(
                n_rows, chunk, knob("tpu_predict_buckets", coerce_bool))
            plan = [(0, n_rows, pad_to)]
        else:
            plan = [(s, min(chunk, n_rows - s), chunk)
                    for s in range(0, n_rows, chunk)]

        raw_parts: List[np.ndarray] = []
        leaf_parts: List[np.ndarray] = []

        def drain(item):
            raw_dev, leaves_dev, rows = item
            if raw_dev is not None:
                raw_np = np.asarray(raw_dev, dtype=np.float64)
                if postprocess is not None:
                    raw_np = postprocess(raw_np)
                raw_parts.append(raw_np[:rows])
            if leaves_dev is not None:
                leaf_parts.append(np.asarray(leaves_dev)[:, :rows])

        if obs.enabled():
            # bucket/chunk accounting: padded rows quantify the cost of
            # the bounded-compile-cache guarantee, chunk count the
            # streaming fan-out
            obs.inc("predict.chunks", len(plan))
            obs.inc("predict.padded_rows",
                    sum(p - r for _s, r, p in plan))
        # depth=1 window == the double buffer this loop hand-rolled
        # before utils/prefetch.py existed: block on the oldest chunk's
        # async D2H copy only once a second chunk is dispatched.
        window = InflightWindow(1, drain)
        for start, rows, pad_to in plan:
            blk = bins[start:start + rows]
            if pad_to > rows:
                blk = np.concatenate(
                    [blk, np.zeros((pad_to - rows, blk.shape[1]),
                                   blk.dtype)])
            if mesh is not None:
                # replicate THIS request's rows across the mesh (the
                # H2D upload it would pay anyway, fanned out)
                from ..serve.shard import replicate_on
                blk_dev = replicate_on(mesh, blk)
            else:
                blk_dev = jnp.asarray(blk)
            raw_dev, leaves_dev = forest_predict_binned(
                stacked, blk_dev, feat_num_bin, feat_has_nan,
                class_idx, self.num_class, mode=mode, mesh=mesh)
            if want_leaves:
                # leaf-only request: the raw scores are never read back
                leaves_dev.copy_to_host_async()
                window.push((None, leaves_dev, rows))
            else:
                raw_dev.copy_to_host_async()
                window.push((raw_dev, None, rows))
        window.drain()
        if want_leaves:
            leaves = (leaf_parts[0] if len(leaf_parts) == 1
                      else np.concatenate(leaf_parts, axis=1))[:n_trees]
            return None, leaves
        raw = (raw_parts[0] if len(raw_parts) == 1
               else np.concatenate(raw_parts, axis=0))
        return raw, None

    # ------------------------------------------------------------------
    def predict_contrib(self, X, start_iteration: int = 0,
                        num_iteration: int = -1, host_model=None,
                        force_f64=None, **overrides) -> np.ndarray:
        """Device-native TreeSHAP (``pred_contrib``) through the same
        serving machinery as :meth:`predict`: memoized device-resident
        path tables (``_shap_cache``), pow2 row buckets + fixed-size
        chunking + the InflightWindow double buffer, and the
        tree-sharded scan when a ``_predict_mesh`` is enabled.

        Output is host-format: ``[n, n_feat + 1]`` for one class, else
        ``[n, K * (n_feat + 1)]`` — identical to
        ``HostModel.predict(pred_contrib=True)`` (f64-exact on CPU
        backends; documented ~3e-5 f32 tolerance on TPU)."""
        if not obs.any_enabled():
            return self._predict_contrib_impl(
                X, start_iteration, num_iteration, host_model,
                force_f64, **overrides)
        return obs.predict_instrumented(
            lambda: self._predict_contrib_impl(
                X, start_iteration, num_iteration, host_model,
                force_f64, **overrides), X)

    def _predict_contrib_impl(self, X, start_iteration: int,
                              num_iteration: int, host_model,
                              force_f64, **overrides) -> np.ndarray:
        from ..ops import shap as shap_ops
        if host_model is None:
            # SHAP walks host trees (original-feature split ids, folded
            # init-score bias) — same cached conversion predict's
            # linear-tree path uses
            from ..io.model_text import HostModel
            hm_key = (len(self.models), self._models_version)
            cache = getattr(self, "_hm_cache", (None, None))
            if cache[0] != hm_key:
                cache = (hm_key,
                         HostModel.from_engine(self, self.config))
                self._hm_cache = cache
            host_model = cache[1]
        ds = self.train_set
        sparse_in = hasattr(X, "tocsr") and not isinstance(X, np.ndarray)
        if sparse_in:
            X = X.tocsr()
            n_rows = X.shape[0]
            n_cols = X.shape[1]
        else:
            from ..io.dataset import apply_pandas_categorical
            X = apply_pandas_categorical(
                X, getattr(ds, "pandas_categorical", None))
            X = np.ascontiguousarray(
                np.asarray(Dataset._to_matrix(X), np.float64))
            n_rows, n_cols = X.shape
        if n_cols != ds.num_total_features:
            log.fatal(
                f"The number of features in data ({n_cols}) is "
                f"not the same as it was in training data "
                f"({ds.num_total_features})")
        n_feat = ds.num_total_features
        K = max(self.num_class, 1)
        total_iters = len(self.models) // K
        if num_iteration <= 0:
            num_iteration = total_iters - start_iteration
        num_iteration = min(num_iteration, total_iters - start_iteration)
        n_trees = num_iteration * K
        start_tree = start_iteration * K
        if n_trees <= 0:
            out = np.zeros((n_rows, K, n_feat + 1), np.float64)
        else:
            trees = host_model.trees[start_tree:start_tree + n_trees]
            if all(t.num_leaves <= 1 for t in trees):
                out = shap_ops.stump_only_contrib(trees, n_rows,
                                                  n_feat, K)
            else:
                with obs.span("predict/contrib", rows=n_rows,
                              trees=n_trees):
                    out = self._run_shap_chunks(
                        trees, X, sparse_in, n_rows, n_feat, K,
                        start_tree, n_trees, force_f64, overrides)
            if self.average_output:
                out = out / max(n_trees // K, 1)
        return out[:, 0, :] if K == 1 else out.reshape(
            n_rows, K * (n_feat + 1))

    def _shap_tables_for(self, trees, start_tree: int, n_trees: int,
                         n_feat: int, K: int, dtype_name: str, mesh):
        """Device-resident stacked path tables for a tree slice,
        memoized next to ``_stack_model_list``'s forest cache: keyed on
        ``(len(models), _models_version)`` so hot-swaps re-cost, LRU
        over ``(start_tree, n_trees, dtype)`` slices, shape-stabilized
        (config leaf cap + pow2 depth/slot/tree-count buckets) exactly
        like ``_stack_for_predict`` so warm SHAP re-derives nothing and
        recompiles nothing within a bucket."""
        from ..ops import shap as shap_ops
        ver = (len(self.models), self._models_version)
        key = (start_tree, n_trees, dtype_name)
        cache = self._shap_cache
        if cache is not None and cache[0] == ver and key in cache[1]:
            entry = cache[1].pop(key)
            cache[1][key] = entry          # LRU refresh
            if obs.enabled():
                obs.inc("predict.contrib_cache_hits")
            return entry
        if obs.enabled():
            obs.inc("predict.contrib_cache_misses")
        (L_a, D_a, U_a, NN_a), paths = shap_ops.shap_path_dims(trees)
        partial = not (start_tree == 0 and n_trees == len(self.models))
        if getattr(self, "_stable_predict_shapes", False) or partial:
            # bucketed caps: leaf/node dims pinned to the config cap,
            # depth/slot dims to pow2 buckets — successive hot-swapped
            # models (or early-stop slices) in the same buckets reuse
            # the compiled scan
            L = max(L_a, int(self.config.num_leaves))
            NN = max(NN_a, L - 1)
            D = _next_pow2(max(D_a, 1))
            U = _next_pow2(max(U_a, 1))
            T_pad = _next_pow2(n_trees)
        else:
            L, D, U, NN = L_a, D_a, U_a, NN_a
            T_pad = n_trees
        if mesh is not None:
            T_pad = _ceil_to(T_pad, int(mesh.devices.size))
        stacked_np, dims = shap_ops.build_shap_tables(
            trees, n_feat, K, dims=(L, D, U, NN),
            pad_trees=T_pad - n_trees, paths=paths)
        if mesh is not None:
            from ..serve.shard import place_shap_sharded
            dev = place_shap_sharded(stacked_np, mesh)
        else:
            dev = {k: jnp.asarray(v) for k, v in stacked_np.items()}
        entry = (dev, dims, T_pad)
        if cache is None or cache[0] != ver:
            cache = (ver, {})
            self._shap_cache = cache
        cache[1][key] = entry
        while len(cache[1]) > _STACK_CACHE_ENTRIES:
            cache[1].pop(next(iter(cache[1])))
        return entry

    def _run_shap_chunks(self, trees, X, sparse_in: bool, n_rows: int,
                         n_feat: int, K: int, start_tree: int,
                         n_trees: int, force_f64, overrides):
        """Run the SHAP scan over ``X`` with the SAME batch-shape
        bucketing, fixed-size chunking, and double-buffered D2H
        streaming as ``_run_forest_chunks`` — the per-chunk host work
        is only the routing-bit pass (vectorized numpy), the tables
        come from the device cache. Returns ``[n, K, n_feat+1]`` f64."""
        import contextlib
        from ..config import coerce_bool
        from ..ops import shap as shap_ops
        from ..ops.predict import onehot_bounded_rows
        cfg = self.config

        def knob(name, cast):
            if overrides and name in overrides:
                return cast(overrides[name])
            return cast(getattr(cfg, name))

        if force_f64 is None:
            force_f64 = jax.default_backend() == "cpu"
        mesh = getattr(self, "_predict_mesh", None)
        if force_f64 and jax.default_backend() != "cpu":
            # exact-f64 escape hatch runs on the host CPU device —
            # never through an accelerator mesh
            mesh = None
        dtype_name = "float64" if force_f64 else "float32"
        ctx = contextlib.ExitStack()
        if force_f64:
            x64_ctx = getattr(jax, "enable_x64", None)
            if x64_ctx is None:
                from jax.experimental import enable_x64 as x64_ctx
            ctx.enter_context(x64_ctx())
            if jax.default_backend() != "cpu":
                ctx.enter_context(
                    jax.default_device(jax.devices("cpu")[0]))
        out = np.zeros((n_rows, K, n_feat + 1), np.float64)
        with ctx:
            dev, (L, D, U, NN), T_pad = self._shap_tables_for(
                trees, start_tree, n_trees, n_feat, K, dtype_name,
                mesh)
            chunk = max(knob("tpu_predict_chunk_rows", int), 1024)
            # bound the scan's widest [rows, L*max(D, U+2)] operand the
            # same way the level traversal bounds its one-hots
            chunk = min(chunk, onehot_bounded_rows(L * max(D, U + 2)))
            if n_rows <= chunk:
                pad_to = predict_pad_rows(
                    n_rows, chunk,
                    knob("tpu_predict_buckets", coerce_bool))
                plan = [(0, n_rows, pad_to)]
            else:
                plan = [(s, min(chunk, n_rows - s), chunk)
                        for s in range(0, n_rows, chunk)]
            if obs.enabled():
                obs.inc("predict.chunks", len(plan))
                obs.inc("predict.padded_rows",
                        sum(p - r for _s, r, p in plan))
            use_sharded = (mesh is not None
                           and int(mesh.devices.size) > 1
                           and T_pad % int(mesh.devices.size) == 0)
            run = (shap_ops.sharded_scan_kernel(
                       mesh, D, U, NN, n_feat, K, dtype_name)
                   if use_sharded else
                   shap_ops._scan_kernel(D, U, NN, n_feat, K,
                                         dtype_name))

            def drain(item):
                phi_dev, lo, rows = item
                out[lo:lo + rows] = np.asarray(phi_dev,
                                               np.float64)[:rows]

            window = InflightWindow(1, drain)
            for start, rows, pad_to in plan:
                if sparse_in:
                    blk = np.asarray(
                        X[start:start + rows].toarray(), np.float64)
                else:
                    blk = X[start:start + rows]
                if pad_to > rows:
                    blk = np.concatenate(
                        [blk, np.zeros((pad_to - rows, blk.shape[1]),
                                       np.float64)])
                # host routing-bit pass: once per (rows-bucket, model
                # version) chunk, not per call — tables are cached
                conds = np.stack(
                    [shap_ops._host_cond_bits(t, blk, NN)
                     for t in trees])
                if T_pad > len(trees):
                    conds = np.concatenate(
                        [conds,
                         np.zeros((T_pad - len(trees),)
                                  + conds.shape[1:], np.uint8)])
                batch = dict(dev)
                if use_sharded:
                    from ..serve.shard import place_tree_axis
                    batch["cond"] = place_tree_axis(mesh, conds)
                else:
                    batch["cond"] = jnp.asarray(conds)
                phi_dev = run(batch)
                phi_dev.copy_to_host_async()
                window.push((phi_dev, start, rows))
            window.drain()
        return out

    @property
    def current_iteration(self) -> int:
        return self.iter_

    def num_trees(self) -> int:
        return len(self.models)

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Current observability snapshot (docs/observability.md):
        process-wide metrics registry contents with the device/compile
        gauges refreshed. Enable collection with ``tpu_metrics=true``
        (off by default, so an un-enabled engine returns an empty or
        partial snapshot)."""
        return obs.snapshot()
