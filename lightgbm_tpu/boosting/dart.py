"""DART boosting: Dropouts meet Multiple Additive Regression Trees.

Reference semantics: ``DART`` (src/boosting/dart.hpp, UNVERIFIED — empty
mount, see SURVEY.md banner). Per iteration:

1. select a random subset of existing iterations to *drop* (skipped
   entirely with probability ``skip_drop``; per-iteration drop probability
   ``drop_rate``, weighted by current tree weight unless ``uniform_drop``;
   capped at ``max_drop``),
2. compute gradients on the ensemble score *minus* the dropped trees'
   contributions and train the new tree there,
3. renormalize so the expected ensemble output is unchanged: the new tree
   gets weight ``lr/(k+1)`` and each dropped tree is rescaled by
   ``k/(k+1)`` (with ``xgboost_dart_mode``: ``lr/(k+lr)`` and
   ``k/(k+lr)``, XGBoost's normalize_type=tree).

TPU-first: the dropped-tree contributions are one stacked
``forest_predict_binned`` on the device-resident binned matrix — no
per-tree host loop — and the re-normalization is two fused elementwise
score updates. The heavy per-iteration work (gradients + tree growth)
reuses the jitted GBDT step unchanged.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..ops.predict import forest_predict_binned
from .gbdt import GBDT


class DART(GBDT):
    """DART engine (reference: src/boosting/dart.hpp DART : public GBDT)."""

    # no carry donation (tpu_donate): train_one_iter holds
    # score_pre/valid_pre across the boosting step and blends the new
    # tree's contribution against them AFTER dispatch — donating would
    # delete exactly those buffers (docs/perf.md "Iteration floor")
    _donate_carries = False

    def __init__(self, config, train_set, fobj=None, mesh=None,
                 init_forest=None):
        super().__init__(config, train_set, fobj=fobj, mesh=mesh,
                         init_forest=init_forest)
        self._rng_drop = np.random.RandomState(config.drop_seed)
        self._iter_weights: List[float] = []   # current weight per iteration
        self._sum_weight = 0.0
        if self.iter_:
            # continuation: the loaded trees' DART weights are unknown;
            # seed each at lr (only affects non-uniform drop probabilities)
            lr = float(config.learning_rate)
            self._iter_weights = [lr] * self.iter_
            self._sum_weight = lr * self.iter_

    def can_fuse_iters(self) -> bool:
        # drop selection / renormalization is host-orchestrated per iter
        return False

    # ------------------------------------------------------------------
    def export_train_state(self):
        st = super().export_train_state()
        st["dart"] = {
            "rng_drop": self._rng_drop.get_state(),
            "iter_weights": [float(w) for w in self._iter_weights],
            "sum_weight": float(self._sum_weight),
        }
        return st

    def import_train_state(self, state) -> bool:
        restored = super().import_train_state(state)
        d = state.get("dart")
        if d is not None:
            # replaces __init__'s lossy lr-per-iteration seeding with
            # the exact per-iteration weights the run had accumulated
            self._rng_drop.set_state(d["rng_drop"])
            self._iter_weights = [float(w) for w in d["iter_weights"]]
            self._sum_weight = float(d["sum_weight"])
        return restored

    # ------------------------------------------------------------------
    def _select_drop(self) -> np.ndarray:
        """DART::DroppingTrees — iteration indices to drop this round."""
        c = self.config
        n_iter = len(self._iter_weights)
        if n_iter == 0 or self._rng_drop.rand() < c.skip_drop:
            return np.array([], dtype=np.int64)
        if c.uniform_drop:
            p = np.full(n_iter, c.drop_rate)
        else:
            # weight-proportional drop, normalized so the mean probability
            # is drop_rate (heavier trees are dropped more often)
            w = np.asarray(self._iter_weights, dtype=np.float64)
            mean_w = self._sum_weight / n_iter
            p = c.drop_rate * w / max(mean_w, 1e-32)
        drop = np.flatnonzero(self._rng_drop.rand(n_iter) < p)
        if c.max_drop > 0 and len(drop) > c.max_drop:
            drop = np.sort(self._rng_drop.choice(
                drop, size=c.max_drop, replace=False))
        return drop

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> None:
        K = self.num_class
        lr = float(self.config.learning_rate)
        drop_iters = self._select_drop()
        k = len(drop_iters)

        drop_contrib = None
        drop_contrib_valid = []
        if k:
            model_idx = [int(i) * K + c
                         for i in drop_iters for c in range(K)]
            # pad to a power-of-two tree count and the static leaf width
            # so forest_predict_binned compiles once per bucket, not once
            # per distinct drop set
            pad_count = 1 << (len(model_idx) - 1).bit_length()
            stacked, class_idx = self._stack_model_list(
                model_idx, pad_count=pad_count,
                pad_leaves=self.config.num_leaves)
            # LOGICAL bins: under EFB the resident train matrix is the
            # bundled physical layout, but tree thresholds are logical
            drop_contrib, _ = forest_predict_binned(
                stacked, self._logical_bins(), self.feat_num_bin,
                self.feat_has_nan, class_idx, K)
            self.score = self.score - drop_contrib
            for vi, dd in enumerate(self.valid_data):
                vc, _ = forest_predict_binned(
                    stacked, dd.bins, self.feat_num_bin,
                    self.feat_has_nan, class_idx, K)
                drop_contrib_valid.append(vc)
                self.valid_scores[vi] = self.valid_scores[vi] - vc

        score_pre = self.score
        valid_pre = list(self.valid_scores)
        super().train_one_iter(grad, hess)

        if k == 0:
            self._iter_weights.append(lr)
            self._sum_weight += lr
            return

        if self.config.xgboost_dart_mode:
            # XGBoost normalize_type=tree: new weight lr/(k+lr)
            new_mult = 1.0 / (k + lr)       # vs the lr already applied
            old_mult = k / (k + lr)
        else:
            new_mult = 1.0 / (k + 1.0)
            old_mult = k / (k + 1.0)

        # score = score_pre + new_mult * (new tree's lr-scaled output)
        #                   + old_mult * (dropped trees' old contribution)
        self.score = (score_pre + (self.score - score_pre) * new_mult
                      + drop_contrib * old_mult)
        for vi in range(len(self.valid_scores)):
            self.valid_scores[vi] = (
                valid_pre[vi]
                + (self.valid_scores[vi] - valid_pre[vi]) * new_mult
                + drop_contrib_valid[vi] * old_mult)

        # host-side tree bookkeeping mirrors the score math
        for t in self.models[-K:]:
            t.shrink(new_mult)
        for i in drop_iters:
            for c in range(K):
                self.models[int(i) * K + c].shrink(old_mult)
            self._iter_weights[int(i)] *= old_mult
        self._iter_weights.append(lr * new_mult)
        self._sum_weight = float(np.sum(self._iter_weights))
        # the rescales mutated stored trees in place: cached device
        # stacks (and cached host models) must not serve the old leaves
        self._invalidate_forest_cache()

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        if self.iter_ and self._iter_weights:
            # NOTE: the dropped-tree rescales of the rolled-back iteration
            # are kept (the reference rolls back only the new trees too)
            self._sum_weight -= self._iter_weights.pop()
        super().rollback_one_iter()
