"""Booster: the user-facing training/prediction handle.

Reference: python-package/lightgbm/basic.py (UNVERIFIED — empty mount, see
SURVEY.md banner). There, ``Booster`` is a ctypes proxy over the C API's
LGBM_Booster* handles; here it wraps the in-process GBDT engine directly —
the TPU framework is Python-hosted, so the ABI seam the reference needs
(C API, SURVEY.md §1 L7) collapses into this class while keeping the same
method surface.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .boosting import GBDT, create_boosting
from .config import Config
from .io.dataset import Dataset
from .utils import log
from .utils.log import LightGBMError

__all__ = ["Booster", "Dataset", "LightGBMError"]


class Booster:
    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 init_forest=None):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._engine: Optional[GBDT] = None
        self._from_model = None
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance")
            self.config = Config(self.params)
            train_set.params.setdefault("max_bin", self.config.max_bin)
            for key in ("min_data_in_bin", "bin_construct_sample_cnt",
                        "use_missing", "zero_as_missing",
                        "data_random_seed", "linear_tree",
                        # device-ingest knobs ride along so train-param
                        # settings govern the construct that this
                        # Booster triggers (ops/ingest.py) — including
                        # the gates _want_transposed_ingest /
                        # _want_device_ingest read (pallas, precision,
                        # streaming), else construct emits device
                        # arrays the engine will never adopt
                        "tpu_ingest_device", "tpu_ingest_chunk_rows",
                        "tpu_ingest_threads", "tpu_use_pallas",
                        "tpu_double_precision_hist", "tpu_streaming",
                        "tree_learner", "tpu_compile_cache_dir"):
                train_set.params.setdefault(key, getattr(self.config, key))
            self._engine = create_boosting(self.config, train_set,
                                           init_forest=init_forest)
            self.train_set = train_set
        elif model_file is not None or model_str is not None:
            from .io.model_text import load_model_string
            if model_file is not None:
                with open(model_file) as f:
                    model_str = f.read()
            self._from_model = load_model_string(model_str)
            self.config = Config(self.params)
        else:
            raise TypeError("At least one of train_set, model_file or "
                            "model_str should be provided")
        # serve-side hot-swap (serving.py): tpu_model_watch names a
        # checkpoint dir this Booster polls at predict time, atomically
        # swapping freshly published models in
        self._model_watch = None
        watch = str(getattr(self.config, "tpu_model_watch", "")
                    or "").strip()
        if watch:
            self.watch_checkpoints(
                watch, interval=float(getattr(
                    self.config, "tpu_model_watch_interval", 2.0)))

    def watch_checkpoints(self, directory: str,
                          interval: float = 2.0) -> "Booster":
        """Hot-swap serving: poll ``directory`` (a recovery-subsystem
        checkpoint dir) every ``interval`` seconds at predict time and
        atomically adopt the newest valid checkpoint's model — zero
        dropped requests, zero warm-path recompiles for same-bucket
        models, graceful degradation on corrupt publishes
        (docs/robustness.md "Hot-swap serving"). The param form is
        ``tpu_model_watch`` / ``tpu_model_watch_interval``."""
        from .serving import ModelWatcher
        self._model_watch = ModelWatcher(directory, interval=interval)
        if self._engine is not None:
            # pin the engine to bucketed predict shapes up front so the
            # warm-up predict compiles the SAME programs every later
            # swap reuses (not an unpadded one-off)
            self._engine._stable_predict_shapes = True
        return self

    # ------------------------------------------------------------------
    @property
    def engine(self) -> GBDT:
        if self._engine is None:
            raise LightGBMError("Booster has no training engine "
                                "(loaded from model file)")
        return self._engine

    def metrics(self) -> Dict[str, Any]:
        """Current observability snapshot (docs/observability.md):
        counters / gauges / histograms from the process-wide registry,
        with the device/compile gauges refreshed. Collection is off by
        default — enable with ``tpu_metrics=true`` (or
        ``lightgbm_tpu.obs.enable()``), else the snapshot is empty or
        partial."""
        from . import obs
        if self._engine is not None and hasattr(self._engine,
                                                "metrics_snapshot"):
            return self._engine.metrics_snapshot()
        # no engine (model-file booster) or an engine without the API
        # (StreamingGBDT): the registry is process-wide anyway
        return obs.snapshot()

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        self.engine.add_valid(data, name)
        if not hasattr(self, "_valid_sets"):
            self._valid_sets = []
        self._valid_sets.append(data)
        return self

    def update(self, train_set: Optional[Dataset] = None,
               fobj: Optional[Callable] = None) -> bool:
        """Run one boosting iteration; returns True if stopped early."""
        if train_set is not None and train_set is not self.train_set:
            raise LightGBMError("Replacing train_set mid-training is not "
                                "supported")
        if fobj is not None:
            preds = self._inner_raw_predict()
            grad, hess = fobj(preds, self.train_set)
            self.engine.train_one_iter(np.asarray(grad), np.asarray(hess))
        else:
            self.engine.train_one_iter()
        return False

    def _inner_raw_predict(self) -> np.ndarray:
        eng = self.engine
        raw = np.asarray(eng.score)[:eng.data.n]
        if eng.num_class == 1:
            return raw[:, 0].astype(np.float64)
        return raw.astype(np.float64).reshape(-1, order="F")

    def rollback_one_iter(self) -> "Booster":
        self.engine.rollback_one_iter()
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self.config.update(params)
        # rebuild jitted step so learning-rate etc. take effect
        self.engine.config = self.config
        self.engine._build_step()
        # a cached host model may bake the old params (e.g. sigmoid):
        # invalidate the booster-level cache AND the engine-level one
        # the linear-tree predict path keeps
        self._params_version = getattr(self, "_params_version", 0) + 1
        if hasattr(self.engine, "_invalidate_forest_cache"):
            self.engine._invalidate_forest_cache()
        return self

    # ------------------------------------------------------------------
    def eval_train(self, feval=None) -> List:
        return self._eval(-1, feval)

    def eval_valid(self, feval=None) -> List:
        out = []
        for i in range(len(self.engine.valid_data)):
            out.extend(self._eval(i, feval))
        return out

    def eval(self, data: Dataset, name: str, feval=None) -> List:
        for i, n in enumerate(self.engine.valid_names):
            if n == name:
                return self._eval(i, feval)
        self.add_valid(data, name)
        return self._eval(len(self.engine.valid_names) - 1, feval)

    def _eval(self, which: int, feval=None) -> List:
        results = self.engine.eval_set(which)
        if feval is not None:
            eng = self.engine
            if which < 0:
                ds, raw = self.train_set, np.asarray(
                    eng.score)[:eng.data.n]
                name = "training"
            else:
                dd = eng.valid_data[which]
                raw = np.asarray(eng.valid_scores[which])[:dd.n]
                name = eng.valid_names[which]
                ds = getattr(self, "_valid_sets", [None] * (which + 1))[which]
            preds = raw[:, 0] if eng.num_class == 1 else raw
            fret = feval(preds.astype(np.float64), ds)
            if fret is not None:
                items = fret if isinstance(fret, list) else [fret]
                for metric_name, value, higher_better in items:
                    results.append((name, metric_name, value,
                                    higher_better))
        return results

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **_kwargs) -> np.ndarray:
        watch = getattr(self, "_model_watch", None)
        if watch is None:
            return self._predict_dispatch(
                data, start_iteration, num_iteration, raw_score,
                pred_leaf, pred_contrib, _kwargs)
        # serve-side hot-swap: the rate-limited poll AND the model read
        # both run under the watcher's swap lock, so any thread's
        # request sees the old or the new model atomically — the
        # THREADING CONTRACT serving.py documents, enforced here
        # instead of delegated to the caller
        with watch.swap_lock:
            watch.maybe_swap(self)
            return self._predict_dispatch(
                data, start_iteration, num_iteration, raw_score,
                pred_leaf, pred_contrib, _kwargs)

    def _predict_dispatch(self, data, start_iteration, num_iteration,
                          raw_score, pred_leaf, pred_contrib,
                          _kwargs) -> np.ndarray:
        if num_iteration is None:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        es_kwargs = {k: _kwargs[k] for k in
                     ("pred_early_stop", "pred_early_stop_freq",
                      "pred_early_stop_margin", "contrib_force_f64")
                     if k in _kwargs}
        if self._from_model is not None:
            return self._host_predict(
                self._from_model, data, raw_score=raw_score,
                start_iteration=start_iteration,
                num_iteration=num_iteration, pred_leaf=pred_leaf,
                pred_contrib=pred_contrib, **es_kwargs)
        # upstream convention: extra predict kwargs act as per-call
        # parameter overrides — forward the serving knobs to the engine
        serving_kwargs = {k: v for k, v in _kwargs.items()
                          if k.startswith("tpu_predict_")}
        if pred_contrib and not es_kwargs.get("pred_early_stop"):
            # SHAP-capable configs take the engine path: cached device
            # path tables, bucketed zero-compile dispatch, tree
            # sharding. Demoted engines (capability table) explain
            # through the host model with a warned stand-down.
            from . import capabilities
            from .serve.shard import engine_kind
            eng = self.engine
            if bool(getattr(self.config, "linear_tree", False)):
                why = "linear_tree"
            else:
                why = engine_kind(eng)
            verdict = capabilities.sharded_shap_verdict(
                engine_kind(eng), self.config)
            if verdict == capabilities.SUPPORTED:
                return eng.predict_contrib(
                    data, start_iteration=start_iteration,
                    num_iteration=num_iteration or -1,
                    host_model=self._to_host_model(),
                    force_f64=es_kwargs.get("contrib_force_f64"),
                    **serving_kwargs)
            if not getattr(self, "_warned_shap_demote", False):
                self._warned_shap_demote = True
                log.warning(capabilities.SHARDED_SHAP_MESSAGES.get(
                    why, capabilities.SHARDED_SHAP_MESSAGES[
                        "streaming"]))
        if pred_contrib or es_kwargs.get("pred_early_stop"):
            return self._host_predict(
                self._to_host_model(), data, raw_score=raw_score,
                start_iteration=start_iteration,
                num_iteration=num_iteration, pred_leaf=pred_leaf,
                pred_contrib=pred_contrib, **es_kwargs)
        return self.engine.predict(
            data, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration or -1, pred_leaf=pred_leaf,
            **serving_kwargs)

    def _host_predict(self, model, data, **kw) -> np.ndarray:
        """HostModel predicts under the SAME serve instrumentation the
        engine path uses (one shared ``obs.predict_instrumented``
        sequence): a model-file-loaded booster and the pred_contrib /
        pred_early_stop detours are serving paths too — /readyz,
        slo.predict_p99_ms and the request/error counters must see
        them, or a load-model-and-serve pod never turns ready."""
        from . import obs
        if not obs.any_enabled():
            return model.predict(data, **kw)
        return obs.predict_instrumented(
            lambda: model.predict(data, **kw), data)

    # ------------------------------------------------------------------
    def _to_host_model(self):
        """Engine trees -> HostModel, cached until the model changes.

        Repeated ``pred_contrib``/``pred_early_stop`` predicts (and
        ``dump_model``/``model_to_string`` reads) reuse one host model
        instead of rebuilding it from the engine's trees each call. The
        key tracks the engine's model count AND mutation version
        (DART/RF rescale leaves in place without changing the count)
        plus ``best_iteration`` and the booster's param version
        (``reset_parameter`` can change values the host model bakes
        in), all of which the built model depends on."""
        eng = self.engine
        key = (len(eng.models), getattr(eng, "_models_version", -1),
               self.best_iteration, getattr(self, "_params_version", 0))
        cached = getattr(self, "_host_model_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from .io.model_text import HostModel
        hm = HostModel.from_engine(eng, self.config,
                                   best_iteration=self.best_iteration)
        self._host_model_cache = (key, hm)
        return hm

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> Dict:
        """JSON-able model dict (GBDT::DumpModel semantics)."""
        from .io.model_text import dump_model_json
        hm = (self._from_model if self._from_model is not None
              else self._to_host_model())
        return dump_model_json(hm, num_iteration or -1, start_iteration)

    def trees_to_dataframe(self):
        """One row per node/leaf (mirrors lightgbm.Booster
        .trees_to_dataframe; requires pandas)."""
        import pandas as pd
        rows = []

        def walk(ti, node, parent_idx, depth):
            base = {"tree_index": ti, "node_depth": depth,
                    "parent_index": parent_idx}
            if "leaf_value" in node:
                rows.append({**base,
                             "node_index": f"{ti}-L{node['leaf_index']}",
                             "split_feature": None, "threshold": None,
                             "split_gain": None, "decision_type": None,
                             "missing_type": None,
                             "value": node["leaf_value"],
                             "weight": node.get("leaf_weight"),
                             "count": node.get("leaf_count")})
                return f"{ti}-L{node['leaf_index']}"
            me = f"{ti}-S{node['split_index']}"
            row = {**base, "node_index": me,
                   "split_feature": node["split_feature"],
                   "threshold": node["threshold"],
                   "split_gain": node["split_gain"],
                   "decision_type": node["decision_type"],
                   "missing_type": node["missing_type"],
                   "value": node["internal_value"],
                   "weight": None,
                   "count": node["internal_count"]}
            rows.append(row)
            row["left_child"] = walk(ti, node["left_child"], me,
                                     depth + 1)
            row["right_child"] = walk(ti, node["right_child"], me,
                                      depth + 1)
            return me

        for ti, info in enumerate(self.dump_model()["tree_info"]):
            walk(ti, info["tree_structure"], None, 1)
        return pd.DataFrame(rows)

    def model_to_c(self) -> str:
        """Standalone C prediction source (convert_model if-else)."""
        from .io.model_text import model_to_c
        hm = (self._from_model if self._from_model is not None
              else self._to_host_model())
        return model_to_c(hm)

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        from .io.model_text import save_model_string
        if (importance_type == "split"
                and int(self.params.get("saved_feature_importance_type",
                                        0) or 0) == 1):
            # config saved_feature_importance_type=1 -> gain importances
            importance_type = "gain"
        hm = (self._from_model if self._from_model is not None
              else self._to_host_model())
        return save_model_string(hm, importance_type=importance_type)

    def save_model(self, filename: str,
                   num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        with open(filename, "w") as f:
            f.write(self.model_to_string(num_iteration, start_iteration,
                                         importance_type))
        return self

    # ------------------------------------------------------------------
    def num_trees(self) -> int:
        if self._from_model is not None:
            return len(self._from_model.trees)
        return self.engine.num_trees()

    def current_iteration(self) -> int:
        if self._from_model is not None:
            return len(self._from_model.trees) \
                // max(self._from_model.num_class, 1)
        return self.engine.current_iteration

    def num_model_per_iteration(self) -> int:
        if self._from_model is not None:
            return self._from_model.num_class
        return self.engine.num_class

    def num_feature(self) -> int:
        if self._from_model is not None:
            return self._from_model.max_feature_idx + 1
        return self.train_set.num_total_features

    def feature_name(self) -> List[str]:
        if self._from_model is not None:
            return list(self._from_model.feature_names)
        return list(self.train_set.feature_names)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        """Split-count or total-gain importance (GBDT::FeatureImportance)."""
        if self._from_model is not None:
            trees = self._from_model.trees
            n_feat = self._from_model.max_feature_idx + 1
            used = list(range(n_feat))
        else:
            trees = self.engine.models
            n_feat = self.train_set.num_total_features
            used = self.train_set.used_features
        if iteration is not None and iteration > 0:
            trees = trees[:iteration * self.num_model_per_iteration()]
        imp = np.zeros(n_feat, dtype=np.float64)
        for t in trees:
            for i in range(t.num_nodes):
                f = used[int(t.split_feature[i])]
                if importance_type == "gain":
                    imp[f] += float(t.split_gain[i])
                else:
                    imp[f] += 1.0
        if importance_type == "split":
            return imp.astype(np.int64)
        return imp

    def _refit_config(self) -> Config:
        """Config for refit: user params, falling back to the loaded
        model's stored objective when params don't name one."""
        params = dict(self.params)
        has_obj = any(Config.canonical_name(k) == "objective"
                      for k in params)
        if not has_obj:
            hm = (self._from_model if self._from_model is not None
                  else self._to_host_model())
            toks = hm.objective_str.split()
            if toks:
                params["objective"] = toks[0]
                for t in toks[1:]:
                    k, _, v = t.partition(":")
                    if k in ("sigmoid", "num_class"):
                        params[k] = float(v) if k == "sigmoid" else int(v)
        return Config(params)

    def refit(self, data, label, weight=None, group=None,
              decay_rate: Optional[float] = None, **_kwargs) -> "Booster":
        """Refit the existing tree STRUCTURES' leaf values on new data
        (GBDT::RefitTree, src/boosting/gbdt.cpp, UNVERIFIED): boost
        sequentially from the init score — per iteration, compute
        gradients at the current refitted score, re-derive each leaf's
        optimal output from the rows it receives, blend ``decay_rate *
        old + (1 - decay_rate) * new``, and add the refitted tree to the
        score before the next iteration. Returns a new (prediction-only)
        Booster."""
        from .io.model_text import load_model_string, save_model_string
        from .objective import create_objective
        from .ops.split import calc_leaf_output
        import jax
        import jax.numpy as jnp
        cfg = self._refit_config()
        if decay_rate is None:
            decay_rate = cfg.refit_decay_rate
        hm = load_model_string(self.model_to_string())  # deep copy
        X = Dataset._to_matrix(data)
        label = np.asarray(label, dtype=np.float64)
        n = len(X)
        K = max(hm.num_tree_per_iteration, 1)
        obj = create_objective(cfg)
        if hasattr(obj, "prepare"):
            obj.prepare(label, weight)
        if obj.is_ranking:
            if group is None:
                raise LightGBMError("refit on a ranking objective needs "
                                    "the group argument")
            qb = np.concatenate([[0], np.cumsum(np.asarray(group))])
            obj.setup_queries(qb.astype(np.int64), n)
        # boost-from-average on the NEW data (the refit booster in the
        # reference is constructed fresh on the new dataset). The stored
        # model folds the bias into the first iteration's leaves, so the
        # running score is the plain sum of STORED leaf values; s0 only
        # seeds the gradient point before tree 0 exists.
        s0 = np.zeros(K)
        if K == 1:
            s0[0] = obj.init_score(label, weight)
        score = np.zeros((n, K))
        w_dev = None if weight is None else jnp.asarray(weight)
        label_dev = jnp.asarray(label)
        num_iters = len(hm.trees) // K
        leaf_idx = [t.predict_leaf_raw(X) for t in hm.trees]
        for it in range(num_iters):
            if hm.average_output:
                # RF: every tree is independent — gradients at init,
                # each tree carries its own bias
                grad_point = np.tile(s0, (n, 1))
            elif it == 0:
                grad_point = np.tile(s0, (n, 1))
            else:
                grad_point = score
            sc = jnp.asarray(grad_point[:, 0] if K == 1 else grad_point)
            if getattr(obj, "has_pos_state", False):
                # refit with neutral propensities (pos_state=None): the
                # training-time bias state is not serialized with the
                # model
                g, h, _ = obj.get_gradients(sc, label_dev, w_dev)
            elif getattr(obj, "needs_rng", False):
                g, h = obj.get_gradients(sc, label_dev, w_dev,
                                         key=jax.random.PRNGKey(it))
            else:
                g, h = obj.get_gradients(sc, label_dev, w_dev)
            g = np.asarray(g).reshape(n, -1)
            h = np.asarray(h).reshape(n, -1)
            for k in range(K):
                t = hm.trees[it * K + k]
                leaf = leaf_idx[it * K + k]
                nl = t.num_leaves
                gs = np.bincount(leaf, weights=g[:, k], minlength=nl)[:nl]
                hs = np.bincount(leaf, weights=h[:, k], minlength=nl)[:nl]
                cnt = np.bincount(leaf, minlength=nl)[:nl]
                new_out = np.asarray(calc_leaf_output(
                    jnp.asarray(gs), jnp.asarray(hs), cfg.lambda_l1,
                    cfg.lambda_l2, cfg.max_delta_step)) * t.shrinkage
                if hm.average_output or it == 0:
                    # keep the file self-contained: bias in iteration-0
                    # leaves (AddBias), or in every leaf for RF
                    new_out = new_out + s0[k]
                # leaves with no rows in the new data keep their old value
                new_out = np.where(cnt > 0, new_out, t.leaf_value)
                t.leaf_value = (decay_rate * t.leaf_value
                                + (1.0 - decay_rate) * new_out)
                t.leaf_count = cnt.astype(np.int64)
                score[:, k] += t.leaf_value[leaf]
        return Booster(params=self.params,
                       model_str=save_model_string(hm))

    def free_dataset(self) -> "Booster":
        return self

    def free_network(self) -> "Booster":
        return self
