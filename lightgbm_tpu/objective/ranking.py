"""Learning-to-rank objectives: LambdaRank and rank_xendcg.

Reference: src/objective/rank_objective.hpp (UNVERIFIED — empty mount, see
SURVEY.md banner): LambdaRank = NDCG-delta-weighted pairwise logistic
lambdas with truncation at ``lambdarank_truncation_level`` (pairs must
involve a top-T-by-score doc), optional per-query norm; rank_xendcg = the
listwise cross-entropy surrogate with per-iteration random gammas.

TPU-first: the reference's per-query dynamic pair loops become dense
padded tensors — queries padded to a common length M, pairs shaped
``[T, M]`` per query (exactly the truncated pair set), vmapped over a
query batch and scanned over batches. Sorting replaces the reference's
per-query index sorts; everything is fixed-shape under jit.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import Objective
from ..utils import log


def _pad_queries(query_boundaries: np.ndarray) -> Tuple[np.ndarray,
                                                        np.ndarray, int]:
    """Build a padded [Q, M] row-index matrix (-1 padding)."""
    qb = np.asarray(query_boundaries, dtype=np.int64)
    counts = np.diff(qb)
    M = int(counts.max())
    Q = len(counts)
    idx = np.full((Q, M), -1, dtype=np.int32)
    for q in range(Q):
        idx[q, :counts[q]] = np.arange(qb[q], qb[q + 1])
    return idx, counts, M


class _RankingBase(Objective):
    is_ranking = True

    def __init__(self, config):
        super().__init__(config)
        self._qidx = None       # [Q, M] padded row indices
        self._qmask = None      # [Q, M] validity
        self._n_rows = 0
        self._label_gain_table = None   # filled by prepare()

    def setup_queries(self, query_boundaries: np.ndarray,
                      n_rows: int, position=None) -> None:
        if query_boundaries is None:
            log.fatal("Ranking objective requires query/group information")
        idx, counts, M = _pad_queries(query_boundaries)
        self._qidx = jnp.asarray(idx)
        self._qmask = jnp.asarray(idx >= 0)
        self._n_rows = n_rows
        # explicit per-row presentation positions (Metadata::positions,
        # v4.2+): padded to [Q, M]; consumed by lambdarank_unbiased in
        # place of the score rank
        self._qpos = None
        if position is not None:
            pos = np.asarray(position, dtype=np.int64).ravel()
            if len(pos) != n_rows:
                log.fatal(f"Length of position ({len(pos)}) does not "
                          f"match number of data ({n_rows})")
            if pos.min() < 0:
                log.fatal("position field must be non-negative")
            padded = np.where(idx >= 0, pos[np.clip(idx, 0, None)], 0)
            self._qpos = jnp.asarray(padded.astype(np.int32))
            self._n_positions = int(pos.max()) + 1
            self._positions_set()

    def _positions_set(self) -> None:
        """Hook: a `position` field was attached (overridden by
        LambdaRank to auto-enable debiasing, reference behavior)."""

    def _gather_queries(self, arr):
        safe = jnp.maximum(self._qidx, 0)
        return arr[safe]


class LambdaRank(_RankingBase):
    name = "lambdarank"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.truncation = config.lambdarank_truncation_level
        self.norm = config.lambdarank_norm
        # Position debiasing (rank_objective.hpp position_bias_,
        # UNVERIFIED — empty mount; formulation follows Unbiased
        # LambdaMART, Hu et al. 2019): per-position propensity
        # corrections t+ (clicked/high side) and t- (unclicked/low
        # side), estimated each iteration from the accumulated pairwise
        # logistic costs and applied as 1/(t_i+ * t_j-) pair weights.
        # The reference enables this automatically when the dataset has
        # a `position` field (see _positions_set); `lambdarank_unbiased`
        # additionally forces it keyed on score rank (extension). State
        # threads through the boosting step (has_pos_state protocol in
        # boosting/gbdt.py).
        self.unbiased = bool(getattr(config, "lambdarank_unbiased", False))
        self.has_pos_state = self.unbiased
        self.bias_reg = float(getattr(
            config, "lambdarank_position_bias_regularization", 0.0))
        # propensity exponent: reference uses 1/(1+regularization);
        # lambdarank_bias_p_norm >= 0 overrides it directly (extension)
        _p = float(getattr(config, "lambdarank_bias_p_norm", -1.0))
        if _p < 0.0 and _p != -1.0:
            log.fatal("lambdarank_bias_p_norm must be -1 (derive from "
                      "lambdarank_position_bias_regularization) or >= 0, "
                      f"got {_p}")
        self.bias_p_norm = _p if _p >= 0.0 else 1.0 / (1.0 + self.bias_reg)

    def _positions_set(self) -> None:
        # reference behavior: an explicit position field activates
        # debiasing without any flag
        if not self.unbiased:
            log.info("position field detected: enabling LambdaRank "
                     "position debiasing (set lambdarank_unbiased=false "
                     "has no effect here; drop the position field to "
                     "train without debiasing)")
        self.unbiased = True
        self.has_pos_state = True

    def init_pos_state(self):
        """Initial per-rank propensities: all ones ([2, S] — row 0 = t+
        for the HIGH doc, row 1 = t- for the LOW doc). S = the position
        space: max explicit position + 1 when the dataset carries a
        ``position`` field, else the padded query length (score ranks)."""
        S = (self._n_positions if getattr(self, "_qpos", None) is not None
             else self._qidx.shape[1])
        return jnp.ones((2, S), jnp.float32)

    def prepare(self, label: np.ndarray, weight) -> None:
        max_label = int(label.max())
        if self.config.label_gain:
            gains = np.asarray(self.config.label_gain, dtype=np.float64)
        else:
            gains = (2.0 ** np.arange(max(max_label + 1, 1))) - 1.0
        self._gains_np = gains
        self._label_gain_table = jnp.asarray(gains, jnp.float32)

    def get_gradients(self, score, label, weight, pos_state=None):
        if self._qidx is None:
            log.fatal("setup_queries was not called for lambdarank")
        Q, M = self._qidx.shape
        T = min(self.truncation, M)
        sig = self.sigmoid
        gains_tbl = self._label_gain_table
        unbiased = self.unbiased
        use_pos = unbiased and getattr(self, "_qpos", None) is not None
        if unbiased:
            S = (self._n_positions if use_pos else M)
            bias_hi = (pos_state[0] if pos_state is not None
                       else jnp.ones(S, jnp.float32))
            bias_lo = (pos_state[1] if pos_state is not None
                       else jnp.ones(S, jnp.float32))

        s = jnp.where(self._qmask, self._gather_queries(score), -jnp.inf)
        y = jnp.where(self._qmask,
                      self._gather_queries(label).astype(jnp.int32), -1)

        qpos_all = (self._qpos if use_pos
                    else jnp.zeros_like(self._qidx))

        def per_query(sq, yq, maskq, pq):
            # score-descending order (ties broken by index, like a stable
            # sort on the reference side)
            order = jnp.argsort(-sq, stable=True)          # [M]
            s_sorted = sq[order]
            y_sorted = yq[order]
            valid_sorted = maskq[order]
            g_sorted = jnp.where(valid_sorted,
                                 gains_tbl[jnp.maximum(y_sorted, 0)], 0.0)
            disc = 1.0 / jnp.log2(jnp.arange(M, dtype=jnp.float32) + 2.0)
            # max DCG at truncation level over ideal (label-sorted) order
            ideal = jnp.sort(g_sorted)[::-1]
            maxdcg = jnp.sum(ideal[:T] * disc[:T])
            inv_maxdcg = jnp.where(maxdcg > 0, 1.0 / maxdcg, 0.0)

            # pair tensor: i in [0, T) (by score rank), j in [0, M)
            si = s_sorted[:T, None]
            sj = s_sorted[None, :]
            yi = y_sorted[:T, None]
            yj = y_sorted[None, :]
            gi = g_sorted[:T, None]
            gj = g_sorted[None, :]
            di = disc[:T, None]
            dj = disc[None, :]
            j_after_i = (jnp.arange(M)[None, :]
                         > jnp.arange(T)[:, None])
            pair_ok = (j_after_i & valid_sorted[None, :]
                       & valid_sorted[:T, None] & (yi != yj))

            # (high, low) by label within the pair
            i_is_high = yi > yj
            s_high = jnp.where(i_is_high, si, sj)
            s_low = jnp.where(i_is_high, sj, si)
            delta = (jnp.abs(gi - gj) * jnp.abs(di - dj) * inv_maxdcg)
            rho = jax.nn.sigmoid(-sig * (s_high - s_low))  # P(wrong order)
            lam = sig * rho * delta                         # magnitude
            hess_pair = sig * sig * rho * (1.0 - rho) * delta
            lam = jnp.where(pair_ok, lam, 0.0)
            hess_pair = jnp.where(pair_ok, hess_pair, 0.0)
            if unbiased:
                # position of the high/low doc of each pair: the
                # dataset's explicit presentation position when given,
                # else the score rank
                if use_pos:
                    p_sorted = pq[order]
                    ri = p_sorted[:T, None]
                    rj = p_sorted[None, :]
                else:
                    ri = jnp.arange(T, dtype=jnp.int32)[:, None]
                    rj = jnp.arange(M, dtype=jnp.int32)[None, :]
                rank_h = jnp.where(i_is_high, ri, rj)       # [T, M]
                rank_l = jnp.where(i_is_high, rj, ri)
                t_hi = bias_hi[rank_h]
                t_lo = bias_lo[rank_l]
                # pairwise logistic cost at the CURRENT model, weighted
                # like the lambdas; each side's accumulator divides by
                # the OTHER side's propensity (Hu et al. eq. 14/15)
                p_cost = jnp.where(
                    pair_ok,
                    -jnp.log(jnp.maximum(1.0 - rho, 1e-20)) * delta, 0.0)
                cost_hi_q = jnp.zeros(S, jnp.float32).at[rank_h].add(
                    p_cost / t_lo)
                cost_lo_q = jnp.zeros(S, jnp.float32).at[rank_l].add(
                    p_cost / t_hi)
                inv_w = 1.0 / (t_hi * t_lo)
                lam = lam * inv_w
                hess_pair = hess_pair * inv_w
            else:
                cost_hi_q = cost_lo_q = jnp.zeros(1, jnp.float32)

            # accumulate: high doc gets -lam, low doc gets +lam
            lam_i = jnp.where(i_is_high, -lam, lam)         # [T, M]
            lam_j = -lam_i
            grad_sorted = jnp.zeros(M, jnp.float32)
            grad_sorted = grad_sorted.at[:T].add(jnp.sum(lam_i, axis=1))
            grad_sorted = grad_sorted + jnp.sum(lam_j, axis=0)
            hess_sorted = jnp.zeros(M, jnp.float32)
            hess_sorted = hess_sorted.at[:T].add(jnp.sum(hess_pair, axis=1))
            hess_sorted = hess_sorted + jnp.sum(hess_pair, axis=0)

            if self.norm:
                sum_lam = jnp.sum(jnp.abs(lam))
                norm_factor = jnp.where(
                    sum_lam > 0, jnp.log2(1.0 + sum_lam) / sum_lam, 1.0)
                grad_sorted = grad_sorted * norm_factor
                hess_sorted = hess_sorted * norm_factor

            # undo the sort
            grad_q = jnp.zeros(M, jnp.float32).at[order].set(grad_sorted)
            hess_q = jnp.zeros(M, jnp.float32).at[order].set(hess_sorted)
            return grad_q, hess_q, cost_hi_q, cost_lo_q

        grad_q, hess_q, cost_hi, cost_lo = jax.vmap(per_query)(
            s, y, self._qmask, qpos_all)

        grad = jnp.zeros(score.shape[0], jnp.float32)
        hess = jnp.zeros(score.shape[0], jnp.float32)
        safe = jnp.maximum(self._qidx, 0)
        gq = jnp.where(self._qmask, grad_q, 0.0)
        hq = jnp.where(self._qmask, hess_q, 0.0)
        grad = grad.at[safe.ravel()].add(gq.ravel())
        hess = hess.at[safe.ravel()].add(hq.ravel())
        if weight is not None:
            grad = grad * weight
            hess = hess * weight
        if not unbiased:
            return grad, hess
        # ---- propensity update: t[r] = (C[r] / C[0])^p with
        # p = 1/(1+lambdarank_position_bias_regularization) (reference
        # UpdatePositionBiasFactors semantics, UNVERIFIED — empty mount;
        # an explicit lambdarank_bias_p_norm=0 makes this an exact
        # no-op, pinned by tests/test_ranking_unbiased.py) ---------------
        chi = jnp.sum(cost_hi, axis=0)                     # [S]
        clo = jnp.sum(cost_lo, axis=0)                     # [S]

        def propensity(c):
            # anchor on the first position that actually accumulated
            # cost (1-based or sparse position ids leave c[0] == 0,
            # which would blow the ratio up by ~1e20)
            first = jnp.argmax(c > 0)
            c0 = jnp.maximum(c[first], 1e-20)
            ratio = jnp.maximum(c / c0, 1e-6)
            t = ratio ** self.bias_p_norm
            # ranks that saw no pairs keep their neutral propensity
            return jnp.where(c > 0, jnp.maximum(t, 1e-3), 1.0)

        new_state = jnp.stack([propensity(chi), propensity(clo)])
        return grad, hess, new_state


class RankXENDCG(_RankingBase):
    name = "rank_xendcg"
    needs_rng = True  # per-iteration gammas; key is a step argument so it
    # is NOT baked into the jit trace

    def __init__(self, config):
        super().__init__(config)

    def prepare(self, label: np.ndarray, weight) -> None:
        pass

    def get_gradients(self, score, label, weight, key=None):
        if self._qidx is None:
            log.fatal("setup_queries was not called for rank_xendcg")
        if key is None:
            key = jax.random.PRNGKey(0)
        Q, M = self._qidx.shape
        s = jnp.where(self._qmask, self._gather_queries(score), -jnp.inf)
        y = jnp.where(self._qmask, self._gather_queries(label), 0.0)
        gammas = jax.random.uniform(key, (Q, M))

        rho = jax.nn.softmax(s, axis=1)                  # padded -> 0
        phi = jnp.where(self._qmask, (2.0 ** y) - 1.0 + gammas, 0.0)
        denom = jnp.sum(phi, axis=1, keepdims=True)
        p = phi / jnp.maximum(denom, 1e-20)
        grad_q = jnp.where(self._qmask, rho - p, 0.0)
        hess_q = jnp.where(self._qmask, rho * (1.0 - rho), 0.0)
        hess_q = jnp.maximum(hess_q, 1e-16)

        grad = jnp.zeros(score.shape[0], jnp.float32)
        hess = jnp.zeros(score.shape[0], jnp.float32)
        safe = jnp.maximum(self._qidx, 0)
        grad = grad.at[safe.ravel()].add(
            jnp.where(self._qmask, grad_q, 0.0).ravel())
        hess = hess.at[safe.ravel()].add(
            jnp.where(self._qmask, hess_q, 0.0).ravel())
        if weight is not None:
            grad = grad * weight
            hess = hess * weight
        return grad, hess
