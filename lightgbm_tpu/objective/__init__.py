"""Objective functions: per-row gradient/hessian producers.

Reference: src/objective/*.hpp + ``ObjectiveFunction::CreateObjectiveFunction``
(src/objective/objective_function.cpp, UNVERIFIED — empty mount, see
SURVEY.md banner). Each objective supplies ``GetGradients(score) ->
(grad, hess)``, an optional boost-from-average init score, and the
score→output transform used at predict time.

TPU-first: objectives are pure ``jnp`` element-wise functions, so they fuse
into the training step under jit (the reference dispatches to OpenMP loops
or CUDA kernels, src/objective/cuda/*). Ranking objectives (lambdarank,
rank_xendcg) live in ``ranking.py`` as segment formulations.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log

Array = jax.Array


class Objective:
    """Base objective. Subclasses implement pure-jnp ``get_gradients``."""

    name = "base"
    is_ranking = False
    # number of boosted models per iteration (K for multiclass)
    def num_models(self, num_class: int) -> int:
        return 1

    def __init__(self, config):
        self.config = config

    def init_score(self, label: np.ndarray,
                   weight: Optional[np.ndarray]) -> float:
        """BoostFromAverage initial score (host-side, once)."""
        return 0.0

    def get_gradients(self, score: Array, label: Array,
                      weight: Optional[Array]) -> Tuple[Array, Array]:
        raise NotImplementedError

    def convert_output(self, score: Array) -> Array:
        """Raw score -> prediction-space transform (identity by default)."""
        return score

    def renew_tree_output(self, *_args, **_kw):
        """Hook for leaf re-fitting (L1/quantile/MAPE median renewal)."""
        return None

    def _apply_weight(self, grad, hess, weight):
        if weight is None:
            return grad, hess
        return grad * weight, hess * weight

    # -- multi-host BoostFromAverage sync (the reference's
    # Network::GlobalSyncUpByMean; SURVEY.md §2.3) ----------------------
    def init_mean_stats(self, label, weight):
        """``(weighted_sum, weight_total)`` such that
        ``init_from_mean(weighted_sum / weight_total)`` reproduces
        ``init_score`` — the syncable decomposition for multi-host
        boost_from_average. None when the init score is not a mean
        statistic (the median/percentile family)."""
        return None

    def init_from_mean(self, mean: float) -> float:
        raise NotImplementedError

    @staticmethod
    def _mean_stats_of(v: np.ndarray, weight) -> Tuple[float, float]:
        if weight is None:
            return float(np.sum(v)), float(len(v))
        return float(np.sum(v * weight)), float(np.sum(weight))

    @staticmethod
    def _wavg(v: np.ndarray, weight: Optional[np.ndarray]) -> float:
        if weight is None:
            return float(np.mean(v))
        return float(np.sum(v * weight) / np.sum(weight))


# ---------------------------------------------------------------------------
# Regression family (src/objective/regression_objective.hpp, UNVERIFIED)
# ---------------------------------------------------------------------------
class RegressionL2(Objective):
    name = "regression"

    def __init__(self, config):
        super().__init__(config)
        # reg_sqrt (regression_objective.hpp sqrt mode): fit
        # sign(y)*sqrt(|y|) instead of y; predictions convert back as
        # sign(s)*s^2
        self.reg_sqrt = bool(getattr(config, "reg_sqrt", False))

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        if self.reg_sqrt:
            label = np.sign(label) * np.sqrt(np.abs(label))
        return self._wavg(label, weight)

    def get_gradients(self, score, label, weight):
        if self.reg_sqrt:
            label = jnp.sign(label) * jnp.sqrt(jnp.abs(label))
        grad = score - label
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        if self.reg_sqrt:
            return jnp.sign(score) * score * score
        return score

    def init_mean_stats(self, label, weight):
        if self.reg_sqrt:
            label = np.sign(label) * np.sqrt(np.abs(label))
        return self._mean_stats_of(label, weight)

    def init_from_mean(self, mean):
        return float(mean)


class RegressionL1(Objective):
    name = "regression_l1"

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        # weighted median of the label
        return _weighted_percentile_np(label, weight, 0.5)

    def get_gradients(self, score, label, weight):
        grad = jnp.sign(score - label)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)

    def renew_tree_output(self, score, label, weight, leaf_id, num_leaves):
        return _leaf_percentile_renewal(score, label, weight, leaf_id,
                                        num_leaves, 0.5)


class Huber(Objective):
    name = "huber"

    def get_gradients(self, score, label, weight):
        alpha = self.config.alpha
        r = score - label
        grad = jnp.clip(r, -alpha, alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)


class Fair(Objective):
    name = "fair"

    def get_gradients(self, score, label, weight):
        c = self.config.fair_c
        r = score - label
        denom = jnp.abs(r) + c
        grad = c * r / denom
        hess = c * c / (denom * denom)
        return self._apply_weight(grad, hess, weight)


class Poisson(Objective):
    name = "poisson"

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        return float(np.log(max(self._wavg(label, weight), 1e-9)))

    def get_gradients(self, score, label, weight):
        grad = jnp.exp(score) - label
        hess = jnp.exp(score + self.config.poisson_max_delta_step)
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        return jnp.exp(score)

    def init_mean_stats(self, label, weight):
        return self._mean_stats_of(np.asarray(label, np.float64), weight)

    def init_from_mean(self, mean):
        return float(np.log(max(mean, 1e-9)))


class Quantile(Objective):
    name = "quantile"

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        return _weighted_percentile_np(label, weight, self.config.alpha)

    def get_gradients(self, score, label, weight):
        alpha = self.config.alpha
        grad = jnp.where(label - score > 0, -alpha, 1.0 - alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess, weight)

    def renew_tree_output(self, score, label, weight, leaf_id, num_leaves):
        return _leaf_percentile_renewal(score, label, weight, leaf_id,
                                        num_leaves, self.config.alpha)


class MAPE(Objective):
    name = "mape"

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        return _weighted_percentile_np(label, weight, 0.5)

    def get_gradients(self, score, label, weight):
        scale = 1.0 / jnp.maximum(jnp.abs(label), 1.0)
        grad = jnp.sign(score - label) * scale
        hess = scale
        return self._apply_weight(grad, hess, weight)

    def renew_tree_output(self, score, label, weight, leaf_id, num_leaves):
        # weighted median with the 1/|label| scaling folded into weights
        scale = 1.0 / np.maximum(np.abs(np.asarray(label)), 1.0)
        w = scale if weight is None else scale * np.asarray(weight)
        return _leaf_percentile_renewal(score, label, w, leaf_id,
                                        num_leaves, 0.5)


class Gamma(Objective):
    name = "gamma"

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        return float(np.log(max(self._wavg(label, weight), 1e-9)))

    def get_gradients(self, score, label, weight):
        e = jnp.exp(-score)
        grad = 1.0 - label * e
        hess = label * e
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        return jnp.exp(score)

    def init_mean_stats(self, label, weight):
        return self._mean_stats_of(np.asarray(label, np.float64), weight)

    def init_from_mean(self, mean):
        return float(np.log(max(mean, 1e-9)))


class Tweedie(Objective):
    name = "tweedie"

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        return float(np.log(max(self._wavg(label, weight), 1e-9)))

    def get_gradients(self, score, label, weight):
        rho = self.config.tweedie_variance_power
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        grad = -label * e1 + e2
        hess = -label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        return jnp.exp(score)

    def init_mean_stats(self, label, weight):
        return self._mean_stats_of(np.asarray(label, np.float64), weight)

    def init_from_mean(self, mean):
        return float(np.log(max(mean, 1e-9)))


# ---------------------------------------------------------------------------
# Binary classification (src/objective/binary_objective.hpp, UNVERIFIED)
# ---------------------------------------------------------------------------
class Binary(Objective):
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self._pos_weight = 1.0
        self._neg_weight = 1.0

    def prepare(self, label: np.ndarray, weight) -> None:
        """Compute class weights (is_unbalance / scale_pos_weight)."""
        cnt_pos = float(np.sum(label > 0))
        cnt_neg = float(len(label) - cnt_pos)
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                self._pos_weight = 1.0
                self._neg_weight = cnt_pos / cnt_neg
            else:
                self._pos_weight = cnt_neg / cnt_pos
                self._neg_weight = 1.0
        else:
            self._pos_weight = self.config.scale_pos_weight
            self._neg_weight = 1.0

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        pavg = min(max(self._wavg((label > 0).astype(np.float64), weight),
                       1e-15), 1.0 - 1e-15)
        init = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        log.info(f"[binary:BoostFromScore]: pavg={pavg:.6f} -> "
                 f"initscore={init:.6f}")
        return init

    def get_gradients(self, score, label, weight):
        sig = self.sigmoid
        y = (label > 0).astype(score.dtype)
        p = jax.nn.sigmoid(sig * score)
        label_w = jnp.where(y > 0, self._pos_weight, self._neg_weight)
        grad = sig * (p - y) * label_w
        hess = sig * sig * p * (1.0 - p) * label_w
        return self._apply_weight(grad, hess, weight)

    def convert_output(self, score):
        return jax.nn.sigmoid(self.sigmoid * score)

    def init_mean_stats(self, label, weight):
        return self._mean_stats_of((label > 0).astype(np.float64),
                                   weight)

    def init_from_mean(self, mean):
        pavg = min(max(float(mean), 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)


# ---------------------------------------------------------------------------
# Multiclass (src/objective/multiclass_objective.hpp, UNVERIFIED)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(Objective):
    name = "multiclass"

    def num_models(self, num_class):
        return num_class

    def get_gradients(self, score, label, weight):
        # score: [n, K]
        K = score.shape[1]
        y = jax.nn.one_hot(label.astype(jnp.int32), K, dtype=score.dtype)
        p = jax.nn.softmax(score, axis=1)
        grad = p - y
        # the factor-2 hessian follows the reference's multiclass softmax
        hess = 2.0 * p * (1.0 - p)
        if weight is not None:
            grad = grad * weight[:, None]
            hess = hess * weight[:, None]
        return grad, hess

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=-1)


class MulticlassOVA(Objective):
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = config.sigmoid

    def num_models(self, num_class):
        return num_class

    def get_gradients(self, score, label, weight):
        K = score.shape[1]
        y = jax.nn.one_hot(label.astype(jnp.int32), K, dtype=score.dtype)
        sig = self.sigmoid
        p = jax.nn.sigmoid(sig * score)
        grad = sig * (p - y)
        hess = sig * sig * p * (1.0 - p)
        if weight is not None:
            grad = grad * weight[:, None]
            hess = hess * weight[:, None]
        return grad, hess

    def convert_output(self, score):
        p = jax.nn.sigmoid(self.sigmoid * score)
        return p / jnp.sum(p, axis=-1, keepdims=True)


# ---------------------------------------------------------------------------
# Cross-entropy family (src/objective/xentropy_objective.hpp, UNVERIFIED)
# ---------------------------------------------------------------------------
class CrossEntropy(Objective):
    name = "cross_entropy"

    def init_score(self, label, weight):
        if not self.config.boost_from_average:
            return 0.0
        pavg = min(max(self._wavg(label, weight), 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)))

    def get_gradients(self, score, label, weight):
        p = jax.nn.sigmoid(score)
        if weight is None:
            return p - label, p * (1.0 - p)
        # weighted cross-entropy: gradient scales with weight
        return (p - label) * weight, p * (1.0 - p) * weight

    def convert_output(self, score):
        return jax.nn.sigmoid(score)


class CrossEntropyLambda(Objective):
    name = "cross_entropy_lambda"

    def get_gradients(self, score, label, weight):
        # intensity parameterization: score = log(exp(eps)-1) domain;
        # follows the reference's xentlambda with weights folded in
        w = jnp.ones_like(score) if weight is None else weight
        eps = jnp.log1p(jnp.exp(score))     # softplus
        sig = jax.nn.sigmoid(score)
        hhat = 1.0 - jnp.exp(-w * eps)
        grad = sig * (w * (1.0 - label / jnp.maximum(hhat, 1e-15)
                           * jnp.exp(-w * eps)))
        hess_base = sig * (1.0 - sig)
        hess = jnp.maximum(hess_base * w, 1e-15)
        return grad, hess

    def convert_output(self, score):
        return jnp.log1p(jnp.exp(score))


class CustomObjective(Objective):
    """Placeholder for user-supplied fobj (engine handles the callable)."""

    name = "custom"

    def get_gradients(self, score, label, weight):
        log.fatal("custom objective must be provided as a callable fobj")


# ---------------------------------------------------------------------------
# helpers + factory
# ---------------------------------------------------------------------------
def _weighted_percentile_np(v: np.ndarray, weight: Optional[np.ndarray],
                            alpha: float) -> float:
    v = np.asarray(v, dtype=np.float64)
    if weight is None:
        return float(np.percentile(v, alpha * 100.0,
                                   method="inverted_cdf"))
    order = np.argsort(v)
    cw = np.cumsum(np.asarray(weight, dtype=np.float64)[order])
    cut = alpha * cw[-1]
    idx = int(np.searchsorted(cw, cut))
    return float(v[order[min(idx, len(v) - 1)]])


def _leaf_percentile_renewal(score, label, weight, leaf_id, num_leaves,
                             alpha):
    """Per-leaf weighted percentile of residuals (RenewTreeOutput).

    Host-side numpy (runs once per tree for L1-family objectives).
    """
    score = np.asarray(score)
    label = np.asarray(label)
    leaf_id = np.asarray(leaf_id)
    out = np.zeros(num_leaves, dtype=np.float64)
    resid = label - score
    for lf in range(num_leaves):
        m = leaf_id == lf
        if not m.any():
            continue
        w = None if weight is None else np.asarray(weight)[m]
        out[lf] = _weighted_percentile_np(resid[m], w, alpha)
    return out


_REGISTRY: Dict[str, Callable[..., Objective]] = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": MAPE,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": Binary,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "custom": CustomObjective,
}


def create_objective(config) -> Objective:
    """Factory by canonical objective name (after Config alias resolution)."""
    name = config.objective
    if name in _REGISTRY:
        return _REGISTRY[name](config)
    if name in ("lambdarank", "rank_xendcg"):
        from .ranking import LambdaRank, RankXENDCG
        return (LambdaRank if name == "lambdarank" else RankXENDCG)(config)
    log.fatal(f"Unknown objective {name}")
