"""THE capability table: feature × engine → supported / demote / fatal.

Reference LightGBM dispatches one config surface across boosting modes
(``Boosting::CreateBoosting``) and tree learners with the eligibility
rules scattered through constructors; through PR 12 this reproduction
was growing the same fragmentation — ``_streaming_compatible`` vs
StreamingGBDT's ``_no()`` gates drifted into bugs three separate times,
and the device-ingest / hist-partition / auto-quantize auto modes each
encoded their own eligibility lists (ROADMAP item 4).

This module is the ONE place those judgments live:

- :data:`CAPABILITIES` — the declarative feature × engine table. A
  *feature* is a named predicate over a resolved :class:`~.config.Config`
  (plus the runtime-only features a constructor sees: a custom ``fobj``,
  ``init_forest`` continuation). An *engine* is one of
  :data:`ENGINES`. The verdict is :data:`SUPPORTED` (engine trains it),
  :data:`DEMOTE` (engine trains it after quietly dropping the feature —
  only ever auto-applied features), or :data:`FATAL` (engine must
  refuse at construction).
- The **eligibility constants** the auto modes consume
  (:data:`AUTO_QUANTIZE_OBJECTIVES`, :data:`STRATIFIABLE_OBJECTIVES`,
  :data:`STREAM_MAX_LEAVES`, ...). Inline copies of these lists
  anywhere else in the tree are flagged by the capability-gate checker
  (``python -m tools.analyze``, docs/static-analysis.md).
- The **auto-mode policies** that route between engines/paths:
  :func:`hist_partition_auto` (the ``tpu_hist_partition=auto`` cost
  model) and :func:`device_ingest_verdict` (can the engine these params
  force adopt device-resident ingest output?).

Consumers: ``boosting.create_boosting`` / ``_streaming_compatible``,
``StreamingGBDT.__init__``, ``RandomForest.__init__``,
``Dataset._want_device_ingest``, ``GBDT.__init__`` (auto-quantize +
hist-partition), ``engine.cv`` (stratification). The drift-guard sweeps
in tests/test_analysis.py and tests/test_streaming_sharded.py pin
table ⟺ constructor agreement for every engine: a gate added or lifted
on one side without the other goes red in CI.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

__all__ = [
    "SUPPORTED", "DEMOTE", "FATAL", "ENGINES", "CAPABILITIES",
    "Capability", "requested_features", "verdict", "engine_verdicts",
    "fatal_features", "demoted_features", "supports",
    "RANKING_OBJECTIVES", "AUTO_QUANTIZE_OBJECTIVES",
    "AUTO_QUANT_MIN_ROWS", "STRATIFIABLE_OBJECTIVES",
    "MULTI_TREE_OBJECTIVES",
    "STREAM_MAX_LEAVES", "STREAM_TREE_LEARNERS",
    "HIST_PARTITION_MIN_ROWS", "hist_partition_auto",
    "DEVICE_INGEST", "device_ingest_verdict", "forced_engine",
    "SHARDED_PREDICT", "sharded_predict_verdict",
    "SHARDED_SHAP", "SHARDED_SHAP_MESSAGES", "sharded_shap_verdict",
    "STREAM_RECUT", "stream_recut_verdict",
    "stream_recut_verdict_params",
]

SUPPORTED = "supported"
DEMOTE = "demote"
FATAL = "fatal"

# the boosting engines create_boosting can return (serving rides GBDT's
# predict surface and has no construction gates of its own)
ENGINES = ("gbdt", "dart", "rf", "streaming")

# ---------------------------------------------------------------------------
# Eligibility constants (the auto modes' lists — keep them HERE)
# ---------------------------------------------------------------------------
# objectives whose training is a ranking problem (need query groups;
# streamed level sweeps cannot evaluate listwise lambdas per block)
RANKING_OBJECTIVES = ("lambdarank", "rank_xendcg")

# tpu_auto_quantize only flips use_quantized_grad on for objectives the
# round-5 >=500k-row equal-round A/B validated at equal-or-better
# holdout quality (docs/perf.md "quantized by default")
AUTO_QUANTIZE_OBJECTIVES = ("binary", "regression", "multiclass",
                            "multiclassova", "cross_entropy")
# ... and only at the scale the A/B measured; below it the exact-f32
# default keeps reference bit-compatibility
AUTO_QUANT_MIN_ROWS = 500_000

# classification objectives cv() can stratify folds for
STRATIFIABLE_OBJECTIVES = ("binary", "multiclass", "multiclassova")

# objectives training one tree PER CLASS per iteration
# (Config.num_tree_per_iteration)
MULTI_TREE_OBJECTIVES = ("multiclass", "multiclassova")

# streaming keeps per-row leaf ids in int16 device state
STREAM_MAX_LEAVES = 32767
# streamed training shards ROWS; voting/feature-parallel split search
# needs the resident column layout
STREAM_TREE_LEARNERS = ("serial", "data")

# tpu_hist_partition=auto only engages where the repartition move
# amortizes (pool-mode Pallas path over a large un-compacted source)
HIST_PARTITION_MIN_ROWS = 1 << 20


class Capability(NamedTuple):
    """One table row: how to detect the feature + per-engine verdicts."""

    describe: str                           # phrase for fatal messages
    requested: Callable[[Any], bool]        # predicate over Config
    verdicts: Dict[str, str]                # engine -> verdict;
    #                                         absent engine = SUPPORTED
    example: Optional[Dict[str, Any]] = None  # params witnessing the
    #                                           feature (sweep tests)
    messages: Dict[str, str] = {}           # engine -> exact fatal text
    #                                         (back-compat error wording)


def _has_cegb(c) -> bool:
    # StreamingGBDT rejects ANY CEGB knob, including a bare non-default
    # cegb_tradeoff
    return (c.cegb_tradeoff != 1.0 or c.cegb_penalty_split > 0
            or bool(c.cegb_penalty_feature_coupled)
            or bool(c.cegb_penalty_feature_lazy))


def _no_bagging(c) -> bool:
    return not (c.bagging_freq > 0
                and (c.bagging_fraction < 1.0
                     or c.pos_bagging_fraction < 1.0
                     or c.neg_bagging_fraction < 1.0))


# ---------------------------------------------------------------------------
# THE TABLE. Every entry name is also the key runtime `extra` flags use
# (StreamingGBDT passes extra={"custom_objective": fobj is not None, ...}).
# `example` params must make the predicate True on top of any base
# config — tests/test_analysis.py constructs every FATAL (feature,
# engine) pair from them and asserts the constructor refuses.
# ---------------------------------------------------------------------------
CAPABILITIES: Dict[str, Capability] = {
    "custom_objective": Capability(
        "a custom objective function",
        lambda c: str(c.objective) == "custom",
        {"streaming": FATAL},
        example={"objective": "custom"}),
    "continuation": Capability(
        "training continuation/init_model",
        lambda c: False,                    # runtime-only (init_forest)
        {"streaming": FATAL}),
    "multiclass": Capability(
        "multiclass",
        lambda c: c.num_tree_per_iteration > 1,
        {"streaming": FATAL},
        example={"objective": "multiclass", "num_class": 3}),
    "ranking_objective": Capability(
        "ranking objectives",
        lambda c: str(c.objective) in RANKING_OBJECTIVES,
        {"streaming": FATAL},
        example={"objective": "lambdarank"}),
    "nonrow_tree_learner": Capability(
        f"tree_learner outside {STREAM_TREE_LEARNERS} (streamed "
        f"training shards ROWS; voting/feature-parallel search needs "
        f"the resident column layout)",
        # WHITELIST, like the pre-table gate: a future learner type is
        # streaming-unsupported until someone adds it to
        # STREAM_TREE_LEARNERS deliberately
        lambda c: c.tree_learner not in STREAM_TREE_LEARNERS,
        {"streaming": FATAL},
        example={"tree_learner": "voting"}),
    "dart_boosting": Capability(
        "boosting=dart",
        lambda c: c.boosting == "dart",
        {"streaming": FATAL},
        example={"boosting": "dart"}),
    "rf_boosting": Capability(
        "boosting=rf",
        lambda c: c.boosting == "rf",
        {"streaming": FATAL},
        example={"boosting": "rf", "bagging_freq": 1,
                 "bagging_fraction": 0.8}),
    "goss": Capability(
        "GOSS sampling",
        lambda c: str(c.data_sample_strategy) == "goss",
        {"rf": FATAL},
        example={"data_sample_strategy": "goss"},
        messages={"rf": "Cannot use GOSS with random forest"}),
    "no_bagging": Capability(
        "training without bagging",
        _no_bagging,
        {"rf": FATAL},
        # explicit spellings so the example composes over ANY base
        # config (the sweep merges it on top of rf's bagging defaults)
        example={"bagging_freq": 0, "bagging_fraction": 1.0,
                 "pos_bagging_fraction": 1.0,
                 "neg_bagging_fraction": 1.0},
        messages={"rf": "Random forest needs bagging: set "
                        "bagging_freq > 0 and bagging_fraction < 1.0"}),
    "linear_tree": Capability(
        "linear_tree",
        lambda c: bool(c.linear_tree),
        {"streaming": FATAL},
        example={"linear_tree": True}),
    "monotone_constraints": Capability(
        "monotone constraints",
        lambda c: bool(c.monotone_constraints),
        {"streaming": FATAL},
        example={"monotone_constraints": [1, 0, 0, 0]}),
    "interaction_constraints": Capability(
        "interaction constraints",
        lambda c: bool(c.interaction_constraints),
        {"streaming": FATAL},
        example={"interaction_constraints": [[0, 1], [2, 3]]}),
    "cegb": Capability(
        "CEGB",
        _has_cegb,
        {"streaming": FATAL},
        example={"cegb_tradeoff": 2.0}),
    "forced_splits": Capability(
        "forced splits",
        lambda c: bool(c.forcedsplits_filename),
        {"streaming": FATAL},
        example={"forcedsplits_filename": "forced.json"}),
    "categorical_features": Capability(
        "categorical features",
        lambda c: bool(c.categorical_feature),
        {"streaming": FATAL},
        example={"categorical_feature": "0"}),
    "wide_leaves": Capability(
        f"num_leaves > {STREAM_MAX_LEAVES} (int16 per-row leaf-id "
        f"state caps streamed trees)",
        lambda c: int(c.num_leaves) > STREAM_MAX_LEAVES,
        {"streaming": FATAL},
        example={"num_leaves": 40_000}),
    "auto_quantize": Capability(
        "auto-enabled quantized gradients (tpu_auto_quantize)",
        lambda c: bool(getattr(c, "_quantize_auto", False)),
        # an un-asked-for discretization would change streamed
        # numerics — quietly demote to exact f32. An EXPLICIT
        # use_quantized_grad stays honored (integer level histograms
        # are what make sharded streaming bit-exact).
        {"streaming": DEMOTE}),
}


# ---------------------------------------------------------------------------
# accessors
# ---------------------------------------------------------------------------
def requested_features(config,
                       extra: Optional[Dict[str, bool]] = None
                       ) -> List[str]:
    """Names of the table features ``config`` (plus runtime ``extra``
    flags) exhibits."""
    extra = extra or {}
    out = []
    for name, cap in CAPABILITIES.items():
        if extra.get(name) or cap.requested(config):
            out.append(name)
    return out


def verdict(feature: str, engine: str) -> str:
    return CAPABILITIES[feature].verdicts.get(engine, SUPPORTED)


def engine_verdicts(engine: str, config,
                    extra: Optional[Dict[str, bool]] = None
                    ) -> List[Tuple[str, Capability, str]]:
    """(feature, capability, verdict) for every non-SUPPORTED verdict
    the engine assigns to a feature this config requests — the loop a
    constructor's gate walks."""
    out = []
    for name in requested_features(config, extra):
        cap = CAPABILITIES[name]
        v = cap.verdicts.get(engine, SUPPORTED)
        if v != SUPPORTED:
            out.append((name, cap, v))
    return out


def fatal_features(engine: str, config,
                   extra: Optional[Dict[str, bool]] = None
                   ) -> List[str]:
    return [n for n, _c, v in engine_verdicts(engine, config, extra)
            if v == FATAL]


def demoted_features(engine: str, config,
                     extra: Optional[Dict[str, bool]] = None
                     ) -> List[str]:
    return [n for n, _c, v in engine_verdicts(engine, config, extra)
            if v == DEMOTE]


def supports(engine: str, config,
             extra: Optional[Dict[str, bool]] = None) -> bool:
    """True iff the engine's constructor would accept this config
    (demotions allowed; dataset-level gates — e.g. pandas-categorical
    bins under streaming — are re-checked by the constructor itself)."""
    return not fatal_features(engine, config, extra)


# ---------------------------------------------------------------------------
# auto-mode policies
# ---------------------------------------------------------------------------
def hist_partition_auto(config, use_pallas: bool,
                        n_pad: int) -> Tuple[bool, Optional[str]]:
    """The ``tpu_hist_partition=auto`` cost model: engage the
    leaf-ordered row partition only where the per-round repartition
    move pays for itself — the Pallas pool path over a large
    un-compacted source (docs/perf.md "Partitioned histograms").
    Returns ``(engage, stand_down_reason)``; the reason is None when
    engaging or when the path was never plausible (non-Pallas /
    rebuild mode, where no stand-down message is owed)."""
    if not use_pallas or str(config.tpu_hist_mode) != "pool":
        return False, None
    if str(config.data_sample_strategy) == "goss":
        return False, "GOSS already compacts the scan"
    if n_pad < HIST_PARTITION_MIN_ROWS:
        return False, ("dataset too small to amortize the "
                       "repartition move")
    return True, None


# which engines can ADOPT device-resident ingest output (ops/ingest.py):
# the streaming engine's host-block scan never adopts device bins —
# they would sit orphaned in HBM, so device ingest demotes to host
# binning when the params force the out-of-core engine
DEVICE_INGEST: Dict[str, str] = {
    "gbdt": SUPPORTED,
    "dart": SUPPORTED,
    "rf": SUPPORTED,
    "streaming": DEMOTE,
}


def forced_engine(params: Dict[str, Any]) -> str:
    """The engine a raw params dict FORCES, before any dataset-size
    auto-routing: ``tpu_streaming=true`` pins streaming, ``boosting``
    pins dart/rf, everything else resolves at create_boosting time
    (returned as "gbdt", the resident default)."""
    from .config import coerce_tristate, get_param
    if coerce_tristate(get_param(params, "tpu_streaming"),
                       "tpu_streaming") == "true":
        return "streaming"
    b = str(get_param(params, "boosting")).lower()
    if b == "dart":
        return "dart"
    if b in ("rf", "random_forest"):
        return "rf"
    return "gbdt"


def device_ingest_verdict(params: Dict[str, Any]) -> str:
    """Can the engine these params force adopt device-resident ingest
    output?  DEMOTE means: bin host-side (warn if the user forced
    ``tpu_ingest_device=true``)."""
    return DEVICE_INGEST.get(forced_engine(params), SUPPORTED)


# which engines' PREDICT surface can shard the stacked tree axis over
# the local mesh (tpu_serve_shard_trees; serve/shard.py +
# ops/predict.py forest_predict_sharded): DART rescales per-tree leaf
# values in place every iteration (shrink), so its stacks churn
# versions and drop subsets are non-contiguous — demote to the
# unsharded path; the streaming engine predicts through the host model
# and has no stacked device surface at all. Demotion means: serve
# unsharded (single-device stacks), never refuse the predict.
SHARDED_PREDICT: Dict[str, str] = {
    "gbdt": SUPPORTED,
    "rf": SUPPORTED,
    "dart": DEMOTE,
    "streaming": DEMOTE,
}


def sharded_predict_verdict(engine: str, config=None) -> str:
    """Verdict for sharding one engine's stacked predict over the tree
    axis. ``linear_tree`` configs demote on EVERY engine — linear-leaf
    predicts ride the host-model path (raw feature values), which the
    device traversal never sees."""
    if config is not None and bool(getattr(config, "linear_tree",
                                           False)):
        return DEMOTE
    return SHARDED_PREDICT.get(engine, DEMOTE)


# which engines' pred_contrib (TreeSHAP) can take the ENGINE path —
# device-resident cached path tables, bucketed zero-compile dispatch,
# and (mesh permitting) the tree-sharded scan (gbdt.predict_contrib /
# ops/shap.py sharded_scan_kernel). DART's in-place leaf rescales churn
# the cached tables' version every iteration; RF's per-tree averaging
# is host-verified only against forest_shap_batch; the streaming
# engine has no stacked device surface. Demotion means: explain through
# the cached host model (identical values), never refuse the call.
SHARDED_SHAP: Dict[str, str] = {
    "gbdt": SUPPORTED,
    "dart": DEMOTE,
    "rf": DEMOTE,
    "streaming": DEMOTE,
}

# exact warned-stand-down wording (basic.py logs the matching line
# once per booster when a pred_contrib call demotes to the host path)
SHARDED_SHAP_MESSAGES: Dict[str, str] = {
    "dart": ("device SHAP demoted for the DART engine (capabilities."
             "SHARDED_SHAP): in-place leaf rescales churn the cached "
             "path tables every iteration; explaining through the "
             "host model"),
    "rf": ("device SHAP demoted for the random-forest engine "
           "(capabilities.SHARDED_SHAP); explaining through the host "
           "model"),
    "streaming": ("device SHAP demoted for the streaming engine "
                  "(capabilities.SHARDED_SHAP): it predicts through "
                  "the host model and has no stacked device surface"),
    "linear_tree": ("device SHAP demoted for linear_tree models "
                    "(capabilities.SHARDED_SHAP): linear-leaf "
                    "contributions ride the host-model path"),
}


def sharded_shap_verdict(engine: str, config=None) -> str:
    """Verdict for routing one engine's ``pred_contrib`` through the
    device-native SHAP path. ``linear_tree`` configs demote on EVERY
    engine, mirroring :func:`sharded_predict_verdict` (the host SHAP
    path refuses linear trees loudly; the engine path never sees
    them)."""
    if config is not None and bool(getattr(config, "linear_tree",
                                           False)):
        return DEMOTE
    return SHARDED_SHAP.get(engine, DEMOTE)


# can streamed per-(rank, block) score slots be RE-CUT onto a changed
# shard/block topology on resume (boosting/streaming.py
# import_train_state)?  The slots themselves are a deterministic
# function of trees × global rows — reshardable (or recomputable from
# the pickled trees) exactly, for any numerics. What the verdict
# guards is the CONTINUED training: bit-equality vs an uninterrupted
# run at the original cut holds only where per-level histogram
# accumulation is cut-invariant — integer quantized level sums.
# Exact-f32 accumulation reassociates when the block/shard cut moves
# (documented-close, not bit-equal), so that cell is FATAL unless the
# user opts into the divergence via ``tpu_elastic_recut=true``
# (docs/robustness.md "Elastic topology").
STREAM_RECUT: Dict[str, str] = {
    "quantized": SUPPORTED,
    "exact_f32": FATAL,       # tpu_elastic_recut=true demotes to a
    #                           recompute-with-divergence-warning
}


def stream_recut_verdict(config) -> Tuple[str, str]:
    """(verdict, why) for re-cutting streamed score state onto a
    layout different from the one the checkpoint was written under.
    SUPPORTED = re-cut, bit-exact continuation; DEMOTE = re-cut with a
    documented-divergence warning (the ``tpu_elastic_recut=true``
    override); FATAL = refuse, ``why`` names the blocking feature, the
    table cell, and the knob."""
    knob = str(getattr(config, "tpu_elastic_recut", "auto"))
    if knob == "false":
        return FATAL, (
            "tpu_elastic_recut=false pins the strict PR-13 contract: "
            "any shard/block layout change on streamed resume is a "
            "hard error — resume under the original layout, or drop "
            "the pin")
    cell = "quantized" if bool(config.use_quantized_grad) \
        else "exact_f32"
    if STREAM_RECUT[cell] == SUPPORTED:
        return SUPPORTED, (
            "integer quantized level histograms are shard/block-cut-"
            "invariant, so the re-cut continuation is bit-exact")
    if knob == "true":
        return DEMOTE, (
            "tpu_elastic_recut=true forces the re-cut: exact-f32 "
            "histogram sums reassociate under the new cut, so the "
            "continued trees are documented-close to — not bit-equal "
            "with — an uninterrupted run at the original layout")
    return FATAL, (
        "exact-f32 streamed score accumulation (use_quantized_grad "
        "off) is the blocking feature: per-level histogram sums "
        "reassociate under a changed shard/block cut, so the resumed "
        "run would be documented-close rather than bit-equal "
        "(capability cell capabilities.STREAM_RECUT['exact_f32']). "
        "Either train with use_quantized_grad=true (cut-invariant "
        "integer sums — bit-exact elastic resume), force the re-cut "
        "with tpu_elastic_recut=true (recompute with a divergence "
        "warning), or resume under the original layout")


class _RecutParamsView:
    """Minimal Config-shaped view over a raw params dict for
    :func:`stream_recut_verdict` — the launcher's degrade path must
    predict the verdict BEFORE deciding to resume a narrower gang
    (a full Config build has process-wide side effects there)."""

    def __init__(self, params: Dict[str, Any]):
        from .config import coerce_tristate, get_param
        self.tpu_elastic_recut = coerce_tristate(
            get_param(params, "tpu_elastic_recut"),
            "tpu_elastic_recut")
        self.use_quantized_grad = bool(
            get_param(params, "use_quantized_grad"))


def stream_recut_verdict_params(params: Dict[str, Any]
                                ) -> Tuple[str, str]:
    """:func:`stream_recut_verdict` over a raw params dict (alias- and
    type-resolved through ``config.get_param``)."""
    return stream_recut_verdict(_RecutParamsView(params))
