"""Subpackage: io."""
