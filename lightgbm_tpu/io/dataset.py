"""Binned dataset + metadata.

Reference: src/io/dataset.cpp, src/io/metadata.cpp,
include/LightGBM/dataset.h (UNVERIFIED — empty mount, see SURVEY.md banner).

TPU-first representational choice (SURVEY.md §7.1): instead of the
reference's per-feature-group ``Bin`` objects (dense/sparse/multi-val
hierarchies tuned for CPU caches), the binned matrix is ONE packed integer
array ``[n_rows, n_used_features]`` (uint8 when every feature has <=256
bins) destined for HBM, row-sharded over the mesh. EFB still happens at bin
time (bundled features share a column with bin offsets) — see bundling.py.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..utils import log
from .binning import (BIN_TYPE_CATEGORICAL, BinMapper, find_bin_mappers,
                      load_forced_bins, resolve_ingest_threads)


def _host_mem_bytes():
    """Total physical host RAM, or None when undeterminable."""
    try:
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        return None


def _is_pandas_df(data) -> bool:
    return (hasattr(data, "dtypes") and hasattr(data, "columns")
            and hasattr(data, "values"))


def _pandas_cat_columns(df) -> list:
    return [c for c, dt in zip(df.columns, df.dtypes)
            if str(dt) == "category"]


def extract_pandas_categorical(df):
    """Per category-dtype column (in column order), the category-value
    list — the mapping stock LightGBM records as ``pandas_categorical``
    in the model file (basic.py _data_from_pandas, UNVERIFIED — empty
    mount). None when the frame has no category columns. Category
    values must be JSON-serializable (they go into the model text
    verbatim) — rejected HERE with a clear error rather than as a
    TypeError at save time."""
    cols = _pandas_cat_columns(df)
    if not cols:
        return None
    import json
    out = []
    for c in cols:
        cats = list(df[c].cat.categories.tolist())
        try:
            json.dumps(cats)
        except TypeError:
            log.fatal(
                f"Categories of column '{c}' are not "
                f"JSON-serializable (e.g. pd.cut Intervals or "
                f"Timestamps) and cannot be stored in the model file — "
                f"convert them to str or int first "
                f"(e.g. df['{c}'] = df['{c}'].astype(str)"
                f".astype('category'))")
        out.append(cats)
    return out


def apply_pandas_categorical(data, pandas_categorical):
    """Replace a DataFrame's category-dtype columns with their integer
    CODES under ``pandas_categorical``'s category lists (float64; NaN
    for missing AND for values outside the recorded lists). Train time
    passes the frame's own lists; predict time passes the lists stored
    in the model, so a frame whose categories arrive in a different
    order — or with new values — still maps code-compatibly with
    training. Non-DataFrame inputs pass through untouched."""
    if not _is_pandas_df(data):
        return data
    cols = _pandas_cat_columns(data)
    if not cols:
        return data
    if pandas_categorical is None or \
            len(pandas_categorical) != len(cols):
        log.fatal(
            f"Input DataFrame has {len(cols)} category-dtype columns "
            f"but the model/dataset records "
            f"{0 if pandas_categorical is None else len(pandas_categorical)} "
            f"— train and predict frames must have matching categorical "
            f"columns (pandas_categorical)")
    data = data.copy(deep=False)
    for c, cats in zip(cols, pandas_categorical):
        # vectorized value->code: set_categories drops values outside
        # ``cats`` to NaN (code -1), exactly the unseen-category
        # semantics of the bitset miss; at train time cats == the
        # column's own list so this is the plain .cat.codes
        codes = data[c].cat.set_categories(cats).cat.codes.to_numpy()
        vals = codes.astype(np.float64)
        vals[codes < 0] = np.nan
        data[c] = vals
    return data


def _coerce_1d(a) -> np.ndarray:
    """1-D float64 coercion accepting numpy / lists / pandas Series /
    pyarrow Array-ChunkedArray (np.asarray would wrap arrow objects as
    dtype=object)."""
    if hasattr(a, "to_numpy") and \
            (type(a).__module__ or "").startswith("pyarrow"):
        a = a.to_numpy(zero_copy_only=False)
    return np.asarray(a, dtype=np.float64)


@dataclasses.dataclass
class Metadata:
    """Per-row training metadata (reference: Metadata, metadata.cpp)."""

    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    # query boundaries: int array of size num_queries+1 (cumulative), like
    # the reference's query_boundaries_ built from per-query counts
    query_boundaries: Optional[np.ndarray] = None
    init_score: Optional[np.ndarray] = None
    # per-row presentation positions (Metadata::positions, v4.2+):
    # consumed by lambdarank_unbiased instead of the score rank
    position: Optional[np.ndarray] = None

    def set_group(self, group: Optional[np.ndarray]) -> None:
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).ravel()
        self.query_boundaries = np.concatenate([[0], np.cumsum(group)])

    def num_queries(self) -> int:
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1


class Dataset:
    """User-facing Dataset mirroring ``lightgbm.Dataset`` semantics.

    Lazy construction: raw data is kept until ``construct()`` is called
    (by ``train()``/``Booster``), at which point binning runs — matching
    basic.py's ``Dataset._lazy_init``. A validation dataset created via
    ``create_valid``/``reference=`` reuses the training set's BinMappers,
    exactly as the reference requires aligned bin boundaries.
    """

    def __init__(self, data, label=None, reference: "Dataset" = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.params = dict(params or {})
        self.reference = reference
        self.free_raw_data = free_raw_data
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.metadata = Metadata()
        if label is not None:
            self.metadata.label = _coerce_1d(label).ravel()
        if weight is not None:
            self.metadata.weight = _coerce_1d(weight).ravel()
        if group is not None:
            self.metadata.set_group(_coerce_1d(group))
        if init_score is not None:
            self.metadata.init_score = _coerce_1d(init_score)
        # filled by construct()
        self._constructed = False
        self.bin_mappers: List[BinMapper] = []
        self._ingest = None          # device-resident ingest result
        self.binned: Optional[np.ndarray] = None   # [n_rows, n_used]
        self.used_features: List[int] = []         # original feature indices
        self.num_total_features = 0
        self.num_data = 0
        self._raw_for_linear: Optional[np.ndarray] = None
        # category-value lists of pandas category-dtype columns
        # (stock lightgbm's pandas_categorical); filled at construct
        self.pandas_categorical = None
        import os as _os
        if isinstance(data, (str, _os.PathLike)):
            self._init_from_file(_os.fspath(data))

    # ------------------------------------------------------------------
    @property
    def binned(self) -> Optional[np.ndarray]:
        """Host ``[n, n_used]`` binned matrix. Under device ingest
        (``tpu_ingest_device``) the matrix lives on the accelerator and
        the host copy materializes LAZILY here, only for the paths that
        genuinely need host bytes (save_binary / EFB bundling / subset /
        model-text round trips) — training reads the device arrays
        directly via ``device_ingested()``."""
        b = getattr(self, "_binned", None)
        if b is None:
            ing = getattr(self, "_ingest", None)
            if ing is not None:
                b = ing.host_binned()
                self._binned = b
        return b

    @binned.setter
    def binned(self, value) -> None:
        self._binned = value

    def device_ingested(self):
        """The on-device ingest result (ops/ingest.DeviceIngestResult)
        or None when this dataset was binned host-side."""
        return getattr(self, "_ingest", None)

    def binned_dtype(self):
        """Bin-id dtype WITHOUT forcing a host materialization of a
        device-resident binned matrix (predict needs only the dtype)."""
        b = getattr(self, "_binned", None)
        if b is not None:
            return b.dtype
        ing = getattr(self, "_ingest", None)
        if ing is not None:
            return np.dtype(ing.bins.dtype)
        return self.binned.dtype

    # ------------------------------------------------------------------
    @staticmethod
    def _to_matrix(data) -> np.ndarray:
        """Accept numpy / pandas / pyarrow / list-of-lists / scipy-sparse.

        Reference: LGBM_DatasetCreateFromMat/CSR/CSC/Arrow (c_api.cpp,
        UNVERIFIED — empty mount); the arrow path mirrors basic.py's
        pyarrow Table handling."""
        if hasattr(data, "toarray"):          # scipy sparse
            dense_bytes = int(data.shape[0]) * int(data.shape[1]) * 8
            budget = _host_mem_bytes()
            note = ("Training, valid-set construction and predict all "
                    "bin sparse input column-wise without densifying — "
                    "pass the sparse matrix to those APIs directly, or "
                    "chunk rows for paths that need raw values")
            if budget is not None and dense_bytes > 0.9 * budget:
                log.fatal(
                    f"densifying sparse input of shape {data.shape} "
                    f"would need {dense_bytes / 2**30:.1f} GiB — more "
                    f"than 90% of host RAM. {note}")
            elif budget is not None and dense_bytes > 0.25 * budget:
                log.warning(
                    f"densifying sparse input of shape {data.shape} "
                    f"({dense_bytes / 2**30:.1f} GiB, > 25% of host "
                    f"RAM). {note}")
            return np.asarray(data.toarray(), dtype=np.float64)
        if (type(data).__module__ or "").startswith("pyarrow") \
                and hasattr(data, "column_names"):   # pyarrow.Table
            cols = [np.asarray(data.column(i).to_numpy(
                zero_copy_only=False), dtype=np.float64)
                for i in range(data.num_columns)]
            return np.stack(cols, axis=1) if cols else \
                np.zeros((0, 0), np.float64)
        if hasattr(data, "values") and hasattr(data, "columns"):  # pandas
            return np.asarray(data.values, dtype=np.float64)
        arr = np.asarray(data, dtype=np.float64)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        return arr

    def _resolve_feature_names(self, n_features: int) -> List[str]:
        if isinstance(self.feature_name, list):
            return list(self.feature_name)
        if hasattr(self.data, "column_names"):    # pyarrow (checked
            # first: arrow Tables also expose a `.columns` of arrays)
            return [str(c) for c in self.data.column_names]
        if hasattr(self.data, "columns"):     # pandas
            return [str(c) for c in self.data.columns]
        return [f"Column_{i}" for i in range(n_features)]

    def _resolve_categorical(self, names: List[str]) -> List[int]:
        cf = self.categorical_feature
        if cf == "auto" or cf is None:
            # pandas category dtype auto-detection
            if hasattr(self.data, "dtypes"):
                return [i for i, dt in enumerate(self.data.dtypes)
                        if str(dt) == "category"]
            return []
        out = []
        for c in cf:
            if isinstance(c, str):
                if c in names:
                    out.append(names.index(c))
                else:
                    log.warning(f"categorical_feature {c} not in data")
            else:
                out.append(int(c))
        return out

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._constructed:
            return self
        from .. import obs
        with obs.span("dataset/construct"):
            return self._construct_impl()

    def _construct_impl(self) -> "Dataset":
        # warm-start: point jax's persistent compile cache BEFORE the
        # first construct-time kernel (the ingest assignment jit)
        from ..config import get_param, setup_compile_cache
        setup_compile_cache(get_param(self.params,
                                      "tpu_compile_cache_dir"))
        if getattr(self, "_stream_path", None):
            return self._construct_streamed()
        if self._finish_pushed():
            return self
        # scipy sparse binning never densifies the raw matrix (8 bytes x
        # n x F would dwarf the uint8 binned output at Criteo-class
        # sparsity); one float64 column is materialized at a time from
        # CSC (LGBM_DatasetCreateFromCSC, c_api.cpp — UNVERIFIED)
        is_sparse = (hasattr(self.data, "tocsc")
                     and hasattr(self.data, "nnz")
                     and not isinstance(self.data, np.ndarray))
        if is_sparse:
            Xc = self.data.tocsc()
            X = Xc          # find_bin_mappers handles sparse natively
            self.num_data, self.num_total_features = Xc.shape
        else:
            data = self.data
            if _is_pandas_df(data) and _pandas_cat_columns(data):
                # valid sets inherit the TRAINING frame's category
                # lists so codes agree across datasets
                self.pandas_categorical = (
                    self.reference.construct().pandas_categorical
                    if self.reference is not None
                    else extract_pandas_categorical(data))
                data = apply_pandas_categorical(
                    data, self.pandas_categorical)
            from ..config import coerce_bool as _cb2
            if (isinstance(data, np.ndarray) and data.ndim == 2
                    and data.dtype in (np.float32, np.float64)
                    and not _cb2(self.params.get("linear_tree", False))):
                # fast path: bin columns of the caller's matrix
                # directly (the native binner takes f32 and strided
                # views) instead of materializing a float64 copy —
                # at 10M x 28 that copy alone is ~2.2 GB. Bin mappers
                # still see float64 (from_sample converts its sample).
                # linear_tree keeps the f64 path: leaf ridge fits read
                # _raw_for_linear and must match predict-time f64.
                X = data
            else:
                X = self._to_matrix(data)
            self.num_data, self.num_total_features = X.shape
        self._validate_metadata()
        names = self._resolve_feature_names(self.num_total_features)
        self.feature_names = names
        cat_idx = self._resolve_categorical(names)
        self.categorical_idx = cat_idx

        if self.reference is not None:
            ref = self.reference.construct()
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.feature_names = ref.feature_names
            self.categorical_idx = ref.categorical_idx
        elif self.bin_mappers:
            # pre-injected mappers (the distributed bin-boundary sync:
            # parallel/launch.py builds identical mappers on every
            # process from an all-gathered sample, the TPU-native
            # analog of DatasetLoader's distributed bin sync —
            # dataset_loader.cpp, UNVERIFIED)
            if len(self.bin_mappers) != self.num_total_features:
                log.fatal(
                    f"preset bin_mappers cover {len(self.bin_mappers)} "
                    f"features but the data has "
                    f"{self.num_total_features}")
            self.used_features = [i for i, m
                                  in enumerate(self.bin_mappers)
                                  if not m.is_trivial]
        else:
            from .binning import mappers_from_params
            self.bin_mappers = mappers_from_params(
                X, self.params, categorical_idx=cat_idx)
            self.used_features = [i for i, m in enumerate(self.bin_mappers)
                                  if not m.is_trivial]
            if len(self.used_features) < self.num_total_features:
                n_drop = self.num_total_features - len(self.used_features)
                log.info(f"Dropped {n_drop} constant feature(s)")
            if not self.used_features:
                log.warning("There are no meaningful features which satisfy "
                            "the provided configuration.")

        dtype = self._binned_dtype_with_guard()
        if self._want_device_ingest(X, is_sparse, dtype):
            from ..ops.ingest import device_ingest
            self._ingest = device_ingest(
                X, self.bin_mappers, self.used_features, dtype,
                chunk_rows=get_param(self.params,
                                     "tpu_ingest_chunk_rows"),
                emit_transposed=self._want_transposed_ingest(dtype))
            self.binned = None    # host copy materializes lazily
        else:
            self.binned = self._bin_all_columns(X, is_sparse, dtype)
        from ..config import coerce_bool as _cb
        if _cb(self.params.get("linear_tree", False)):
            if is_sparse:
                log.fatal("linear_tree requires dense input data (leaf "
                          "ridge fits read raw feature values)")
            self._raw_for_linear = X[:, self.used_features].copy()
        self._constructed = True
        if self.free_raw_data:
            self.data = None
        return self

    def _want_device_ingest(self, X, is_sparse: bool, dtype) -> bool:
        """Route bin ASSIGNMENT to the accelerator (ops/ingest.py)?
        "true" forces; "auto" engages on a TPU backend for dense
        numeric ndarray input big enough to amortize the dispatch —
        but stands down when the binned matrix would not comfortably
        fit in HBM (the >HBM case belongs to the streaming engine's
        host-resident bins); "false" (or sparse / non-numeric / no
        usable features) keeps the host loop. Even forced "true"
        yields to a forced streaming engine (its host-block scan never
        adopts device bins — they would sit orphaned in HBM) and to
        categorical ids outside the exact float32/int32 window (the
        f32 chunk stream cannot represent them; the host int64 path
        can)."""
        from .. import capabilities
        from ..config import coerce_tristate, get_param
        mode = coerce_tristate(
            get_param(self.params, "tpu_ingest_device"),
            "tpu_ingest_device")
        if mode == "false":
            return False
        if (is_sparse or not isinstance(X, np.ndarray) or X.ndim != 2
                or X.dtype not in (np.float32, np.float64)
                or not self.used_features):
            return False
        forced = mode == "true"
        if capabilities.device_ingest_verdict(self.params) \
                != capabilities.SUPPORTED:
            # the engine these params force (the streaming engine's
            # host-block scan) never adopts device-resident bins — they
            # would sit orphaned in HBM; the capability table owns the
            # per-engine adoption verdicts (capabilities.DEVICE_INGEST)
            if forced:
                log.warning("tpu_ingest_device=true ignored: "
                            "tpu_streaming=true keeps bins "
                            "host-resident")
            return False
        from ..ops.ingest import cat_device_safe
        if not cat_device_safe(self.bin_mappers, self.used_features):
            if forced:
                log.warning("tpu_ingest_device=true ignored: "
                            "categorical ids exceed the exact "
                            "float32/int32 device window; binning "
                            "host-side")
            return False
        from ..utils.hbm import (STREAM_HBM_FRACTION, binned_device_bytes,
                                 hbm_bytes_limit)
        limit = hbm_bytes_limit()
        if limit:
            est = binned_device_bytes(
                self.num_data, len(self.used_features),
                np.dtype(dtype).itemsize,
                self._want_transposed_ingest(dtype))
            # budget 2x the resident size: the chunk parts AND the
            # final concatenated arrays are alive together at the end
            # of device_ingest, so transient peak is ~double. Even a
            # FORCED device ingest stands down here — past this size
            # auto-streaming (boosting._should_stream, same helper)
            # picks the host-block engine, which never adopts device
            # bins: they would sit orphaned in HBM
            if 2 * est > STREAM_HBM_FRACTION * limit:
                if forced:
                    log.warning("tpu_ingest_device=true ignored: binned "
                                "matrix too large to sit comfortably in "
                                "HBM (streaming territory); binning "
                                "host-side")
                return False
        # a distributed learner on >1 device will SHARD host numpy in
        # _DeviceData — device-resident single-device bins would just be
        # materialized back to host and re-uploaded sharded (strictly
        # slower than host binning), so even forced mode stands down
        import jax
        if jax.device_count() > 1:
            tl = str(get_param(self.params, "tree_learner")).lower()
            if tl != "serial":
                if forced:
                    log.warning("tpu_ingest_device=true ignored: a "
                                "distributed tree_learner shards "
                                "host-binned data; binning host-side")
                return False
        if forced:
            return True
        if jax.default_backend() != "tpu" or self.num_data < 65_536:
            return False
        return True

    def _want_transposed_ingest(self, dtype) -> bool:
        """Emit the feature-major int8 ``bins_t`` tile during ingest?
        Mirrors the engine's Pallas-kernel gate (uint8 bins + TPU +
        tpu_use_pallas) so the host transpose in ``_DeviceData`` never
        runs — the fused kernel writes both layouts per chunk."""
        from ..config import get_param
        if np.dtype(dtype) != np.uint8:
            return False
        if not get_param(self.params, "tpu_use_pallas"):
            return False
        if get_param(self.params, "tpu_double_precision_hist"):
            return False
        import jax
        return jax.default_backend() == "tpu"

    def _bin_all_columns(self, X, is_sparse: bool, dtype,
                         n_rows: int = None) -> np.ndarray:
        """Pack the binned matrix [n, n_used]. Dense row-major input
        takes ONE native row-major pass over all numeric columns
        (native/binning.cpp bin_matrix — column-at-a-time binning
        cache-misses every strided read); categorical columns and the
        fallbacks go per-column."""
        used = self.used_features
        if n_rows is None:
            n_rows = self.num_data
        if not used:
            return np.zeros((n_rows, 0), dtype=dtype)
        from ..config import get_param
        from .binning import _native
        lib = _native()
        dense_fast = (lib is not None and not is_sparse
                      and isinstance(X, np.ndarray) and X.ndim == 2
                      and X.dtype in (np.float32, np.float64)
                      and X.flags.c_contiguous
                      and n_rows > 65536)
        if dense_fast:
            import ctypes
            n_cols = len(used)
            is_num = np.array(
                [self.bin_mappers[f].bin_type != BIN_TYPE_CATEGORICAL
                 for f in used], dtype=np.int32)
            ubs = [np.ascontiguousarray(
                       self.bin_mappers[f].bin_upper_bound
                       if is_num[j] else np.zeros(1), dtype=np.float64)
                   for j, f in enumerate(used)]
            ub_off = np.zeros(n_cols + 1, dtype=np.int64)
            np.cumsum([len(u) for u in ubs], out=ub_off[1:])
            ub_concat = np.concatenate(ubs)
            mt_code = {"none": 0, "zero": 1, "nan": 2}
            meta_mt = np.array(
                [mt_code.get(self.bin_mappers[f].missing_type, 0)
                 for f in used], dtype=np.int32)
            meta_db = np.array(
                [self.bin_mappers[f].default_bin for f in used],
                dtype=np.int64)
            meta_nb = np.array(
                [self.bin_mappers[f].num_bin for f in used],
                dtype=np.int64)
            col_idx = np.array(used, dtype=np.int64)
            out = np.empty((n_rows, n_cols), dtype=dtype)
            out_kind = {np.uint8: 0, np.uint16: 1,
                        np.int32: 2}[np.dtype(dtype).type]
            c = ctypes
            row_stride = X.strides[0] // X.itemsize

            def bin_rows(s: int, e: int) -> None:
                lib.bin_matrix(
                    c.c_void_p(X.ctypes.data
                               + s * row_stride * X.itemsize),
                    int(X.dtype == np.float32), e - s, row_stride,
                    col_idx.ctypes.data_as(c.POINTER(c.c_int64)),
                    n_cols,
                    ub_concat.ctypes.data_as(c.POINTER(c.c_double)),
                    ub_off.ctypes.data_as(c.POINTER(c.c_int64)),
                    meta_mt.ctypes.data_as(c.POINTER(c.c_int32)),
                    meta_db.ctypes.data_as(c.POINTER(c.c_int64)),
                    meta_nb.ctypes.data_as(c.POINTER(c.c_int64)),
                    is_num.ctypes.data_as(c.POINTER(c.c_int32)),
                    c.c_void_p(out.ctypes.data
                               + s * n_cols * out.itemsize), out_kind)

            # row-chunked thread parallelism over the native pass:
            # ctypes releases the GIL for the call's duration and each
            # chunk writes a disjoint out slice, so the kernel scales
            # with cores (it is per-value binary search — pure CPU)
            n_threads = min(
                resolve_ingest_threads(
                    get_param(self.params, "tpu_ingest_threads")),
                max(n_rows // 262_144, 1))
            if n_threads > 1:
                from concurrent.futures import ThreadPoolExecutor
                blk = -(-n_rows // n_threads)
                spans = [(s, min(s + blk, n_rows))
                         for s in range(0, n_rows, blk)]
                with ThreadPoolExecutor(max_workers=n_threads) as ex:
                    list(ex.map(lambda se: bin_rows(*se), spans))
            else:
                bin_rows(0, n_rows)
            for j, f in enumerate(used):     # categorical remainder
                if not is_num[j]:
                    out[:, j] = self.bin_mappers[f].values_to_bins(
                        X[:, f]).astype(dtype)
            return out

        def col_values(f):
            if is_sparse:
                # X is the CSC matrix here (construct passes it through)
                colv = np.zeros(n_rows, np.float64)
                sl = slice(X.indptr[f], X.indptr[f + 1])
                colv[X.indices[sl]] = X.data[sl]
                return colv
            return X[:, f]

        # per-column fallback: thread-pooled for non-accelerator users
        # (numpy's searchsorted/unique release the GIL, so columns bin
        # in parallel); small jobs keep the serial loop — pool startup
        # would dominate
        n_threads = min(
            resolve_ingest_threads(
                get_param(self.params, "tpu_ingest_threads")),
            len(used))
        if n_threads > 1 and n_rows * len(used) >= 2_000_000:
            from concurrent.futures import ThreadPoolExecutor
            out = np.empty((n_rows, len(used)), dtype=dtype)

            def bin_one(jf):
                j, f = jf
                out[:, j] = self.bin_mappers[f].values_to_bins(
                    col_values(f))

            with ThreadPoolExecutor(max_workers=n_threads) as ex:
                list(ex.map(bin_one, enumerate(used)))
            return out
        return np.stack(
            [self.bin_mappers[f].values_to_bins(col_values(f))
             .astype(dtype) for f in used], axis=1)

    # ------------------------------------------------------------------
    def _binned_dtype_with_guard(self):
        """Bin-id dtype for the packed matrix + the host-RAM capacity
        guard: fail with a clear message BEFORE allocating a binned
        matrix that cannot fit (file input can stream out-of-core via
        two_round=true, but the BINNED matrix itself must fit)."""
        max_num_bin = max((self.bin_mappers[f].num_bin
                           for f in self.used_features), default=2)
        dtype = np.uint8 if max_num_bin <= 256 else np.uint16
        est = (int(self.num_data) * max(len(self.used_features), 1)
               * np.dtype(dtype).itemsize)
        budget = _host_mem_bytes()
        if budget is not None and est > 0.9 * budget:
            log.fatal(
                f"binned dataset ({self.num_data} rows x "
                f"{len(self.used_features)} features) would need "
                f"{est / 2**30:.1f} GiB — more than 90% of host RAM "
                f"({budget / 2**30:.1f} GiB). Reduce rows/features, "
                f"lower max_bin to fit uint8, or shard rows across "
                f"hosts (parallel/multihost.py)")
        return dtype

    def _construct_streamed(self) -> "Dataset":
        """Two-round out-of-core load (dataset_loader.cpp two-round path
        + utils/pipeline_reader.h, UNVERIFIED — empty mount): round 1
        streams the file to draw a uniform row sample (bottom-k keys =
        sampling without replacement) and collect the small metadata
        columns; round 2 streams again, binning each chunk directly into
        the preallocated packed matrix. Peak memory is the BINNED matrix
        (1-2 bytes/cell) + one raw chunk — never the n x F float64 raw
        matrix."""
        from ..config import coerce_bool, get_param
        from .text_loader import iter_text_chunks
        p = self.params
        sp = self._stream_cols
        if coerce_bool(p.get("linear_tree", False)):
            log.fatal("two_round streaming cannot keep the raw feature "
                      "matrix linear_tree needs; load in one round")
        chunk_rows = get_param(p, "tpu_stream_chunk_rows")
        cap = int(p.get("bin_construct_sample_cnt", 200000))
        rng = np.random.default_rng(int(p.get("data_random_seed", 1)))

        def chunks():
            return iter_text_chunks(
                self._stream_path, chunk_rows=chunk_rows,
                label_column=sp.get("label_column", "auto"),
                weight_column=sp.get("weight_column"),
                group_column=sp.get("group_column"),
                ignore_column=sp.get("ignore_column"),
                has_header=(coerce_bool(sp["header"]) if "header" in sp
                            else None))

        # ---- round 1: sample + metadata (a valid set built against a
        # reference skips the sample pool and adopts the reference's
        # mappers, mirroring the one-round path) -----------------------
        use_ref = self.reference is not None
        pool_X = pool_keys = None
        labels, weights, qids = [], [], []
        n_total = 0
        feat_names = None
        for ch in chunks():
            n_total += len(ch.X)
            feat_names = ch.feature_names or feat_names
            if ch.label is not None:
                labels.append(ch.label)
            if ch.weight is not None:
                weights.append(ch.weight)
            if ch.qid is not None:
                qids.append(ch.qid)
            n_feat_seen = ch.X.shape[1]
            if use_ref:
                continue
            keys = rng.random(len(ch.X))
            if pool_X is None:
                pool_X, pool_keys = ch.X, keys
            else:
                pool_X = np.concatenate([pool_X, ch.X])
                pool_keys = np.concatenate([pool_keys, keys])
            if len(pool_keys) > cap:
                top = np.argpartition(pool_keys, cap)[:cap]
                pool_X, pool_keys = pool_X[top], pool_keys[top]
        if n_total == 0:
            log.fatal(f"Data file {self._stream_path} is empty")
        self.num_data = n_total
        self.num_total_features = n_feat_seen
        if self.metadata.label is None and labels:
            self.metadata.label = np.concatenate(labels)
        if self.metadata.weight is None and weights:
            self.metadata.weight = np.concatenate(weights)
        if self.metadata.query_boundaries is None and qids:
            qid = np.concatenate(qids)
            change = np.flatnonzero(np.diff(qid) != 0) + 1
            self.metadata.set_group(np.diff(
                np.concatenate([[0], change, [len(qid)]])))
        # sidecar files, like the one-round loader (metadata.cpp:
        # <data>.weight / <data>.query)
        import os as _os
        if self.metadata.weight is None \
                and _os.path.exists(self._stream_path + ".weight"):
            self.metadata.weight = np.loadtxt(
                self._stream_path + ".weight", dtype=np.float64).ravel()
        if self.metadata.query_boundaries is None \
                and _os.path.exists(self._stream_path + ".query"):
            self.metadata.set_group(np.loadtxt(
                self._stream_path + ".query", dtype=np.int64).ravel())
        self._validate_metadata()
        if use_ref:
            ref = self.reference.construct()
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.feature_names = ref.feature_names
            self.categorical_idx = ref.categorical_idx
            if self.num_total_features != ref.num_total_features:
                log.fatal(f"streamed file has {self.num_total_features} "
                          f"features, reference has "
                          f"{ref.num_total_features}")
        else:
            self.feature_names = (feat_names if feat_names else
                                  [f"Column_{i}" for i in
                                   range(self.num_total_features)])
            cat_idx = self._resolve_categorical(self.feature_names)
            self.categorical_idx = cat_idx
            self.bin_mappers = find_bin_mappers(
                pool_X,
                max_bin=int(p.get("max_bin", 255)),
                min_data_in_bin=int(p.get("min_data_in_bin", 3)),
                sample_cnt=cap,
                use_missing=coerce_bool(p.get("use_missing", True)),
                zero_as_missing=coerce_bool(p.get("zero_as_missing",
                                                  False)),
                categorical_features=cat_idx,
                max_bin_by_feature=p.get("max_bin_by_feature"),
                seed=int(p.get("data_random_seed", 1)),
                n_threads=resolve_ingest_threads(
                    get_param(p, "tpu_ingest_threads")),
                forced_bins=(load_forced_bins(
                    str(p["forcedbins_filename"]))
                    if p.get("forcedbins_filename") else None))
            del pool_X, pool_keys
            self.used_features = [
                i for i, m in enumerate(self.bin_mappers)
                if not m.is_trivial]
            if not self.used_features:
                log.warning("There are no meaningful features which "
                            "satisfy the provided configuration.")

        # ---- round 2: bin chunk-by-chunk into the packed matrix ------
        # each chunk goes through _bin_all_columns — the SAME ingest
        # path push_rows and construct use (native one-pass row-major
        # binning, thread-pooled fallback) — instead of the per-column
        # strided loop; peak memory stays one raw chunk + the binned
        # matrix (pinned by the peak-RSS test in test_io_files.py)
        dtype = self._binned_dtype_with_guard()
        self.binned = np.empty((n_total, len(self.used_features)),
                               dtype=dtype)
        r0 = 0
        for ch in chunks():
            r1 = r0 + len(ch.X)
            self.binned[r0:r1] = self._bin_all_columns(
                np.ascontiguousarray(ch.X), False, dtype,
                n_rows=len(ch.X))
            r0 = r1
        if r0 != n_total:
            log.fatal(f"file changed between streaming rounds: "
                      f"{r0} rows vs {n_total}")
        self._constructed = True
        log.info(f"two_round: streamed {n_total} rows x "
                 f"{self.num_total_features} features into a "
                 f"{self.binned.nbytes / 2**20:.0f} MiB binned matrix")
        return self

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, params=params,
                       free_raw_data=self.free_raw_data)

    # ------------------------------------------------------------------
    def push_rows(self, chunk, label=None, weight=None) -> "Dataset":
        """Streaming row ingestion (LGBM_DatasetPushRows / the streaming
        C API seam, c_api.cpp — UNVERIFIED). Build with ``Dataset(None,
        reference=...)`` and push row chunks; with a reference whose bin
        mappers exist, each chunk is binned IMMEDIATELY and the raw
        floats are dropped (true streaming memory behavior). Without a
        reference, raw chunks accumulate until ``construct`` samples
        them for binning."""
        if self._constructed:
            log.fatal("push_rows after construct()")
        if self.data is not None:
            log.fatal("push_rows requires Dataset(None, ...)")
        chunk = self._to_matrix(chunk)
        if not hasattr(self, "_pushed"):
            self._pushed, self._pushed_meta = [], {"label": [],
                                                   "weight": []}
        if self.reference is not None:
            ref = self.reference.construct()
            if chunk.shape[1] != ref.num_total_features:
                log.fatal(f"pushed chunk has {chunk.shape[1]} features, "
                          f"reference has {ref.num_total_features}")
            dtype = ref.binned_dtype()
            if ref.used_features:
                # native one-pass binning (same hot path construct and
                # predict use) — the per-column Python fallback is
                # ~200x slower, which matters exactly here: push_rows
                # is the >HBM streaming ingest path
                self._pushed.append(
                    ref._bin_all_columns(chunk, False, dtype,
                                         n_rows=len(chunk)))
            else:
                self._pushed.append(
                    np.zeros((len(chunk), 0), dtype))
        else:
            self._pushed.append(chunk)
        if label is not None:
            self._pushed_meta["label"].append(_coerce_1d(label).ravel())
        if weight is not None:
            self._pushed_meta["weight"].append(_coerce_1d(weight).ravel())
        return self

    def _validate_metadata(self) -> None:
        """Length-check every metadata field against num_data (the
        reference validates all Metadata fields at construct;
        metadata.cpp — UNVERIFIED)."""
        n = self.num_data
        md = self.metadata
        for fname in ("label", "weight", "position"):
            v = getattr(md, fname)
            if v is not None and len(v) != n:
                log.fatal(f"Length of {fname} ({len(v)}) does not "
                          f"match number of data ({n})")
        if md.init_score is not None:
            m = len(np.asarray(md.init_score).ravel())
            # num_data, or num_data * num_class for multiclass
            if m != n and (n == 0 or m % n != 0):
                log.fatal(f"Length of init_score ({m}) does not match "
                          f"number of data ({n})")
        if md.query_boundaries is not None \
                and int(md.query_boundaries[-1]) != n:
            log.fatal(f"Sum of query counts "
                      f"({int(md.query_boundaries[-1])}) does not match "
                      f"number of data ({n})")

    def _finish_pushed(self) -> bool:
        """Finalize streamed rows at construct time; True if handled
        fully (reference path: chunks are already binned)."""
        if not getattr(self, "_pushed", None):
            return False
        if self._pushed_meta["label"]:
            self.metadata.label = np.concatenate(
                self._pushed_meta["label"])
        if self._pushed_meta["weight"]:
            self.metadata.weight = np.concatenate(
                self._pushed_meta["weight"])
        # free the metadata chunk lists in BOTH branches (at 1e9+
        # streamed rows the retained label chunks alone are ~10 GB)
        self._pushed_meta = {"label": [], "weight": []}
        if self.reference is not None:
            ref = self.reference.construct()
            self.binned = np.concatenate(self._pushed, axis=0)
            self.num_data = len(self.binned)
            self._validate_metadata()
            self.num_total_features = ref.num_total_features
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.feature_names = ref.feature_names
            self.categorical_idx = ref.categorical_idx
            self._pushed = []
            self._constructed = True
            return True
        # no reference: hand the stacked raw rows to the normal path
        self.data = np.concatenate(self._pushed, axis=0)
        self._pushed = []
        return False

    def set_label(self, label) -> "Dataset":
        self.metadata.label = _coerce_1d(label).ravel()
        return self

    def set_weight(self, weight) -> "Dataset":
        self.metadata.weight = (None if weight is None else
                                _coerce_1d(weight).ravel())
        return self

    def set_group(self, group) -> "Dataset":
        self.metadata.set_group(None if group is None
                                else _coerce_1d(group))
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.metadata.init_score = (None if init_score is None else
                                    _coerce_1d(init_score))
        return self

    def set_position(self, position) -> "Dataset":
        self.metadata.position = (None if position is None else
                                  _coerce_1d(position).astype(np.int32))
        return self

    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name == "label":
            return self.set_label(data)
        if field_name == "weight":
            return self.set_weight(data)
        if field_name == "group":
            return self.set_group(data)
        if field_name == "init_score":
            return self.set_init_score(data)
        if field_name == "position":
            return self.set_position(data)
        log.fatal(f"Unknown field name {field_name}")

    def get_field(self, field_name: str):
        if field_name == "label":
            return self.metadata.label
        if field_name == "weight":
            return self.metadata.weight
        if field_name == "group":
            return self.metadata.query_boundaries
        if field_name == "init_score":
            return self.metadata.init_score
        if field_name == "position":
            return self.metadata.position
        log.fatal(f"Unknown field name {field_name}")

    def get_label(self):
        return self.metadata.label

    def get_weight(self):
        return self.metadata.weight

    def get_group(self):
        qb = self.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def get_init_score(self):
        return self.metadata.init_score

    def _init_from_file(self, path: str) -> None:
        """Load from disk: the framework's binary dataset format
        (save_binary) or CSV/TSV/LibSVM text (DatasetLoader::LoadFromFile
        semantics — label/weight/group columns + sidecar files)."""
        import pickle
        with open(path, "rb") as f:
            magic = f.read(8)
        if magic == b"LGBTBIN1":
            with open(path, "rb") as f:
                f.read(8)
                state = pickle.load(f)
            user_md = self.metadata
            user_params = self.params
            for k, v in state.items():
                setattr(self, k, v)
            # user-passed metadata/params override the stored copies
            for field in ("label", "weight", "init_score",
                          "query_boundaries"):
                v = getattr(user_md, field)
                if v is not None:
                    setattr(self.metadata, field, v)
            self.params = {**self.params, **user_params}
            self._constructed = True
            self.data = None
            return
        from ..config import Config, coerce_bool
        from .text_loader import load_text
        # resolve reference aliases (label=, weight=, group=/query=,
        # has_header=, ignore_feature=...) to canonical names
        p = {Config.canonical_name(k): v for k, v in self.params.items()}
        if coerce_bool(p.get("two_round", False)):
            # out-of-core two-round load: defer to construct(), which
            # streams the file twice (sample pass + binning pass) and
            # never materializes the raw matrix
            self._stream_path = path
            self._stream_cols = p
            return
        loaded = load_text(
            path,
            label_column=p.get("label_column", "auto"),
            weight_column=p.get("weight_column"),
            group_column=p.get("group_column"),
            ignore_column=p.get("ignore_column"),
            has_header=(coerce_bool(p["header"]) if "header" in p
                        else None))
        self.data = loaded.X
        if self.metadata.label is None and loaded.label is not None:
            self.metadata.label = loaded.label.astype(np.float64)
        if self.metadata.weight is None and loaded.weight is not None:
            self.metadata.weight = loaded.weight.astype(np.float64)
        if self.metadata.query_boundaries is None \
                and loaded.group is not None:
            self.metadata.set_group(loaded.group)
        if self.feature_name == "auto" and loaded.feature_names:
            self.feature_name = loaded.feature_names

    def save_binary(self, path: str) -> "Dataset":
        """Serialize the CONSTRUCTED dataset (binned matrix + mappers +
        metadata) — the reference's binary dataset file
        (dataset.cpp SaveBinaryFile), loadable via Dataset(path)."""
        import pickle
        self.construct()
        state = {k: getattr(self, k) for k in (
            "binned", "bin_mappers", "used_features", "feature_names",
            "categorical_idx", "num_total_features", "num_data",
            "metadata", "params")}
        with open(path, "wb") as f:
            f.write(b"LGBTBIN1")
            pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
        return self

    def num_feature(self) -> int:
        self.construct()
        return len(self.used_features)

    def num_data_(self) -> int:
        self.construct()
        return self.num_data

    def __len__(self) -> int:
        if self._constructed:
            return self.num_data
        if self.data is None:             # push_rows-style streaming
            return sum(len(c) for c in getattr(self, "_pushed", []))
        if hasattr(self.data, "shape"):   # ndarray/scipy/pandas — no
            return int(self.data.shape[0])  # densifying coercion
        if hasattr(self.data, "num_rows"):  # pyarrow
            return int(self.data.num_rows)
        return len(self._to_matrix(self.data))

    # ------------------------------------------------------------------
    def subset(self, used_indices: Sequence[int],
               params: Optional[Dict[str, Any]] = None) -> "Dataset":
        """Row-subset sharing this dataset's bin mappers (for cv folds)."""
        self.construct()
        idx = np.asarray(used_indices, dtype=np.int64)
        sub = Dataset.__new__(Dataset)
        sub.data = None
        sub.params = dict(params or self.params)
        sub.reference = self
        sub.free_raw_data = self.free_raw_data
        sub.feature_name = self.feature_name
        sub.categorical_feature = self.categorical_feature
        sub.pandas_categorical = self.pandas_categorical
        sub.metadata = Metadata()
        md = self.metadata
        if md.label is not None:
            sub.metadata.label = md.label[idx]
        if md.weight is not None:
            sub.metadata.weight = md.weight[idx]
        if md.init_score is not None:
            sub.metadata.init_score = np.asarray(md.init_score)[idx]
        if md.position is not None:
            sub.metadata.position = md.position[idx]
        if md.query_boundaries is not None:
            # rebuild query boundaries from per-row query ids; assumes idx
            # keeps whole queries together (cv's group-aware folds do)
            qid = np.searchsorted(md.query_boundaries, idx,
                                  side="right") - 1
            change = np.flatnonzero(np.diff(qid)) + 1
            counts = np.diff(np.concatenate([[0], change, [len(idx)]]))
            sub.metadata.set_group(counts)
        sub._constructed = True
        sub.bin_mappers = self.bin_mappers
        sub.binned = self.binned[idx]
        sub.used_features = self.used_features
        sub.feature_names = self.feature_names
        sub.categorical_idx = self.categorical_idx
        sub.num_total_features = self.num_total_features
        sub.num_data = len(idx)
        sub._raw_for_linear = (None if self._raw_for_linear is None
                               else self._raw_for_linear[idx])
        return sub

    # ------------------------------------------------------------------
    def feature_num_bins(self) -> np.ndarray:
        """num_bin per used feature (padded arrays for the jit learner)."""
        self.construct()
        return np.array([self.bin_mappers[f].num_bin
                         for f in self.used_features], dtype=np.int32)
