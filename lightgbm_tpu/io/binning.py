"""Feature binning: quantile-ish greedy binning with zero/NaN handling.

Reference: src/io/bin.cpp ``BinMapper::FindBin`` / ``GreedyFindBin`` and
include/LightGBM/bin.h (UNVERIFIED — empty mount, see SURVEY.md banner).

Semantics reproduced:
- numerical features: bins chosen on a sample so that each bin holds roughly
  equal counts, honoring ``min_data_in_bin``; distinct-value-count aware
  (heavy values get their own bin); zero ([-1e-35, 1e-35]) forced into its
  own bin; bin boundaries are midpoints between adjacent distinct values.
- missing handling: ``missing_type`` in {none, zero, nan}. With
  ``use_missing`` and NaNs present, NaN occupies the LAST bin. With
  ``zero_as_missing``, zeros/NaN map to the zero bin.
- categorical features: categories sorted by count desc, capped at
  ``max_bin``-1 (rare tail pruned, mirroring the 99%% mass cut upstream);
  bin 0 is reserved for NaN/unseen categories.

The implementation is NumPy (host-side); binning is a one-time load cost,
the hot path is the binned matrix on device.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..utils import log


def _native():
    '''Native binning library, or None (pure-Python fallback). A
    function (not a cached global) so tests can monkeypatch it off.'''
    from ..native import binning
    return binning()


K_ZERO_THRESHOLD = 1e-35
BIN_TYPE_NUMERICAL = "numerical"
BIN_TYPE_CATEGORICAL = "categorical"
MISSING_NONE = "none"
MISSING_ZERO = "zero"
MISSING_NAN = "nan"


def _greedy_find_distinct_bounds(distinct_values: np.ndarray,
                                 counts: np.ndarray,
                                 max_bin: int,
                                 total_cnt: int,
                                 min_data_in_bin: int) -> List[float]:
    """Pick bin upper bounds over sorted distinct values.

    Returns a list of upper bounds; the last bound is +inf. Mirrors the
    greedy equal-mass packing of the reference's GreedyFindBin: values whose
    count exceeds the mean bin size get dedicated bins; the rest are packed
    to roughly ``mean_bin_size`` each.
    """
    n_distinct = len(distinct_values)
    lib = _native()
    if lib is not None and n_distinct > 4096:
        import ctypes
        dv = np.ascontiguousarray(distinct_values, dtype=np.float64)
        cn = np.ascontiguousarray(counts, dtype=np.int64)
        out = np.empty(int(max_bin) + 2, dtype=np.float64)
        n_out = lib.greedy_find_bounds(
            dv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            cn.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n_distinct, int(max_bin), int(total_cnt),
            int(min_data_in_bin),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return list(out[:n_out])
    bounds: List[float] = []
    if n_distinct == 0:
        return [np.inf]
    if n_distinct <= max_bin:
        # one bin per distinct value, merging up to min_data_in_bin
        cur_cnt = 0
        for i in range(n_distinct - 1):
            cur_cnt += counts[i]
            if cur_cnt >= min_data_in_bin:
                bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cur_cnt = 0
        bounds.append(np.inf)
        return bounds
    # more distinct values than bins: greedy packing
    if min_data_in_bin > 0:
        max_bin = min(max_bin, max(1, total_cnt // min_data_in_bin))
    mean_size = total_cnt / max_bin
    is_big = counts >= mean_size
    rest_cnt = int(total_cnt - counts[is_big].sum())
    rest_bins = int(max_bin - is_big.sum())
    mean_size = rest_cnt / rest_bins if rest_bins > 0 else np.inf

    upper_idx: List[int] = []  # index i means boundary between value i, i+1
    cur_cnt = 0
    for i in range(n_distinct - 1):
        if not is_big[i]:
            rest_cnt -= counts[i]
        cur_cnt += counts[i]
        # close the bin on: a heavy value, reaching mean size, or just before
        # a heavy value once half-full
        if is_big[i] or cur_cnt >= mean_size or \
                (is_big[i + 1] and cur_cnt >= max(1.0, mean_size * 0.5)):
            upper_idx.append(i)
            cur_cnt = 0
            if len(upper_idx) >= max_bin - 1:
                break
            if not is_big[i]:
                rest_bins -= 1
                if rest_bins > 0:
                    mean_size = rest_cnt / rest_bins
    for i in upper_idx:
        bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
    bounds.append(np.inf)
    return bounds


def resolve_ingest_threads(n_threads: int) -> int:
    """The ONE tpu_ingest_threads resolution rule (0/unset = one per
    core, capped) — shared by mapper finding, the native row-chunked
    binning pass and the per-column fallback so the knob can never mean
    different things on different paths. Callers apply their own
    work-size gates on top."""
    if n_threads and n_threads > 0:
        return int(n_threads)
    import os
    return min(os.cpu_count() or 1, 16)


def _distinct_with_counts(values: np.ndarray):
    if len(values) == 0:
        return np.empty(0), np.empty(0, dtype=np.int64)
    return np.unique(values, return_counts=True)


@dataclasses.dataclass
class BinMapper:
    """Per-feature value→bin mapping (reference: BinMapper, bin.h)."""

    bin_type: str = BIN_TYPE_NUMERICAL
    num_bin: int = 1
    missing_type: str = MISSING_NONE
    # numerical: sorted upper bounds, len == number of value bins
    bin_upper_bound: Optional[np.ndarray] = None
    # categorical: raw int category value per bin (index 0 unused / NaN-bin)
    bin_to_cat: Optional[np.ndarray] = None
    cat_to_bin: Optional[Dict[int, int]] = None
    default_bin: int = 0       # bin of value 0.0 (sparse default)
    most_freq_bin: int = 0
    min_value: float = 0.0
    max_value: float = 0.0

    @property
    def is_trivial(self) -> bool:
        """True when the feature has <=1 effective bin (constant feature)."""
        return self.num_bin <= 1

    # ------------------------------------------------------------------
    @staticmethod
    def from_sample(values: np.ndarray, total_sample_cnt: int, max_bin: int,
                    min_data_in_bin: int = 3, use_missing: bool = True,
                    zero_as_missing: bool = False,
                    is_categorical: bool = False,
                    min_data_in_cat: int = 1,
                    forced_bounds=None) -> "BinMapper":
        """Build a mapper from sampled raw values (NaN included).
        ``forced_bounds``: user-forced bin upper bounds
        (forcedbins_filename, DatasetLoader FindBinWithPredefinedBin —
        UNVERIFIED): the listed boundaries are guaranteed present; the
        remaining bin budget is filled by the usual greedy packing."""
        values = np.asarray(values, dtype=np.float64)
        if is_categorical:
            return BinMapper._categorical_from_sample(
                values, max_bin, use_missing)
        m = BinMapper._numerical_from_sample(
            values, total_sample_cnt, max_bin, min_data_in_bin, use_missing,
            zero_as_missing)
        if forced_bounds is not None and len(forced_bounds):
            forced = np.asarray(sorted(set(float(b)
                                           for b in forced_bounds)))
            ub = np.asarray(m.bin_upper_bound)
            cap = max_bin - (1 if m.missing_type == MISSING_NAN else 0)
            if len(forced) + 1 > cap:
                # +inf terminator always occupies one slot; forced
                # bounds beyond the budget are dropped (highest first)
                # so num_bin can never exceed max_bin
                log.warning(
                    f"forcedbins: {len(forced)} forced bounds exceed "
                    f"the max_bin={max_bin} budget; keeping the first "
                    f"{cap - 1}")
                forced = forced[:cap - 1]
            merged = np.array(sorted(set(ub) | set(forced)))
            if len(merged) > cap:
                # over budget: drop the greedy (non-forced) bounds
                # nearest to a forced one until the cap holds
                keep_forced = np.isin(merged, forced) | np.isinf(merged)
                greedy = merged[~keep_forced]
                n_drop = len(merged) - cap
                if n_drop > 0 and len(greedy):
                    dist = np.min(np.abs(greedy[:, None]
                                         - forced[None, :]), axis=1)
                    drop = set(greedy[np.argsort(dist)[:n_drop]])
                    merged = np.array([b for b in merged
                                       if b not in drop])
            if merged[-1] != np.inf:
                merged = np.append(merged, np.inf)
            m.bin_upper_bound = merged
            m.num_bin = len(merged) + (1 if m.missing_type == MISSING_NAN
                                       else 0)
            # most_freq_bin tracks default_bin whenever the feature had
            # zero mass; recompute both against the merged bounds so
            # neither can point at a pre-merge bin index
            m.default_bin = int(np.searchsorted(merged, 0.0,
                                                side="left"))
            m.most_freq_bin = m.default_bin if m._zero_mass else 0
        return m

    @staticmethod
    def _numerical_from_sample(values, total_sample_cnt, max_bin,
                               min_data_in_bin, use_missing,
                               zero_as_missing) -> "BinMapper":
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        finite = values[~nan_mask]
        # implicit zeros: rows not present in the sample (sparse semantics) —
        # total_sample_cnt may exceed len(values); the difference counts as 0.
        implicit_zero = max(0, total_sample_cnt - len(values) - na_cnt)

        if zero_as_missing:
            missing_type = MISSING_ZERO
            # NaNs will be mapped to the zero bin at bin time; count them
            # into the zero mass so bin-size statistics match
            implicit_zero += na_cnt
        elif use_missing and na_cnt > 0:
            missing_type = MISSING_NAN
        else:
            missing_type = MISSING_NONE
            if na_cnt > 0:
                # treat NaN as zero when use_missing=false (reference does)
                implicit_zero += na_cnt

        zero_mask = np.abs(finite) <= K_ZERO_THRESHOLD
        zero_cnt = int(zero_mask.sum()) + implicit_zero
        neg = np.sort(finite[(~zero_mask) & (finite < 0)])
        pos = np.sort(finite[(~zero_mask) & (finite > 0)])

        n_eff = len(neg) + len(pos) + zero_cnt
        # reserve one bin for NaN when missing_type == nan
        value_bins = max_bin - (1 if missing_type == MISSING_NAN else 0)
        # allocate bins to the negative / positive sides by mass; zero gets
        # its own forced bin whenever zeros exist
        zero_bin_needed = zero_cnt > 0
        avail = value_bins - (1 if zero_bin_needed else 0)
        bounds: List[float] = []
        if n_eff == 0 or avail <= 0:
            bounds = [np.inf]
        else:
            nz = len(neg) + len(pos)
            if nz == 0:
                bounds = [np.inf]
            else:
                neg_bins = int(round(avail * len(neg) / nz)) if nz else 0
                neg_bins = min(max(neg_bins, 1 if len(neg) else 0), avail)
                pos_bins = avail - neg_bins if len(pos) else 0
                neg_bins = avail - pos_bins if len(neg) else 0
                if len(neg):
                    dv, cnt = _distinct_with_counts(neg)
                    b = _greedy_find_distinct_bounds(
                        dv, cnt, max(neg_bins, 1), len(neg), min_data_in_bin)
                    b[-1] = -K_ZERO_THRESHOLD  # cap the negative side at zero
                    bounds.extend(b)
                if zero_bin_needed:
                    if not bounds or bounds[-1] < -K_ZERO_THRESHOLD:
                        bounds.append(-K_ZERO_THRESHOLD)
                    bounds.append(K_ZERO_THRESHOLD)
                elif len(neg) and len(pos):
                    # ensure a boundary separating neg from pos exists
                    pass
                if len(pos):
                    dv, cnt = _distinct_with_counts(pos)
                    b = _greedy_find_distinct_bounds(
                        dv, cnt, max(pos_bins, 1), len(pos), min_data_in_bin)
                    bounds.extend(b)
                else:
                    if not bounds or bounds[-1] != np.inf:
                        bounds.append(np.inf)
        # dedupe & sort
        ub = np.array(sorted(set(bounds)), dtype=np.float64)
        if len(ub) == 0 or ub[-1] != np.inf:
            ub = np.append(ub, np.inf)
        num_bin = len(ub) + (1 if missing_type == MISSING_NAN else 0)

        m = BinMapper(bin_type=BIN_TYPE_NUMERICAL, num_bin=int(num_bin),
                      missing_type=missing_type, bin_upper_bound=ub,
                      min_value=float(finite.min()) if len(finite) else 0.0,
                      max_value=float(finite.max()) if len(finite) else 0.0)
        m.default_bin = int(np.searchsorted(ub, 0.0, side="left"))
        m.most_freq_bin = m.default_bin if zero_cnt > 0 else 0
        m._zero_mass = zero_cnt > 0   # read by the forcedbins merge
        return m

    @staticmethod
    def _categorical_from_sample(values, max_bin, use_missing) -> "BinMapper":
        nan_mask = np.isnan(values)
        cats = values[~nan_mask].astype(np.int64)
        if np.any(values[~nan_mask] < 0):
            log.warning("Met negative value in categorical features, will "
                        "convert it to NaN")
            neg = values[~nan_mask] < 0
            cats = cats[~neg]
        dv, cnt = np.unique(cats, return_counts=True)
        order = np.argsort(-cnt, kind="stable")
        dv, cnt = dv[order], cnt[order]
        # keep top categories covering 99% of mass, capped at max_bin-1
        # (bin 0 is the NaN/unseen bin)
        keep = min(len(dv), max_bin - 1)
        if keep > 1:
            cum = np.cumsum(cnt[:keep])
            cut = int(np.searchsorted(cum, 0.99 * cnt.sum()) + 1)
            keep = min(keep, max(cut, 1))
        dv = dv[:keep]
        bin_to_cat = np.concatenate([[-1], dv]).astype(np.int64)
        cat_to_bin = {int(v): i + 1 for i, v in enumerate(dv)}
        m = BinMapper(bin_type=BIN_TYPE_CATEGORICAL, num_bin=int(keep + 1),
                      missing_type=MISSING_NAN if use_missing else MISSING_NONE,
                      bin_to_cat=bin_to_cat, cat_to_bin=cat_to_bin,
                      min_value=float(dv.min()) if len(dv) else 0.0,
                      max_value=float(dv.max()) if len(dv) else 0.0)
        m.default_bin = cat_to_bin.get(0, 0)
        m.most_freq_bin = 1 if keep >= 1 else 0
        return m

    # ------------------------------------------------------------------
    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value→bin for a full column (NaN-aware). Large
        numerical columns take the single-pass native path (f32
        accepted WITHOUT the float64 copy; strided column views of a
        row-major matrix bin in place)."""
        raw = np.asarray(values)
        if self.bin_type != BIN_TYPE_CATEGORICAL \
                and raw.ndim == 1 and len(raw) > 65536 \
                and raw.dtype in (np.float32, np.float64) \
                and raw.strides[0] > 0:
            lib = _native()
            if lib is not None:
                import ctypes
                ub = np.ascontiguousarray(self.bin_upper_bound,
                                          dtype=np.float64)
                out = np.empty(len(raw), dtype=np.int32)
                mt = {MISSING_NONE: 0, MISSING_ZERO: 1,
                      MISSING_NAN: 2}[self.missing_type]
                lib.bin_numeric_column(
                    raw.ctypes.data_as(ctypes.c_void_p),
                    int(raw.dtype == np.float32),
                    len(raw), raw.strides[0] // raw.itemsize,
                    ub.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    len(ub), mt, int(self.default_bin),
                    int(self.num_bin),
                    out.ctypes.data_as(ctypes.c_void_p), 2, 1)
                return out
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_TYPE_CATEGORICAL:
            out = np.zeros(len(values), dtype=np.int32)
            nan_mask = ~np.isfinite(values)
            iv = np.where(nan_mask, -1, values).astype(np.int64)
            # vectorized dict lookup via the bin_to_cat table
            table_vals = self.bin_to_cat[1:]
            sorter = np.argsort(table_vals)
            pos = np.searchsorted(table_vals[sorter], iv)
            pos = np.clip(pos, 0, len(table_vals) - 1)
            hit = table_vals[sorter][pos] == iv
            out[hit & ~nan_mask] = (sorter[pos[hit & ~nan_mask]] + 1)
            return out
        nan_mask = np.isnan(values)
        if self.missing_type == MISSING_ZERO:
            values = np.where(nan_mask, 0.0, values)
            nan_mask = np.zeros_like(nan_mask)
        vb = np.searchsorted(self.bin_upper_bound, values, side="left")
        vb = np.clip(vb, 0, len(self.bin_upper_bound) - 1)
        if self.missing_type == MISSING_NAN:
            vb = np.where(nan_mask, self.num_bin - 1, vb)
        else:
            vb = np.where(nan_mask, self.default_bin, vb)
        return vb.astype(np.int32)

    def value_to_bin(self, value: float) -> int:
        return int(self.values_to_bins(np.array([value]))[0])

    def bin_to_threshold(self, bin_idx: int) -> float:
        """Upper-bound real value for a bin threshold (for model dump)."""
        assert self.bin_type == BIN_TYPE_NUMERICAL
        b = int(np.clip(bin_idx, 0, len(self.bin_upper_bound) - 1))
        return float(self.bin_upper_bound[b])


def find_bin_mappers(X: np.ndarray, max_bin: int, min_data_in_bin: int = 3,
                     sample_cnt: int = 200000, use_missing: bool = True,
                     zero_as_missing: bool = False,
                     categorical_features: Optional[List[int]] = None,
                     max_bin_by_feature: Optional[List[int]] = None,
                     seed: int = 1,
                     forced_bins: Optional[Dict[int, List[float]]] = None,
                     n_threads: int = 0) -> List[BinMapper]:
    """Build a BinMapper per column of ``X`` from a row sample.

    Mirrors DatasetLoader::ConstructFromSampleData's sampling step
    (src/io/dataset_loader.cpp, UNVERIFIED). Per-feature boundary
    finding is independent and numpy-sort dominated (sorts release the
    GIL), so columns run on a thread pool when the sample is big enough
    to pay for it; results are position-ordered, so the mapper list is
    identical to the serial loop's.
    """
    n_rows, n_features = X.shape
    categorical = set(categorical_features or [])
    # scipy sparse accepted without densifying the full matrix: rows are
    # sampled in CSR, then one column at a time is materialized (the
    # reference's sparse sample path, dataset_loader.cpp SampleData)
    is_sparse = hasattr(X, "tocsr") and not isinstance(X, np.ndarray)
    if n_rows > sample_cnt:
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n_rows, size=sample_cnt, replace=False))
        sample = (X.tocsr()[idx] if is_sparse else X[idx])
    else:
        sample = X
    if is_sparse:
        sample = sample.tocsc()
    n_sample = sample.shape[0]

    def build_one(f: int) -> BinMapper:
        mb = max_bin
        if max_bin_by_feature and f < len(max_bin_by_feature) \
                and max_bin_by_feature[f] > 0:
            mb = max_bin_by_feature[f]
        col = sample[:, f]
        if is_sparse:
            col = np.asarray(col.todense(), dtype=np.float64).ravel()
        return BinMapper.from_sample(
            col, n_sample, mb, min_data_in_bin, use_missing,
            zero_as_missing, is_categorical=(f in categorical),
            forced_bounds=(forced_bins or {}).get(f))

    n_threads = min(resolve_ingest_threads(n_threads), n_features)
    if n_threads > 1 and n_sample * n_features >= 1_000_000:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=n_threads) as ex:
            return list(ex.map(build_one, range(n_features)))
    return [build_one(f) for f in range(n_features)]


def mappers_from_params(X, params: Dict, categorical_idx=None,
                        sample_cnt=None) -> List["BinMapper"]:
    """The ONE params -> ``find_bin_mappers`` marshaling point, shared
    by ``Dataset.construct`` and the distributed bin-boundary sync
    (``parallel.launch.sync_bin_mappers``) so both paths can never
    drift on a binning parameter."""
    from ..config import coerce_bool, get_param
    p = params
    return find_bin_mappers(
        X,
        max_bin=int(p.get("max_bin", 255)),
        min_data_in_bin=int(p.get("min_data_in_bin", 3)),
        sample_cnt=(int(p.get("bin_construct_sample_cnt", 200000))
                    if sample_cnt is None else sample_cnt),
        use_missing=coerce_bool(p.get("use_missing", True)),
        zero_as_missing=coerce_bool(p.get("zero_as_missing", False)),
        categorical_features=categorical_idx,
        max_bin_by_feature=p.get("max_bin_by_feature"),
        seed=int(p.get("data_random_seed", 1)),
        forced_bins=(load_forced_bins(str(p["forcedbins_filename"]))
                     if p.get("forcedbins_filename") else None),
        n_threads=get_param(p, "tpu_ingest_threads"))


def load_forced_bins(path: str) -> Dict[int, List[float]]:
    """Parse a forcedbins_filename JSON file: a list of
    ``{"feature": i, "bin_upper_bound": [...]}`` entries (upstream
    docs/Advanced-Topics forced-bins format)."""
    import json
    with open(path) as f:
        spec = json.load(f)
    out: Dict[int, List[float]] = {}
    for entry in spec:
        out[int(entry["feature"])] = [
            float(v) for v in entry["bin_upper_bound"]]
    return out
