"""LightGBM-compatible model text serialization.

Reference: ``GBDT::SaveModelToString`` / ``LoadModelFromString``
(src/boosting/gbdt_model_text.cpp, UNVERIFIED — empty mount, see SURVEY.md
banner). Writing the reference's versioned text format gives free interop:
models trained here load in stock LightGBM and vice versa, and it doubles
as the checkpoint/resume format (snapshot_freq, init_model continuation).

Notes on faithful quirks:
- ``decision_type`` packs: bit0 = categorical split, bit1 = default_left,
  bits 2-3 = missing type (0 none / 1 zero / 2 NaN).
- boost-from-average init scores are folded into the first tree's leaf
  values at save time (the reference's AddBias), so the file is
  self-contained: prediction = sum of tree outputs.
- ``split_feature`` uses ORIGINAL feature indices (pre feature-dropping),
  unlike the in-engine trees which index used features.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..tree import Tree
from ..utils import log

_MISSING_CODE = {"none": 0, "zero": 1, "nan": 2}
_MISSING_DECODE = {v: k for k, v in _MISSING_CODE.items()}


@dataclasses.dataclass
class HostModel:
    """A fully host-side model: trees + metadata, predict + (de)serialize."""

    trees: List[Tree]
    num_class: int = 1
    num_tree_per_iteration: int = 1
    objective_str: str = "regression"
    feature_names: List[str] = dataclasses.field(default_factory=list)
    feature_infos: List[str] = dataclasses.field(default_factory=list)
    max_feature_idx: int = 0
    label_index: int = 0
    average_output: bool = False
    params: Dict[str, str] = dataclasses.field(default_factory=dict)
    # per-node missing type codes per tree (parallel to split arrays)
    missing_types: Optional[List[np.ndarray]] = None
    # category-value lists for pandas category-dtype input columns
    # (stock lightgbm's pandas_categorical model-file field)
    pandas_categorical: Optional[list] = None

    # ------------------------------------------------------------------
    @staticmethod
    def from_engine(engine, config, best_iteration: int = -1) -> "HostModel":
        ds = engine.train_set
        used = ds.used_features
        trees: List[Tree] = []
        missing_types: List[np.ndarray] = []
        for ti, t in enumerate(engine.models):
            t2 = Tree(**{f.name: getattr(t, f.name)
                         for f in dataclasses.fields(Tree)})
            # map used-feature indices -> original feature indices
            t2.split_feature = np.array(
                [used[int(f)] for f in t.split_feature], dtype=np.int32)
            # zero-missing features serialize as missing_type NONE: this
            # learner bins NaN into the zero bin and routes zeros by
            # threshold (never by default-direction), and stock LightGBM
            # with mt=none converts NaN to 0 at predict — identical
            # routing; writing mt=zero would make stock route
            # |x|<=1e-35 by a default_left this learner never fits
            mt = np.array(
                [0 if ds.bin_mappers[int(f)].missing_type == "zero"
                 else _MISSING_CODE[ds.bin_mappers[int(f)].missing_type]
                 for f in t2.split_feature], dtype=np.int32)
            if t2.is_categorical is not None:
                # categorical missing routes via bitset-miss, not the
                # numerical default-direction machinery
                mt[t2.is_categorical[:len(mt)]] = 0
            t2.node_missing_type = mt    # host traversal NaN semantics
            if getattr(t, "is_linear", False):
                t2.is_linear = True
                t2.leaf_coeff = list(t.leaf_coeff)
                # leaf feature indices: used-space -> original
                t2.leaf_features = [[used[f] for f in lf]
                                    for lf in t.leaf_features]
            if ti < engine.num_class and not engine.average_output:
                # fold init score into the first iteration's trees
                # (AddBias); RF trees already carry the bias per-tree
                bias = float(engine.init_scores[ti % engine.num_class])
                t2.leaf_value = t2.leaf_value + bias
                t2.internal_value = t2.internal_value + bias
                if getattr(t2, "is_linear", False):
                    # linear intercepts carry the bias too
                    t2.leaf_coeff = [
                        None if b is None else
                        np.concatenate([b[:-1], [b[-1] + bias]])
                        for b in t2.leaf_coeff]
            trees.append(t2)
            missing_types.append(mt)

        obj = config.objective
        if obj == "regression" and getattr(config, "reg_sqrt", False):
            obj_str = "regression sqrt"        # reference token order
        elif obj == "binary":
            obj_str = f"binary sigmoid:{config.sigmoid:g}"
        elif obj in ("multiclass", "multiclassova"):
            obj_str = f"{obj} num_class:{config.num_class}"
            if obj == "multiclassova":
                obj_str += f" sigmoid:{config.sigmoid:g}"
        elif obj == "lambdarank":
            obj_str = "lambdarank"
        else:
            obj_str = obj

        infos = []
        for f in range(ds.num_total_features):
            m = ds.bin_mappers[f] if f < len(ds.bin_mappers) else None
            if m is None or m.is_trivial:
                infos.append("none")
            elif m.bin_type == "categorical":
                infos.append(":".join(str(int(v))
                                      for v in m.bin_to_cat[1:]))
            else:
                infos.append(f"[{m.min_value:g}:{m.max_value:g}]")

        return HostModel(
            trees=trees,
            num_class=engine.num_class,
            num_tree_per_iteration=engine.num_class,
            objective_str=obj_str,
            feature_names=list(ds.feature_names),
            feature_infos=infos,
            max_feature_idx=ds.num_total_features - 1,
            average_output=engine.average_output,
            params={"objective": obj, "num_leaves": config.num_leaves,
                    "learning_rate": config.learning_rate,
                    "max_bin": config.max_bin,
                    "boosting": config.boosting},
            missing_types=missing_types,
            pandas_categorical=getattr(ds, "pandas_categorical", None),
        )

    # ------------------------------------------------------------------
    def predict(self, data, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1,
                pred_leaf: bool = False,
                pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0,
                contrib_force_f64=None) -> np.ndarray:
        from .dataset import Dataset as _DS
        from .dataset import apply_pandas_categorical
        data = apply_pandas_categorical(data, self.pandas_categorical)
        if hasattr(data, "tocsr") and not isinstance(data, np.ndarray) \
                and data.shape[0] > 0:
            # scipy sparse: densify in bounded row chunks (linear
            # leaves / SHAP need raw feature values, but never the whole
            # matrix at once)
            csr = data.tocsr()
            chunk = 65536
            outs = [self.predict(
                        csr[i:i + chunk].toarray(),
                        raw_score=raw_score,
                        start_iteration=start_iteration,
                        num_iteration=num_iteration,
                        pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                        pred_early_stop=pred_early_stop,
                        pred_early_stop_freq=pred_early_stop_freq,
                        pred_early_stop_margin=pred_early_stop_margin,
                        contrib_force_f64=contrib_force_f64)
                    for i in range(0, csr.shape[0], chunk)]
            return np.concatenate(outs, axis=0)
        X = _DS._to_matrix(data)
        n = X.shape[0]
        total_iters = len(self.trees) // max(self.num_tree_per_iteration, 1)
        if num_iteration <= 0:
            num_iteration = total_iters - start_iteration
        num_iteration = min(num_iteration, total_iters - start_iteration)
        t0 = start_iteration * self.num_tree_per_iteration
        t1 = t0 + num_iteration * self.num_tree_per_iteration
        use = self.trees[t0:t1]
        K = max(self.num_tree_per_iteration, 1)
        if pred_leaf:
            out = np.zeros((n, len(use)), dtype=np.int32)
            for i, t in enumerate(use):
                out[:, i] = t.predict_leaf_raw(X)
            return out
        if pred_contrib:
            return self._predict_contrib(X, use, K,
                                         force_f64=contrib_force_f64,
                                         slice_key=(t0, t1))
        raw = np.zeros((n, K), dtype=np.float64)
        obj0 = self.objective_str.split(" ")[0]
        early = (pred_early_stop and not self.average_output
                 and obj0 in ("binary", "multiclass", "softmax",
                              "multiclassova"))
        active = np.ones(n, dtype=bool) if early else None
        for i, t in enumerate(use):
            k = (t0 + i) % K
            if active is None:
                raw[:, k] += t.predict_raw(X)
            else:
                # prediction early-stopping (pred_early_stop;
                # reference: src/boosting/prediction_early_stop.cpp):
                # rows whose margin already exceeds the threshold stop
                # traversing further trees
                if active.any():
                    raw[active, k] += t.predict_raw(X[active])
                if (i + 1) % (pred_early_stop_freq * K) == 0:
                    if K == 1:
                        # reference binary margin: 2 * |raw|
                        # (prediction_early_stop.cpp)
                        margin = 2.0 * np.abs(raw[:, 0])
                    else:
                        part = np.partition(raw, K - 2, axis=1)
                        margin = part[:, -1] - part[:, -2]
                    active &= margin < pred_early_stop_margin
        if self.average_output and len(use):
            raw /= (len(use) // K)
        if raw_score:
            return raw[:, 0] if K == 1 else raw
        return self._transform(raw)

    def _transform(self, raw: np.ndarray) -> np.ndarray:
        obj = self.objective_str.split(" ")[0]
        if obj == "binary":
            sigmoid = 1.0
            for tok in self.objective_str.split(" ")[1:]:
                if tok.startswith("sigmoid:"):
                    sigmoid = float(tok.split(":")[1])
            return 1.0 / (1.0 + np.exp(-sigmoid * raw[:, 0]))
        if obj in ("multiclass", "softmax"):
            e = np.exp(raw - raw.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        if obj == "multiclassova":
            p = 1.0 / (1.0 + np.exp(-raw))
            return p / p.sum(axis=1, keepdims=True)
        if obj in ("poisson", "gamma", "tweedie"):
            return np.exp(raw[:, 0])
        if obj in ("cross_entropy", "xentropy"):
            return 1.0 / (1.0 + np.exp(-raw[:, 0]))
        if obj == "regression" and "sqrt" in self.objective_str.split(" "):
            r = raw[:, 0]
            return np.sign(r) * r * r
        return raw[:, 0] if raw.shape[1] == 1 else raw

    def _predict_contrib(self, X, trees, K, force_f64=None,
                          slice_key=None):
        from ..ops.shap import build_shap_tables, forest_shap_batch
        if any(getattr(t, "is_linear", False) for t in trees):
            # the reference likewise refuses SHAP for linear trees —
            # constant-leaf attributions would not sum to the prediction
            log.fatal("pred_contrib is not supported for linear-tree "
                      "models")
        n = X.shape[0]
        n_feat = self.max_feature_idx + 1
        tables = None
        if slice_key is not None:
            # per-slice path-table cache: a HostModel is immutable once
            # built (Booster._to_host_model already caches the model
            # itself), so the demoted/host SHAP route stops paying the
            # per-call path walk too. Stump-only slices build None —
            # don't cache those, forest_shap_batch short-circuits them.
            cache = getattr(self, "_shap_table_cache", None)
            if cache is None:
                cache = self._shap_table_cache = {}
            tables = cache.get(slice_key)
            if tables is None:
                tables = build_shap_tables(trees, n_feat, K)
                if tables is not None:
                    cache[slice_key] = tables
                    while len(cache) > 8:
                        cache.pop(next(iter(cache)))
        out = forest_shap_batch(trees, X, n_feat, K=K,
                                force_f64=force_f64, tables=tables)
        if self.average_output and len(trees):
            # RF: contributions average like the prediction does, keeping
            # the SHAP local-accuracy invariant sum(contrib) == raw pred
            out /= (len(trees) // K)
        if K == 1:
            return out[:, 0, :]
        return out.reshape(n, K * (n_feat + 1))


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------
def _arr(name: str, values, fmt="{}") -> str:
    return f"{name}=" + " ".join(fmt.format(v) for v in values)


def _tree_to_string(t: Tree, missing_type: Optional[np.ndarray]) -> str:
    nn = t.num_nodes
    if missing_type is None:
        missing_type = np.zeros(nn, dtype=np.int32)
    is_cat = (t.is_categorical[:nn].astype(np.int32)
              if t.is_categorical is not None
              else np.zeros(nn, dtype=np.int32))
    num_cat = int(len(t.cat_boundaries) - 1) \
        if t.cat_boundaries is not None else 0
    decision_type = (is_cat
                     | (np.asarray(t.default_left[:nn]).astype(np.int32)
                        * 2)
                     | (missing_type[:nn].astype(np.int32) << 2))
    lines = [
        f"num_leaves={t.num_leaves}",
        f"num_cat={num_cat}",
        _arr("split_feature", t.split_feature[:nn]),
        _arr("split_gain", t.split_gain[:nn], "{:g}"),
        _arr("threshold", t.threshold_real[:nn], "{:.17g}"),
        _arr("decision_type", decision_type),
        _arr("left_child", t.left_child[:nn]),
        _arr("right_child", t.right_child[:nn]),
        _arr("leaf_value", t.leaf_value[:t.num_leaves], "{:.17g}"),
        _arr("leaf_weight", t.leaf_weight[:t.num_leaves], "{:g}"),
        _arr("leaf_count", t.leaf_count[:t.num_leaves]),
        _arr("internal_value", t.internal_value[:nn], "{:g}"),
        _arr("internal_weight", [0.0] * nn, "{:g}"),
        _arr("internal_count", t.internal_count[:nn]),
        f"is_linear={1 if getattr(t, 'is_linear', False) else 0}",
        f"shrinkage={t.shrinkage:g}",
    ]
    if getattr(t, "is_linear", False):
        # linear-leaf payload: intercept per leaf (leaf_const), flat
        # feature/coefficient lists with per-leaf counts
        # (gbdt_model_text.cpp linear-tree block layout)
        nl = t.num_leaves
        consts, counts, feats, coefs = [], [], [], []
        for lf in range(nl):
            beta = t.leaf_coeff[lf] if lf < len(t.leaf_coeff) else None
            if beta is None:
                consts.append(float(t.leaf_value[lf]))
                counts.append(0)
            else:
                consts.append(float(beta[-1]))
                counts.append(len(t.leaf_features[lf]))
                feats.extend(int(f) for f in t.leaf_features[lf])
                coefs.extend(float(c) for c in beta[:-1])
        lines.append(_arr("leaf_const", consts, "{:.17g}"))
        lines.append(_arr("num_features", counts))
        lines.append(_arr("leaf_features", feats))
        lines.append(_arr("leaf_coeff", coefs, "{:.17g}"))
    if num_cat > 0:
        # LightGBM layout: threshold[i] indexes cat_boundaries, whose
        # [idx, idx+1) range delimits uint32 words in cat_threshold
        lines.insert(6, _arr("cat_threshold", t.cat_threshold))
        lines.insert(6, _arr("cat_boundaries", t.cat_boundaries))
    return "\n".join(lines) + "\n"


def save_model_string(model: HostModel,
                      importance_type: str = "split") -> str:
    tree_strs = []
    for i, t in enumerate(model.trees):
        mt = (model.missing_types[i]
              if model.missing_types is not None else None)
        tree_strs.append(f"Tree={i}\n" + _tree_to_string(t, mt) + "\n")
    header = [
        "tree",
        "version=v4",
        f"num_class={model.num_class}",
        f"num_tree_per_iteration={model.num_tree_per_iteration}",
        f"label_index={model.label_index}",
        f"max_feature_idx={model.max_feature_idx}",
        f"objective={model.objective_str}",
        *((["average_output"]) if model.average_output else []),
        "feature_names=" + " ".join(model.feature_names),
        "feature_infos=" + " ".join(model.feature_infos),
        "tree_sizes=" + " ".join(str(len(s)) for s in tree_strs),
        "",
    ]
    out = "\n".join(header) + "\n" + "".join(tree_strs)
    out += "end of trees\n\n"
    # feature importances, sorted desc like the reference; split counts
    # by default, total gain under saved_feature_importance_type=1
    use_gain = importance_type in ("gain", 1, "1")
    imp: Dict[str, float] = {}
    for t in model.trees:
        for j in range(t.num_nodes):
            f = int(t.split_feature[j])
            name = (model.feature_names[f]
                    if f < len(model.feature_names) else f"Column_{f}")
            w = float(t.split_gain[j]) if use_gain else 1
            imp[name] = imp.get(name, 0) + w
    out += "feature_importances:\n"
    for name, cnt in sorted(imp.items(), key=lambda kv: -kv[1]):
        out += f"{name}={cnt:g}\n" if use_gain else f"{name}={cnt}\n"
    out += "\nparameters:\n"
    for k, v in model.params.items():
        out += f"[{k}: {v}]\n"
    import json as _json
    out += ("end of parameters\n\npandas_categorical:"
            + _json.dumps(model.pandas_categorical) + "\n")
    return out


def _node_json(model: HostModel, t: Tree, mt, nd: int) -> Dict:
    """Nested node dict (GBDT::DumpModel tree_structure layout)."""
    if t.num_nodes == 0 or nd < 0:
        leaf = -nd - 1 if nd < 0 else 0
        return {"leaf_index": int(leaf),
                "leaf_value": float(t.leaf_value[leaf]),
                "leaf_weight": float(t.leaf_weight[leaf]),
                "leaf_count": int(t.leaf_count[leaf])}
    is_cat = (t.is_categorical is not None
              and bool(t.is_categorical[nd]))
    if is_cat:
        # LightGBM's DumpModel writes the category left-set as
        # "v1||v2||..." (tree.cpp NodeToJSON), not the group index
        ci = int(t.threshold_real[nd])
        words = t.cat_threshold[
            t.cat_boundaries[ci]:t.cat_boundaries[ci + 1]]
        cats = np.flatnonzero(np.unpackbits(
            np.ascontiguousarray(words).view(np.uint8),
            bitorder="little"))
        thr_repr = "||".join(str(int(c)) for c in cats)
    else:
        thr_repr = float(t.threshold_real[nd])
    node = {
        "split_index": int(nd),
        "split_feature": int(t.split_feature[nd]),
        "split_gain": float(t.split_gain[nd]),
        "threshold": thr_repr,
        "decision_type": "==" if is_cat else "<=",
        "default_left": bool(t.default_left[nd]),
        "missing_type": {0: "None", 1: "Zero", 2: "NaN"}.get(
            int(mt[nd]) if mt is not None else 0, "None"),
        "internal_value": float(t.internal_value[nd]),
        "internal_count": int(t.internal_count[nd]),
    }
    lc, rc = int(t.left_child[nd]), int(t.right_child[nd])
    node["left_child"] = _node_json(model, t, mt, lc)
    node["right_child"] = _node_json(model, t, mt, rc)
    return node


def dump_model_json(model: HostModel, num_iteration: int = -1,
                    start_iteration: int = 0) -> Dict:
    """JSON-able model dict (GBDT::DumpModel, gbdt_model_text.cpp)."""
    import sys
    max_leaves = max((t.num_leaves for t in model.trees), default=1)
    sys.setrecursionlimit(max(sys.getrecursionlimit(),
                              4 * max_leaves + 1000))
    K = max(model.num_tree_per_iteration, 1)
    total_iters = len(model.trees) // K
    if num_iteration <= 0:
        num_iteration = total_iters - start_iteration
    num_iteration = min(num_iteration, total_iters - start_iteration)
    t0 = start_iteration * K
    trees = []
    for i in range(t0, t0 + num_iteration * K):
        t = model.trees[i]
        mt = (model.missing_types[i]
              if model.missing_types is not None else None)
        trees.append({
            "tree_index": i,
            "num_leaves": int(t.num_leaves),
            "num_cat": (int(len(t.cat_boundaries) - 1)
                        if t.cat_boundaries is not None else 0),
            "shrinkage": float(t.shrinkage),
            "tree_structure": _node_json(
                model, t, mt, 0 if t.num_nodes else -1),
        })
    return {
        "name": "tree",
        "version": "v4",
        "num_class": model.num_class,
        "num_tree_per_iteration": model.num_tree_per_iteration,
        "label_index": model.label_index,
        "max_feature_idx": model.max_feature_idx,
        "objective": model.objective_str,
        "average_output": model.average_output,
        "feature_names": list(model.feature_names),
        "feature_infos": list(model.feature_infos),
        "tree_info": trees,
    }


def _node_c(t: Tree, nd: int, indent: str) -> str:
    """Nested if/else for one node (convert_model C export)."""
    if t.num_nodes == 0 or nd < 0:
        leaf = -nd - 1 if nd < 0 else 0
        return f"{indent}return {float(t.leaf_value[leaf]):.17g};\n"
    f = int(t.split_feature[nd])
    is_cat = (t.is_categorical is not None
              and bool(t.is_categorical[nd]))
    if is_cat:
        ci = int(t.threshold_real[nd])
        words = t.cat_threshold[
            t.cat_boundaries[ci]:t.cat_boundaries[ci + 1]]
        vals = [int(v) for v in np.flatnonzero(np.unpackbits(
            np.ascontiguousarray(words).view(np.uint8),
            bitorder="little"))]
        cond = " || ".join(f"(int)x[{f}] == {v}" for v in vals) or "0"
        cond = f"(!isnan(x[{f}]) && ({cond}))"
    else:
        thr = float(t.threshold_real[nd])
        dl = "1" if bool(t.default_left[nd]) else "0"
        nmt = getattr(t, "node_missing_type", None)
        code = int(nmt[nd]) if nmt is not None else 2
        if code == 0:      # none: NaN behaves as 0.0
            cond = f"((isnan(x[{f}]) ? 0.0 : x[{f}]) <= {thr:.17g})"
        elif code == 1:    # zero: |x|<=1e-35 and NaN take the default
            cond = (f"((isnan(x[{f}]) || fabs(x[{f}]) <= 1e-35) ? {dl} "
                    f": (x[{f}] <= {thr:.17g}))")
        else:              # nan
            cond = f"(isnan(x[{f}]) ? {dl} : (x[{f}] <= {thr:.17g}))"
    out = f"{indent}if ({cond}) {{\n"
    out += _node_c(t, int(t.left_child[nd]), indent + "  ")
    out += f"{indent}}} else {{\n"
    out += _node_c(t, int(t.right_child[nd]), indent + "  ")
    out += f"{indent}}}\n"
    return out


def model_to_c(model: HostModel) -> str:
    """Standalone C prediction code (the reference's convert_model
    task, src/application/application.cpp: if-else model export)."""
    import sys
    max_leaves = max((t.num_leaves for t in model.trees), default=1)
    sys.setrecursionlimit(max(sys.getrecursionlimit(),
                              4 * max_leaves + 1000))
    K = max(model.num_tree_per_iteration, 1)
    parts = ["#include <math.h>\n\n"]
    for i, t in enumerate(model.trees):
        parts.append(f"static double PredictTree{i}"
                     f"(const double* x) {{\n")
        parts.append(_node_c(t, 0 if t.num_nodes else -1, "  "))
        parts.append("}\n\n")
    parts.append(f"void Predict(const double* x, double* out) {{\n")
    for k in range(K):
        parts.append(f"  out[{k}] = 0.0;\n")
    for i in range(len(model.trees)):
        parts.append(f"  out[{i % K}] += PredictTree{i}(x);\n")
    if model.average_output and model.trees:
        n_iter = len(model.trees) // K
        for k in range(K):
            parts.append(f"  out[{k}] /= {n_iter};\n")
    parts.append("}\n")
    return "".join(parts)


def _parse_kv_block(text: str) -> Dict[str, str]:
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if "=" in line:
            k, v = line.split("=", 1)
            out[k] = v
    return out


def _parse_tree_block(block: str) -> (Tree, np.ndarray):
    kv = _parse_kv_block(block)
    num_leaves = int(kv["num_leaves"])
    nn = max(num_leaves - 1, 0)

    def geti(name, size, default=0):
        if name not in kv or not kv[name].strip():
            return np.full(size, default, dtype=np.int32)
        return np.array(kv[name].split(), dtype=np.float64).astype(np.int32)

    def getf(name, size, default=0.0):
        if name not in kv or not kv[name].strip():
            return np.full(size, default, dtype=np.float64)
        return np.array(kv[name].split(), dtype=np.float64)

    decision_type = geti("decision_type", nn)
    default_left = (decision_type & 2) > 0
    missing_type = (decision_type >> 2) & 3
    threshold = getf("threshold", nn)
    num_cat = int(kv.get("num_cat", 0))
    is_categorical = None
    cat_boundaries = None
    cat_threshold = None
    if num_cat > 0:
        is_categorical = (decision_type & 1) > 0
        cat_boundaries = np.array(kv["cat_boundaries"].split(),
                                  dtype=np.int64)
        cat_threshold = np.array(kv["cat_threshold"].split(),
                                 dtype=np.float64).astype(np.uint32)
    is_linear = int(kv.get("is_linear", 0)) == 1
    t = Tree(
        num_leaves=num_leaves,
        split_feature=geti("split_feature", nn),
        threshold_bin=np.zeros(nn, dtype=np.int32),
        threshold_real=threshold,
        default_left=default_left,
        left_child=geti("left_child", nn),
        right_child=geti("right_child", nn),
        split_gain=getf("split_gain", nn),
        internal_value=getf("internal_value", nn),
        internal_count=geti("internal_count", nn).astype(np.int64),
        leaf_value=getf("leaf_value", num_leaves),
        leaf_count=geti("leaf_count", num_leaves).astype(np.int64),
        leaf_weight=getf("leaf_weight", num_leaves),
        shrinkage=float(kv.get("shrinkage", 1.0)),
        cat_boundaries=cat_boundaries,
        cat_threshold=cat_threshold,
        is_categorical=is_categorical,
    )
    if is_linear and "leaf_const" in kv:
        consts = getf("leaf_const", num_leaves)
        counts = geti("num_features", num_leaves)
        feats_flat = (np.array(kv["leaf_features"].split(), dtype=np.int64)
                      if kv.get("leaf_features", "").strip() else
                      np.zeros(0, np.int64))
        coefs_flat = (np.array(kv["leaf_coeff"].split(), dtype=np.float64)
                      if kv.get("leaf_coeff", "").strip() else
                      np.zeros(0))
        t.is_linear = True
        t.leaf_features = []
        t.leaf_coeff = []
        off = 0
        for lf in range(num_leaves):
            c = int(counts[lf])
            if c == 0:
                # a linear-tree leaf with no features still outputs
                # leaf_const (tree.h Tree::Predict: the coefficient
                # loop is empty so nan_found never trips), NOT
                # leaf_value — pinned by tests/test_model_fixture.py
                t.leaf_features.append([])
                t.leaf_coeff.append(np.array([consts[lf]]))
            else:
                t.leaf_features.append(
                    [int(f) for f in feats_flat[off:off + c]])
                t.leaf_coeff.append(np.concatenate(
                    [coefs_flat[off:off + c], [consts[lf]]]))
            off += c
    return t, missing_type


def load_model_string(text: str) -> HostModel:
    if "tree" not in text.splitlines()[0]:
        log.fatal("Model file doesn't specify the model format")
    head, *tree_parts = text.split("\nTree=")
    kv = _parse_kv_block(head)
    trees: List[Tree] = []
    missing_types: List[np.ndarray] = []
    for part in tree_parts:
        body = part.split("\nend of trees")[0]
        # drop the leading tree index line
        body = body.split("\n", 1)[1] if "\n" in body else body
        t, mt = _parse_tree_block(body)
        t.node_missing_type = mt
        trees.append(t)
        missing_types.append(mt)
    pandas_categorical = None
    marker = "\npandas_categorical:"
    if marker in text:
        import json as _json
        line = text.split(marker, 1)[1].split("\n", 1)[0].strip()
        if line:
            try:
                pandas_categorical = _json.loads(line)
            except ValueError:
                log.warning("Malformed pandas_categorical field ignored")
    return HostModel(
        trees=trees,
        num_class=int(kv.get("num_class", 1)),
        num_tree_per_iteration=int(kv.get("num_tree_per_iteration", 1)),
        objective_str=kv.get("objective", "regression"),
        feature_names=kv.get("feature_names", "").split(),
        feature_infos=kv.get("feature_infos", "").split(),
        max_feature_idx=int(kv.get("max_feature_idx", 0)),
        label_index=int(kv.get("label_index", 0)),
        average_output="average_output" in head,
        missing_types=missing_types,
        pandas_categorical=pandas_categorical,
    )
