"""Text dataset loaders: CSV / TSV / LibSVM (+ sidecar files).

Reference: ``DatasetLoader::LoadFromFile`` + the Parser hierarchy
(src/io/dataset_loader.cpp, src/io/parser.cpp, UNVERIFIED — empty mount,
see SURVEY.md banner): format auto-detection from the first lines,
``label_column``/``weight_column``/``group_column``/``ignore_column``
(by index or ``name:`` prefix), header handling, and ``.weight`` /
``.query`` sidecar files.

The dense fast path runs through the native C++ parser
(native/text_parser.cpp, ctypes) with a numpy fallback.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import log


@dataclasses.dataclass
class LoadedText:
    X: np.ndarray
    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None
    feature_names: Optional[List[str]] = None
    # raw per-row query ids (streamed chunks only — group boundaries
    # can span chunks, so the consumer derives counts from qids)
    qid: Optional[np.ndarray] = None


def _first_data_lines(path: str, k: int = 2) -> List[str]:
    out = []
    with open(path, "r") as f:
        for line in f:
            s = line.strip()
            if s and not s.startswith("#"):
                out.append(s)
                if len(out) >= k:
                    break
    return out


def _detect_delim(line: str) -> str:
    for d in ("\t", ",", " "):
        if d in line:
            return d
    return ","


def _is_number(tok: str) -> bool:
    tok = tok.strip()
    if tok in ("", "NA", "na", "nan", "NaN", "?"):
        return True
    try:
        float(tok)
        return True
    except ValueError:
        return False


def sniff_format(path: str) -> Tuple[str, str, bool]:
    """Returns (kind, delim, has_header): kind in {csv, libsvm}."""
    lines = _first_data_lines(path)
    if not lines:
        log.fatal(f"Data file {path} is empty")
    first = lines[0]
    probe = lines[-1]
    toks = probe.replace("\t", " ").split()
    if len(toks) >= 2 and all(":" in t for t in toks[1:3]):
        return "libsvm", " ", False
    delim = _detect_delim(first)
    has_header = not all(_is_number(t) for t in first.split(delim))
    return "csv", delim, has_header


def _parse_dense_native(path: str, delim: str, skip: int,
                        n_rows: int, n_cols: int) -> Optional[np.ndarray]:
    from ..native import text_parser
    lib = text_parser()
    if lib is None:
        return None
    import ctypes
    out = np.empty((n_rows, n_cols), dtype=np.float64)
    got = lib.parse_dense(
        path.encode(), delim.encode(), skip,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_rows, n_cols)
    if got < 0:
        return None
    return out[:got]


def _parse_dense_python(path: str, delim: str, skip: int) -> np.ndarray:
    rows = []
    miss = {"", "na", "nan", "?"}
    with open(path) as f:
        skipped = 0
        for line in f:
            s = line.strip()
            if not s or s.startswith("#"):
                continue
            if skipped < skip:
                skipped += 1
                continue
            rows.append([np.nan if t.strip().lower() in miss
                         else float(t) for t in s.split(delim)])
    return np.asarray(rows, dtype=np.float64)


def _parse_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    from ..native import text_parser
    lib = text_parser()
    if lib is not None:
        import ctypes
        n_rows = lib.count_lines(path.encode())
        max_nnz = max(os.path.getsize(path) // 4, 16)
        ri = np.empty(max_nnz, dtype=np.int32)
        ci = np.empty(max_nnz, dtype=np.int32)
        vv = np.empty(max_nnz, dtype=np.float64)
        lab = np.empty(n_rows, dtype=np.float64)
        nnz = lib.parse_libsvm(
            path.encode(), 0,
            ri.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            ci.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            vv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            lab.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            max_nnz, n_rows)
        if nnz >= 0:
            ri, ci, vv = ri[:nnz], ci[:nnz], vv[:nnz]
            n_cols = int(ci.max()) + 1 if nnz else 0
            X = np.zeros((n_rows, n_cols), dtype=np.float64)
            X[ri, ci] = vv
            return X, lab
    # python fallback
    labels, entries = [], []
    max_col = -1
    with open(path) as f:
        for line in f:
            s = line.strip()
            if not s or s.startswith("#"):
                continue
            toks = s.split()
            labels.append(float(toks[0]))
            row = []
            for t in toks[1:]:
                i, _, v = t.partition(":")
                c = int(i)
                max_col = max(max_col, c)
                row.append((c, float(v)))
            entries.append(row)
    X = np.zeros((len(labels), max_col + 1), dtype=np.float64)
    for r, row in enumerate(entries):
        for c, v in row:
            X[r, c] = v
    return X, np.asarray(labels)


def _resolve_column(spec, names: Optional[List[str]]) -> Optional[int]:
    """LightGBM column spec: int index, 'N', or 'name:colname'."""
    if spec is None or spec == "":
        return None
    if isinstance(spec, int):
        return spec
    s = str(spec)
    if s.startswith("name:"):
        want = s[5:]
        if names and want in names:
            return names.index(want)
        log.fatal(f"Could not find column {want} in data file header")
    return int(s)


def _resolve_columns(names, label_column, weight_column, group_column,
                     ignore_column):
    """Shared column-spec resolution for the one-round and streamed
    loaders: returns (label_idx, weight_idx, group_idx, drop_list)."""
    lbl_idx = _resolve_column(
        0 if label_column in ("auto", "", None) else label_column, names)
    w_idx = _resolve_column(weight_column, names)
    g_idx = _resolve_column(group_column, names)
    drop = [i for i in (lbl_idx, w_idx, g_idx) if i is not None]
    if ignore_column:
        if isinstance(ignore_column, str):
            s = ignore_column
            if s.startswith("name:"):
                # reference form name:c1,c2,c3 — prefix applies to the
                # whole comma list
                spec = ["name:" + c for c in s[5:].split(",") if c]
            else:
                spec = s.split(",")
        else:
            spec = ignore_column
        drop += [_resolve_column(c, names) for c in spec]
    return lbl_idx, w_idx, g_idx, drop


def load_text(path, label_column="auto", weight_column=None,
              group_column=None, ignore_column=None,
              has_header: Optional[bool] = None) -> LoadedText:
    """Load a text dataset the way the reference CLI does."""
    path = os.fspath(path)
    kind, delim, sniffed_header = sniff_format(path)
    if kind == "libsvm":
        X, label = _parse_libsvm(path)
        out = LoadedText(X=X, label=label)
    else:
        header = sniffed_header if has_header is None else has_header
        names = None
        if header:
            names = [t.strip() for t in
                     _first_data_lines(path, 1)[0].split(delim)]
        # size from the native counters when available, else python parse
        from ..native import text_parser
        lib = text_parser()
        X = None
        if lib is not None:
            n_rows = lib.count_lines(path.encode()) - (1 if header else 0)
            # field count from the already-read first line (avoids a
            # second full-file pass in the native counter)
            n_cols = _first_data_lines(path, 1)[0].count(delim) + 1
            if n_rows > 0 and n_cols > 0:
                X = _parse_dense_native(path, delim, 1 if header else 0,
                                        n_rows, n_cols)
        if X is None:
            X = _parse_dense_python(path, delim, 1 if header else 0)
        lbl_idx, w_idx, g_idx, drop = _resolve_columns(
            names, label_column, weight_column, group_column,
            ignore_column)
        keep = [i for i in range(X.shape[1]) if i not in drop]
        out = LoadedText(
            X=X[:, keep],
            label=X[:, lbl_idx] if lbl_idx is not None else None,
            weight=X[:, w_idx] if w_idx is not None else None,
            feature_names=([names[i] for i in keep] if names else None))
        if g_idx is not None:
            # group column holds per-row query ids; counts taken in ROW
            # APPEARANCE order (np.unique would sort by qid and misalign
            # boundaries for non-ascending id sequences)
            qid = X[:, g_idx].astype(np.int64)
            change = np.flatnonzero(np.diff(qid) != 0) + 1
            out.group = np.diff(np.concatenate([[0], change, [len(qid)]]))

    # sidecar files (metadata.cpp: <data>.weight / <data>.query)
    if out.weight is None and os.path.exists(path + ".weight"):
        out.weight = np.loadtxt(path + ".weight", dtype=np.float64).ravel()
    if out.group is None and os.path.exists(path + ".query"):
        out.group = np.loadtxt(path + ".query", dtype=np.int64).ravel()
    return out


def _split_chunk_columns(X: np.ndarray, names, lbl_idx, w_idx, g_idx,
                         drop) -> LoadedText:
    keep = [i for i in range(X.shape[1]) if i not in drop]
    # metadata columns are COPIES, not views: the streamed loader
    # accumulates label/weight chunks across the whole file, and a view
    # would pin every raw [chunk, F+meta] parse block in memory — the
    # exact full-matrix footprint streaming exists to avoid
    return LoadedText(
        X=X[:, keep],
        label=X[:, lbl_idx].copy() if lbl_idx is not None else None,
        weight=X[:, w_idx].copy() if w_idx is not None else None,
        qid=(X[:, g_idx].astype(np.int64) if g_idx is not None
             else None),
        feature_names=([names[i] for i in keep] if names else None))


def iter_text_chunks(path, chunk_rows: int = 500_000,
                     label_column="auto", weight_column=None,
                     group_column=None, ignore_column=None,
                     has_header: Optional[bool] = None):
    """Stream a CSV/TSV file in row chunks (two_round loading — the
    reference's pipelined reader, utils/pipeline_reader.h +
    dataset_loader.cpp two-round path, UNVERIFIED): yields LoadedText
    per chunk WITHOUT ever materializing the full raw matrix. LibSVM
    files are rejected (use the one-round loader)."""
    path = os.fspath(path)
    kind, delim, sniffed_header = sniff_format(path)
    if kind == "libsvm":
        log.fatal("two_round streaming supports CSV/TSV files; LibSVM "
                  "files load in one round (their sparse form is "
                  "already compact)")
    header = sniffed_header if has_header is None else has_header
    names = None
    if header:
        names = [t.strip() for t in
                 _first_data_lines(path, 1)[0].split(delim)]
    lbl_idx, w_idx, g_idx, drop = _resolve_columns(
        names, label_column, weight_column, group_column, ignore_column)

    import pandas as pd
    reader = pd.read_csv(
        path, sep=delim, header=0 if header else None,
        chunksize=int(chunk_rows), comment="#",
        na_values=["na", "nan", "NA", "NaN", "?"], engine="c")
    for chunk in reader:
        X = chunk.to_numpy(dtype=np.float64)
        yield _split_chunk_columns(X, names, lbl_idx, w_idx, g_idx, drop)
