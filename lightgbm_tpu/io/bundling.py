"""Exclusive Feature Bundling (EFB).

Reference: ``DatasetLoader`` FindGroups / FastFeatureBundling
(src/io/dataset_loader.cpp, UNVERIFIED — empty mount, see SURVEY.md
banner): sparse features that are (almost) never non-default on the same
row are merged into one physical column whose bins are the union of the
members' non-default bins at disjoint offsets — the histogram scan then
touches F_bundled columns instead of F.

TPU-first formulation: bundling is a static BIN-level relabeling decided
on the host at dataset construction. The learner scans the bundled
matrix (``[n, F_phys]``) and expands each leaf's physical histogram back
to logical features with a precomputed ``[F, B] -> (phys_col, phys_bin)``
gather (each bundled feature's DEFAULT-bin mass is recovered as the leaf
residual), so split semantics are EXACTLY the unbundled ones when
``max_conflict_rate=0``.

The "default" of a feature is the bin its zero value falls in (the
reference's most-frequent-bin treatment generalized: the default may sit
anywhere in the bin range, so physical offsets skip over it).
"""
from __future__ import annotations

import dataclasses
from typing import List

import numpy as np


@dataclasses.dataclass
class BundlePlan:
    """Static bundling layout shared by train and valid matrices."""

    bundles: List[List[int]]        # each: logical feature idx list
    phys_col: np.ndarray            # [F] physical column per feature
    start: np.ndarray               # [F] offset of the 1st non-def bin
    default_bin: np.ndarray         # [F] the feature's default bin
    bundled: np.ndarray             # [F] bool: True if in a multi-bundle
    n_phys: int
    phys_num_bin: np.ndarray        # [F_phys]

    @property
    def any_bundled(self) -> bool:
        return bool(self.bundled.any())


def find_bundles(binned: np.ndarray, num_bins: np.ndarray,
                 eligible: np.ndarray, default_bins: np.ndarray,
                 max_conflict_rate: float = 0.0,
                 sample_cnt: int = 50_000, max_bundle_bins: int = 256,
                 seed: int = 0) -> List[List[int]]:
    """Greedy conflict-bounded grouping (FindGroups): order features by
    non-default count, place each into the first bundle whose
    accumulated conflict count stays within ``max_conflict_rate``."""
    n, F = binned.shape
    rng = np.random.default_rng(seed)
    rows = (np.arange(n) if n <= sample_cnt
            else rng.choice(n, size=sample_cnt, replace=False))
    sub = binned[rows]
    nz = [np.flatnonzero(sub[:, f] != default_bins[f]) for f in range(F)]
    nnz = np.array([len(z) for z in nz])
    max_conflicts = int(max_conflict_rate * len(rows))

    order = np.argsort(-nnz, kind="stable")
    bundles: List[List[int]] = []
    bundle_mask: List[np.ndarray] = []      # rows already non-default
    bundle_conf: List[int] = []
    bundle_bins: List[int] = []
    for f in order:
        f = int(f)
        if not eligible[f]:
            continue
        if nnz[f] > 0.5 * len(rows):
            continue                         # dense: not worth bundling
        placed = False
        fmask = np.zeros(len(rows), dtype=bool)
        fmask[nz[f]] = True
        for bi in range(len(bundles)):
            extra_bins = int(num_bins[f]) - 1
            if bundle_bins[bi] + extra_bins > max_bundle_bins:
                continue
            conf = int(np.count_nonzero(bundle_mask[bi] & fmask))
            if bundle_conf[bi] + conf <= max_conflicts:
                bundles[bi].append(f)
                bundle_mask[bi] |= fmask
                bundle_conf[bi] += conf
                bundle_bins[bi] += extra_bins
                placed = True
                break
        if not placed:
            bundles.append([f])
            bundle_mask.append(fmask)
            bundle_conf.append(0)
            bundle_bins.append(1 + int(num_bins[f]) - 1)

    # full-data verification: the sample can miss conflicts, and
    # apply_bundles relabels EVERY row — enforce the conflict budget on
    # the full matrix, evicting the worst offender until it holds
    full_budget = int(max_conflict_rate * n)
    out = []
    for grp in (b for b in bundles if len(b) >= 2):
        grp = list(grp)
        while len(grp) >= 2:
            nd = np.stack([binned[:, f] != default_bins[f] for f in grp])
            cnt = nd.sum(axis=0)
            conflict_rows = cnt > 1
            if int(np.count_nonzero(conflict_rows)) <= full_budget:
                break
            share = (nd & conflict_rows[None, :]).sum(axis=1)
            grp.pop(int(np.argmax(share)))
        if len(grp) >= 2:
            out.append(grp)
    return out


def plan_bundles(num_bins: np.ndarray, default_bins: np.ndarray,
                 multi_bundles: List[List[int]]) -> BundlePlan:
    """Column/offset layout: multi-feature bundles first, then singleton
    identity columns for everything else. Within a bundle column, bin 0
    means "every member at its default"; member f's non-default bins
    occupy ``[start_f, start_f + num_bins_f - 2]``."""
    F = len(num_bins)
    phys_col = np.zeros(F, dtype=np.int32)
    start = np.zeros(F, dtype=np.int32)
    bundled = np.zeros(F, dtype=bool)
    phys_num_bin: List[int] = []
    col = 0
    for grp in multi_bundles:
        off = 1                              # bin 0 = all-defaults
        for f in grp:
            phys_col[f] = col
            start[f] = off
            bundled[f] = True
            off += int(num_bins[f]) - 1
        phys_num_bin.append(off)
        col += 1
    for f in range(F):
        if not bundled[f]:
            phys_col[f] = col
            start[f] = 0                     # identity (all bins)
            phys_num_bin.append(int(num_bins[f]))
            col += 1
    return BundlePlan(bundles=multi_bundles, phys_col=phys_col,
                      start=start,
                      default_bin=np.asarray(default_bins, np.int32),
                      bundled=bundled, n_phys=col,
                      phys_num_bin=np.asarray(phys_num_bin, np.int32))


def apply_bundles(binned: np.ndarray, plan: BundlePlan) -> np.ndarray:
    """Relabel a logical binned matrix [n, F] into the physical bundled
    matrix [n, F_phys]. A member's non-default bin b maps to
    ``start + b - (b > default)`` (the default bin is skipped in the
    enumeration). Conflicting rows (several members non-default,
    possible when max_conflict_rate > 0) keep the LAST member's value."""
    n, F = binned.shape
    dtype = (np.uint8 if int(plan.phys_num_bin.max(initial=1)) <= 256
             else np.uint16)
    out = np.zeros((n, plan.n_phys), dtype=dtype)
    for f in range(F):
        col = plan.phys_col[f]
        b = binned[:, f].astype(np.int64)
        if plan.bundled[f]:
            d = int(plan.default_bin[f])
            nd = b != d
            idx = b[nd] - (b[nd] > d)
            out[nd, col] = (plan.start[f] + idx).astype(dtype)
        else:
            out[:, col] = b.astype(dtype)
    return out


def build_expand_maps(plan: BundlePlan, num_bins: np.ndarray, B: int):
    """Precompute the physical->logical histogram gather:
    ``map_pf/map_pb [F, B]``, ``map_valid [F, B]`` and ``at_default
    [F, B]`` (the slot where each bundled feature's residual default-bin
    mass is injected)."""
    F = len(num_bins)
    map_pf = np.zeros((F, B), dtype=np.int32)
    map_pb = np.zeros((F, B), dtype=np.int32)
    map_valid = np.zeros((F, B), dtype=bool)
    at_default = np.zeros((F, B), dtype=bool)
    for f in range(F):
        nb = int(num_bins[f])
        map_pf[f, :] = plan.phys_col[f]
        if plan.bundled[f]:
            d = int(plan.default_bin[f])
            for b in range(nb):
                if b == d:
                    at_default[f, b] = True
                    continue
                map_pb[f, b] = plan.start[f] + b - (b > d)
                map_valid[f, b] = True
        else:
            for b in range(min(nb, B)):
                map_pb[f, b] = b
                map_valid[f, b] = True
    return map_pf, map_pb, map_valid, at_default
